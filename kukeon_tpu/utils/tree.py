"""Small pytree utilities shared across the compute path."""

import jax
import jax.numpy as jnp


def tree_param_count(tree) -> int:
    """Total number of scalar parameters in a pytree."""
    return sum(x.size for x in jax.tree.leaves(tree))


def tree_size_bytes(tree) -> int:
    """Total in-memory size of a pytree of arrays, in bytes."""
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))


def tree_cast(tree, dtype):
    """Cast every floating-point leaf of a pytree to ``dtype``."""
    def cast(x):
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x
    return jax.tree.map(cast, tree)
