from kukeon_tpu.utils.tree import tree_size_bytes, tree_param_count  # noqa: F401
