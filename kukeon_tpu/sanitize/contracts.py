"""Guarded-by contract loading: the shared file both analyzers consume.

The contract is KUKE005's inferred (plus ``# guarded-by:``-declared)
guarded-attribute sets, exported by ``python -m kukeon_tpu.analysis
--write-contracts`` into ``kukeon_tpu/analysis/guarded_by.json`` and
checked into the tree (a tier-1 drift guard regenerates and compares it).
kukelint recomputes the sets from source on every run — the file exists
for consumers that must not pay an AST pass at import time: kukesan's
``__setattr__`` hooks look classes up here by ``module.Class`` key.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any

_load_lock = threading.Lock()
_cache: dict[str, dict[str, tuple[str, ...]]] | None = None


def contracts_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "analysis", "guarded_by.json")


def load() -> dict[str, dict[str, tuple[str, ...]]]:
    """The parsed contract, cached for the process (``module.Class ->
    attr -> lock names``). Missing/unreadable file = empty contract: the
    sanitizer degrades to lock-order + blocking checks rather than
    failing imports."""
    global _cache
    with _load_lock:
        if _cache is not None:
            return _cache
        out: dict[str, dict[str, tuple[str, ...]]] = {}
        try:
            with open(contracts_path(), encoding="utf-8") as f:
                data: Any = json.load(f)
            for key, attrs in data.get("classes", {}).items():
                out[key] = {attr: tuple(locks)
                            for attr, locks in attrs.items()}
        except (OSError, ValueError):
            out = {}
        _cache = out
        return out


def for_class(cls: type) -> dict[str, tuple[str, ...]]:
    """This class's own contract entry (callers merge over the MRO)."""
    return load().get(f"{cls.__module__}.{cls.__qualname__}", {})


def _reset_for_tests() -> None:
    global _cache
    with _load_lock:
        _cache = None
