"""CLI: ``python -m kukeon_tpu.sanitize [package_root]`` — print the
merged runtime/static lock-graph report as JSON.

From a fresh process the runtime side is empty and the report is the
static KUKE006 graph plus empty diffs; the interesting reports come from
a live session — the tier-1 conftest writes one to the path in
``KUKEON_SANITIZE_REPORT`` at the end of a ``KUKEON_SANITIZE=1`` run.
"""

from __future__ import annotations

import json
import sys

from kukeon_tpu.sanitize.report import merge_report


def main(argv: list[str] | None = None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    package_root = args[0] if args else None
    print(json.dumps(merge_report(package_root), indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
