"""Merging the runtime-observed lock-order graph into KUKE006's static one.

Both analyzers name locks identically (``path/to/file.py:Class.attr`` —
the sanitize factory derives the prefix from the creating frame, the
static pass from the scanned file), so their edge sets diff directly:

- **runtime-only edges** are acquisitions the AST pass could not resolve
  (locks reached through callbacks, dynamically started threads,
  cross-module chains through untyped attributes) — exactly the blind
  spots kukelint's own docs list. Each carries the witness stacks.
- **static-only edges** are orderings the suite never exercised this run
  — a coverage signal, not a bug.

The tier-1 conftest writes this report to ``KUKEON_SANITIZE_REPORT``
(when set) at the end of a ``KUKEON_SANITIZE=1`` session;
``python -m kukeon_tpu.sanitize`` prints it for the current process.
"""

from __future__ import annotations

from typing import Any

from kukeon_tpu.sanitize import runtime as _rt


def merge_report(package_root: str | None = None) -> dict[str, Any]:
    """One JSON-able document diffing the runtime graph against the
    static KUKE006 graph of ``package_root`` (default: the installed
    kukeon_tpu package)."""
    import os

    from kukeon_tpu.analysis.core import load_sources
    from kukeon_tpu.analysis.locks import build_lock_graph

    if package_root is None:
        package_root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))
    static = build_lock_graph(load_sources(package_root), package_root)
    observed = _rt.observed_edges()
    static_keys = set(static)
    runtime_keys = set(observed)
    runtime_only = sorted(runtime_keys - static_keys)
    static_only = sorted(static_keys - runtime_keys)
    shared = sorted(static_keys & runtime_keys)
    return {
        "version": 1,
        "tool": "kukesan",
        "static_edges": len(static_keys),
        "runtime_edges": len(runtime_keys),
        "shared": [{"from": a, "to": b} for a, b in shared],
        "runtime_only": [
            {"from": a, "to": b,
             "held_at": observed[(a, b)][0],
             "acquired_at": observed[(a, b)][1]}
            for a, b in runtime_only
        ],
        "static_only": [
            {"from": a, "to": b,
             "file": static[(a, b)][0], "line": static[(a, b)][1]}
            for a, b in static_only
        ],
        "findings": [f.to_dict() for f in _rt.findings()],
    }
