"""kukesan core: recording lock proxies, held-sets, the runtime lock-order
graph, guarded-by ``__setattr__`` hooks, and blocking-call hazards.

Everything here is stdlib-only and import-light: obs/registry.py and the
analysis package import this module, so it must never pull in jax (or
anything heavy). All sanitizer state is process-global on purpose — the
lock-order graph accumulates across every engine/router/cell a test
session constructs, which is exactly what makes cross-module cycles
observable.

Internal synchronization uses a RAW ``threading.Lock`` (``_state_lock``):
the sanitizer must never trace itself.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
import time as _time
import urllib.request
from typing import Any, Callable

ENV = "KUKEON_SANITIZE"
SLEEP_THRESHOLD_ENV = "KUKEON_SANITIZE_SLEEP_S"
_DEFAULT_SLEEP_THRESHOLD_S = 0.01
_STACK_DEPTH = 16

RULE_IDS = {
    "lock-order-cycle": "KUKESAN001",
    "unguarded-write": "KUKESAN002",
    "blocking-under-lock": "KUKESAN003",
}


def enabled() -> bool:
    """True when KUKEON_SANITIZE asks for recording proxies. Checked at
    *creation* time of every primitive, so a process (or a single test
    via monkeypatch.setenv) opts in before constructing the objects it
    wants sanitized."""
    return os.environ.get(ENV, "").lower() in ("1", "true", "yes", "on")


class SanitizerError(RuntimeError):
    """A fail-hard sanitizer verdict (observed lock-order cycle)."""


@dataclasses.dataclass(frozen=True)
class SanFinding:
    """One recorded sanitizer finding, with stack provenance."""

    kind: str                         # key into RULE_IDS
    message: str
    stacks: tuple[tuple[str, str], ...]   # (label, formatted stack)

    @property
    def rule(self) -> str:
        return RULE_IDS.get(self.kind, "KUKESAN000")

    def render(self) -> str:
        parts = [f"{self.rule} [{self.kind}] {self.message}"]
        for label, stack in self.stacks:
            parts.append(f"--- {label} ---")
            parts.append(stack)
        return "\n".join(parts)

    def to_dict(self) -> dict[str, Any]:
        """The same shape ``python -m kukeon_tpu.analysis --format json``
        emits for static findings, so one consumer reads both reports."""
        return {
            "id": f"{self.rule}:{self.message}",
            "rule": self.rule,
            "kind": self.kind,
            "message": self.message,
            "stacks": {label: stack for label, stack in self.stacks},
        }


# --- process-global sanitizer state ------------------------------------------

_state_lock = threading.Lock()          # raw: guards everything below
_findings: list[SanFinding] = []
# (held-name, acquired-name) -> (held's acquire stack, acquirer stack)
_edges: dict[tuple[str, str], tuple[str, str]] = {}
_adj: dict[str, set[str]] = {}
_active = False                          # flips True on first proxy creation
_orig_sleep: Callable[[float], None] | None = None
_orig_urlopen: Callable[..., Any] | None = None

_tls = threading.local()


class _Held:
    """One sanitized lock the current thread holds (plus where)."""

    __slots__ = ("lock", "stack", "count")

    def __init__(self, lock: "_SanLockBase", stack: str) -> None:
        self.lock = lock
        self.stack = stack
        self.count = 1


def _held_list() -> list[_Held]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = []
        _tls.held = held
    return held


def _sleep_threshold() -> float:
    raw = os.environ.get(SLEEP_THRESHOLD_ENV, "")
    try:
        return float(raw) if raw else _DEFAULT_SLEEP_THRESHOLD_S
    except ValueError:
        return _DEFAULT_SLEEP_THRESHOLD_S


def _capture_stack(skip: int = 2) -> str:
    """Compact stack summary (most recent call last), skipping sanitizer
    frames. Deliberately avoids ``traceback`` + linecache I/O: this runs
    on every sanitized acquire."""
    frames: list[str] = []
    try:
        f = sys._getframe(skip)
    except ValueError:
        return "<no stack>"
    own = os.path.abspath(__file__)
    depth = 0
    while f is not None and depth < _STACK_DEPTH:
        code = f.f_code
        if os.path.abspath(code.co_filename) != own:
            frames.append(
                f"  {_shorten(code.co_filename)}:{f.f_lineno} "
                f"in {code.co_qualname if hasattr(code, 'co_qualname') else code.co_name}")
            depth += 1
        f = f.f_back
    frames.reverse()
    return "\n".join(frames) if frames else "<no stack>"


def _shorten(filename: str) -> str:
    """Repo-relative path when the file lives under the package tree (the
    same ids the static analyzer uses), basename otherwise."""
    norm = filename.replace(os.sep, "/")
    i = norm.rfind("kukeon_tpu/")
    if i >= 0:
        return norm[i:]
    j = norm.rfind("tests/")
    if j >= 0:
        return norm[j:]
    return os.path.basename(filename)


def _qualify(name: str, depth: int = 2) -> str:
    """``caller-file.py:Name`` — the same id scheme the static KUKE006
    graph uses (``kukeon_tpu/serving/engine.py:ServingEngine._lock``), so
    runtime and static edges merge by exact name."""
    try:
        f = sys._getframe(depth)
        return f"{_shorten(f.f_code.co_filename)}:{name}"
    except ValueError:
        return name


def _add_finding(finding: SanFinding) -> None:
    with _state_lock:
        _findings.append(finding)


def findings() -> list[SanFinding]:
    """Snapshot of the recorded findings (not cleared)."""
    with _state_lock:
        return list(_findings)


def drain_findings() -> list[SanFinding]:
    """Return AND clear the recorded findings — the per-test conftest gate
    uses this so each test answers only for its own violations."""
    with _state_lock:
        out = list(_findings)
        _findings.clear()
    return out


def observed_edges() -> dict[tuple[str, str], tuple[str, str]]:
    """The runtime lock-order graph observed so far:
    ``(held, acquired) -> (held's acquire stack, acquirer stack)``."""
    with _state_lock:
        return dict(_edges)


def _reset_for_tests() -> None:
    """Clear findings AND the lock-order graph (fixture tests seed
    deliberate cycles that must not leak into later tests' graphs)."""
    with _state_lock:
        _findings.clear()
        _edges.clear()
        _adj.clear()


# --- blocking-call hazards ---------------------------------------------------


def _hot_held() -> list[_Held]:
    return [h for h in _held_list() if h.lock.hot]


def _check_blocking(what: str, duration_s: float | None) -> None:
    """Record a KUKESAN003 hazard when a blocking call runs while the
    thread holds a hot lock. ``duration_s`` None means unbounded."""
    if duration_s is not None and duration_s < _sleep_threshold():
        return
    hot = _hot_held()
    if not hot:
        return
    names = ", ".join(h.lock.name for h in hot)
    stacks: list[tuple[str, str]] = [("blocking call", _capture_stack(3))]
    for h in hot:
        stacks.append((f"{h.lock.name} acquired at", h.stack))
    dur = "unbounded" if duration_s is None else f"{duration_s:g}s"
    _add_finding(SanFinding(
        "blocking-under-lock",
        f"{what} ({dur}) executed while holding hot lock(s) {names} — "
        f"every other thread contending for the lock stalls for the "
        f"whole call; move the blocking work outside the critical "
        f"section",
        tuple(stacks)))


def blocking(what: str, duration_s: float | None = None) -> None:
    """Explicit blocking-call seam for sites the patches cannot see (the
    engine's ``_fetch``/``_upload`` device transfers). No-op until the
    sanitizer is active, and free of any allocation when no hot lock is
    held."""
    if not _active:
        return
    _check_blocking(what, duration_s)


def _patched_sleep(seconds: float) -> None:
    assert _orig_sleep is not None
    try:
        dur: float | None = float(seconds)
    except (TypeError, ValueError):
        dur = None
    _check_blocking("time.sleep", dur)
    _orig_sleep(seconds)


def _patched_urlopen(*args: Any, **kwargs: Any) -> Any:
    assert _orig_urlopen is not None
    _check_blocking("urllib.request.urlopen", None)
    return _orig_urlopen(*args, **kwargs)


def _activate() -> None:
    """Arm the process-wide hooks once (first sanitized primitive): the
    ``time.sleep`` / ``urlopen`` wrappers only *inspect the thread-local
    held-set*, so they are inert for code that holds no sanitized lock."""
    global _active, _orig_sleep, _orig_urlopen
    with _state_lock:
        if _active:
            return
        _orig_sleep = _time.sleep
        _time.sleep = _patched_sleep  # type: ignore[assignment]
        _orig_urlopen = urllib.request.urlopen
        urllib.request.urlopen = _patched_urlopen  # type: ignore[assignment]
        _active = True


# --- lock-order graph --------------------------------------------------------


def _find_path(start: str, goal: str) -> list[str] | None:
    """A node path start..goal over ``_adj`` (caller holds _state_lock)."""
    stack: list[list[str]] = [[start]]
    seen = {start}
    while stack:
        path = stack.pop()
        node = path[-1]
        if node == goal:
            return path
        for nxt in _adj.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append(path + [nxt])
    return None


def _record_edge(held: _Held, lock: "_SanLockBase", stack: str) -> None:
    a, b = held.lock.name, lock.name
    with _state_lock:
        if (a, b) in _edges:
            return
        _edges[(a, b)] = (held.stack, stack)
        _adj.setdefault(a, set()).add(b)
        # Does the new edge close a cycle? Any path b -> … -> a does.
        path = _find_path(b, a)
        if path is None:
            return
        cycle = [a] + path            # a -> b -> … -> a (path closes it)
        stacks: list[tuple[str, str]] = []
        for i in range(len(cycle) - 1):
            sa, sb = _edges[(cycle[i], cycle[i + 1])]
            stacks.append((f"{cycle[i]} held at", sa))
            stacks.append((f"{cycle[i + 1]} acquired at", sb))
        chain = " -> ".join(cycle)
        finding = SanFinding(
            "lock-order-cycle",
            f"observed lock acquisition-order cycle (deadlock when the "
            f"acquisitions interleave): {chain}",
            tuple(stacks))
        _findings.append(finding)
    raise SanitizerError(finding.render())


def _note_acquired(lock: "_SanLockBase") -> None:
    """Track an acquire: edges from every currently-held lock, then join
    the held-set. A cycle verdict raises OUT of the caller's acquire —
    the caller releases the raw lock first, so the fail-hard path leaves
    no orphaned held primitive behind."""
    held = _held_list()
    stack = _capture_stack(3)
    for h in held:
        if h.lock.name != lock.name:
            _record_edge(h, lock, stack)
    held.append(_Held(lock, stack))


def _note_released(lock: "_SanLockBase") -> None:
    held = _held_list()
    for i in range(len(held) - 1, -1, -1):
        if held[i].lock is lock:
            del held[i]
            return


# --- recording proxies -------------------------------------------------------


class _SanLockBase:
    """Shared proxy surface over a raw primitive. The ``name`` is the
    static-analyzer-compatible lock id; ``hot`` marks locks that must
    never be held across blocking calls (KUKESAN003)."""

    def __init__(self, inner: Any, name: str, hot: bool) -> None:
        self._inner = inner
        self.name = name
        self.hot = hot

    def locked(self) -> bool:
        return bool(self._inner.locked())

    def _is_owned(self) -> bool:
        """Owner check (adopted by threading.Condition): exact, from the
        thread-local held-set — no acquire(0) probing."""
        return any(h.lock is self for h in _held_list())

    held_by_me = _is_owned

    def __repr__(self) -> str:
        return f"<kukesan {type(self).__name__} {self.name!r} hot={self.hot}>"


class _SanLock(_SanLockBase):
    """Recording proxy over ``threading.Lock``."""

    def __init__(self, name: str, hot: bool) -> None:
        super().__init__(threading.Lock(), name, hot)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = bool(self._inner.acquire(blocking, timeout))
        if got:
            try:
                _note_acquired(self)
            except SanitizerError:
                self._inner.release()
                raise
        return got

    def release(self) -> None:
        self._inner.release()
        _note_released(self)

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: object) -> None:
        self.release()


class _SanRLock(_SanLockBase):
    """Recording proxy over ``threading.RLock`` (held-set entry counted,
    edges recorded on the outermost acquire only)."""

    def __init__(self, name: str, hot: bool) -> None:
        super().__init__(threading.RLock(), name, hot)

    def _entry(self) -> _Held | None:
        for h in _held_list():
            if h.lock is self:
                return h
        return None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = bool(self._inner.acquire(blocking, timeout))
        if got:
            e = self._entry()
            if e is not None:
                e.count += 1
            else:
                try:
                    _note_acquired(self)
                except SanitizerError:
                    self._inner.release()
                    raise
        return got

    def release(self) -> None:
        self._inner.release()
        e = self._entry()
        if e is not None:
            e.count -= 1
            if e.count <= 0:
                _note_released(self)

    def locked(self) -> bool:
        return self._entry() is not None

    def _is_owned(self) -> bool:
        return self._entry() is not None

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: object) -> None:
        self.release()


class _SanEvent:
    """Recording proxy over ``threading.Event``: an untimed (or
    above-threshold) ``wait`` while holding a hot lock is a blocking
    hazard — the classic shape of the watchdog-waits-on-the-engine
    deadlock."""

    def __init__(self, name: str) -> None:
        self._inner = threading.Event()
        self.name = name

    def is_set(self) -> bool:
        return self._inner.is_set()

    def set(self) -> None:
        self._inner.set()

    def clear(self) -> None:
        self._inner.clear()

    def wait(self, timeout: float | None = None) -> bool:
        if not self._inner.is_set():
            _check_blocking(f"Event.wait({self.name})", timeout)
        return self._inner.wait(timeout)

    def __repr__(self) -> str:
        return f"<kukesan Event {self.name!r}>"


# --- factories ---------------------------------------------------------------


def lock(name: str, *, hot: bool = False) -> Any:
    """A ``threading.Lock`` — or, under KUKEON_SANITIZE=1, a recording
    proxy named ``caller-file.py:name`` so runtime edges merge with the
    static KUKE006 graph. ``hot=True`` additionally forbids blocking
    calls while held (KUKESAN003)."""
    if not enabled():
        return threading.Lock()
    _activate()
    return _SanLock(_qualify(name), hot)


def rlock(name: str, *, hot: bool = False) -> Any:
    """``threading.RLock``, same contract as :func:`lock`."""
    if not enabled():
        return threading.RLock()
    _activate()
    return _SanRLock(_qualify(name), hot)


def condition(lock_obj: Any = None, *, name: str = "condition") -> Any:
    """``threading.Condition`` over a (possibly sanitized) lock. Tracking
    lives entirely in the lock proxy — ``Condition`` adopts its
    ``acquire``/``release``/``_is_owned``, so ``wait()`` correctly drops
    and re-records the held entry."""
    if lock_obj is None and enabled():
        _activate()
        lock_obj = _SanLock(_qualify(name), False)
    return threading.Condition(lock_obj)


def event(name: str) -> Any:
    """``threading.Event`` — or a proxy flagging hot-lock-held waits."""
    if not enabled():
        return threading.Event()
    _activate()
    return _SanEvent(_qualify(name))


# --- guarded-by enforcement --------------------------------------------------

_guards_cache: dict[type, dict[str, tuple[str, ...]]] = {}


def _class_guards(cls: type) -> dict[str, tuple[str, ...]]:
    """attr -> candidate lock attr names, merged over the MRO (base-class
    contracts apply to subclass instances) and cached per class."""
    cached = _guards_cache.get(cls)
    if cached is not None:
        return cached
    from kukeon_tpu.sanitize import contracts

    merged: dict[str, tuple[str, ...]] = {}
    for c in reversed(cls.__mro__):
        explicit = c.__dict__.get("__san_contract__")
        if explicit:
            for attr, locks in explicit.items():
                merged[attr] = tuple(locks)
        from_file = contracts.for_class(c)
        for attr, locks in from_file.items():
            merged[attr] = tuple(locks)
    with _state_lock:
        _guards_cache[cls] = merged
    return merged


def _check_guarded(obj: Any, attr: str, lock_names: tuple[str, ...]) -> None:
    verifiable = False
    for ln in lock_names:
        lk = obj.__dict__.get(ln)
        if isinstance(lk, _SanLockBase):
            verifiable = True
            if lk._is_owned():
                return
    if not verifiable:
        # The guard lock does not exist yet (object mid-construction
        # without a wrapped __init__) or is a raw primitive we cannot
        # interrogate: no verdict either way.
        return
    want = ", ".join(f"self.{n}" for n in lock_names)
    _add_finding(SanFinding(
        "unguarded-write",
        f"{type(obj).__name__}.{attr} is guarded by {want} (KUKE005 "
        f"contract) but written without the lock held",
        (("write at", _capture_stack(3)),)))


def guard_class(cls: type | None = None, *,
                contract: dict[str, tuple[str, ...]] | None = None) -> Any:
    """Class decorator opting a class into runtime guarded-by checks.

    Unarmed: returns the class untouched (zero overhead). Armed: installs
    a ``__setattr__`` hook validating every attribute rebind against the
    class's contract — the KUKE005 export in ``analysis/guarded_by.json``
    by default, or the explicit ``contract={attr: (lock_attr, …)}``
    mapping (fixture tests, classes outside the scanned package). The
    class's own ``__init__`` (and everything it calls) is exempt via a
    dynamic-extent depth flag, mirroring the static rule's constructor
    exemption."""

    def deco(klass: type) -> type:
        if not enabled():
            return klass
        _activate()
        if contract:
            klass.__san_contract__ = {                 # type: ignore[attr-defined]
                attr: tuple(locks) for attr, locks in contract.items()}
        _guards_cache.pop(klass, None)

        init = klass.__dict__.get("__init__")
        if init is not None and not getattr(init, "_san_wrapped", False):
            def wrapped_init(self: Any, *a: Any, **kw: Any) -> None:
                d = self.__dict__
                d["_san_init_depth"] = d.get("_san_init_depth", 0) + 1
                try:
                    init(self, *a, **kw)
                finally:
                    d["_san_init_depth"] -= 1
            wrapped_init._san_wrapped = True           # type: ignore[attr-defined]
            wrapped_init.__name__ = "__init__"
            wrapped_init.__qualname__ = getattr(init, "__qualname__",
                                                "__init__")
            klass.__init__ = wrapped_init              # type: ignore[misc]

        # Install the checking __setattr__ unless an ancestor's hook is
        # already inherited (double-decorating a hierarchy must not stack
        # two checks per write).
        current = klass.__setattr__
        if not getattr(current, "_san_wrapped", False):
            orig_setattr = current

            def checking_setattr(self: Any, name: str, value: Any) -> None:
                guards = _class_guards(type(self))
                g = guards.get(name)
                if g is not None and not self.__dict__.get("_san_init_depth"):
                    _check_guarded(self, name, g)
                orig_setattr(self, name, value)

            checking_setattr._san_wrapped = True       # type: ignore[attr-defined]
            klass.__setattr__ = checking_setattr       # type: ignore[misc, assignment]
        return klass

    if cls is not None:
        return deco(cls)
    return deco
