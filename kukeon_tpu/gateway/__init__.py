"""Replica gateway: scale-out serving for one model behind one endpoint.

``ModelSpec.replicas: N`` makes the runner materialize N serving cells
(ports ``port+1 .. port+N``) plus one gateway process on ``port``. The
gateway proxies ``/v1/generate`` (ndjson streaming passthrough included),
``/v1/embed``, and the health surface, routing by least queue depth (fed
by cheap periodic ``/v1/stats`` polls) with prefix affinity: requests
carrying a ``prefixId`` consistently hash to the same replica so that
engine's prefix cache keeps hitting, falling back to least-loaded when the
affine replica is unready or shedding.

FlexNPU (arxiv 2606.04415) motivates the shape — co-located replicas
behind a placement-aware front-end absorb bursty LLM traffic — and the
profiled-segmentation line of work (arxiv 2503.01025) motivates routing on
measured per-replica load instead of round-robin.

Lifecycle: 429/503 from a replica triggers bounded retry on another
replica (never mid-stream — those surface in-band), draining replicas
leave rotation, and ``kuke rollout`` performs a drain → restart → ready
rolling restart one replica at a time with zero failed requests.
"""

from kukeon_tpu.gateway.router import ReplicaState, Router  # noqa: F401
from kukeon_tpu.gateway.rollout import (  # noqa: F401
    RolloutError,
    RolloutStep,
    drain_replica,
    rolling_restart,
)
