"""Routing policy for the replica gateway.

Two inputs, one decision:

- **Measured load.** A background thread polls every replica's
  ``GET /v1/stats`` (cheap JSON the cell already serves) and keeps
  per-replica readiness, drain state, and queue depth. Routing reads the
  cached snapshot — the hot path never blocks on a poll.
- **Prefix affinity.** Requests carrying a ``prefixId`` rendezvous-hash to
  one replica (highest ``sha256(prefix_id | replica)`` wins), so an agent
  session's growing context keeps hitting the SAME engine's prefix cache.
  Rendezvous hashing keeps the mapping stable when a replica drops out:
  only the keys that hashed to the lost replica move.

Default policy is least queue depth (gateway-side in-flight counts break
ties) over the ready set; the affine replica wins when it is ready and not
excluded by an earlier failed attempt this request.

Thread model: the poll loop writes each replica's snapshot fields
(``ready``/``draining``/``queue_depth``/``poll_ok``) as plain attributes
the pick path reads — worst case a pick routes on a snapshot one poll
stale, which the retry layer above absorbs. The only mutually-written
field is the in-flight count, guarded by ``_inflight_lock`` (created
through the kukesan factory and marked *hot*: blocking calls while
holding it are sanitizer findings — the count must stay a
nanosecond-scale critical section on the proxy hot path).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import urllib.request
from typing import Callable, FrozenSet, List, Optional, Set, Union

from kukeon_tpu import sanitize


@sanitize.guard_class
class ReplicaState:
    """One replica's routing view: identity + the last polled snapshot."""

    def __init__(self, name: str, url: str) -> None:
        self.name = name
        self.url = url.rstrip("/")
        self.ready = False
        self.draining = False
        self.queue_depth = 0
        self.poll_ok = False
        self.last_poll_at = 0.0
        # Disaggregation role census (mixed | prefill | decode), learned
        # from /v1/stats polls. Sticky across poll failures: a dead decode
        # replica must stay counted as the decode pool's member so the
        # gateway knows to FALL BACK rather than silently de-disaggregate.
        self.role = "mixed"
        # Gateway-side in-flight proxied requests: fresher than the polled
        # queue depth, used as the tiebreaker between equally-deep queues.
        self._inflight_lock = sanitize.lock(
            "ReplicaState._inflight_lock", hot=True)
        self.inflight = 0   # guarded-by: _inflight_lock

    def begin(self) -> None:
        with self._inflight_lock:
            self.inflight += 1

    def end(self) -> None:
        with self._inflight_lock:
            self.inflight -= 1

    def load(self) -> int:
        return self.queue_depth + self.inflight

    def snapshot(self) -> dict[str, object]:
        return {
            "name": self.name,
            "url": self.url,
            "role": self.role,
            "ready": self.ready,
            "draining": self.draining,
            "queueDepth": self.queue_depth,
            "inflight": self.inflight,
            "pollOk": self.poll_ok,
        }

    def prefill_capable(self) -> bool:
        """Can run a prefill (or a whole request): prefill and mixed roles.
        This is also the local-decode fallback pool — role is routing
        policy, not engine capability, so a prefill cell CAN decode when
        the decode pool is gone."""
        return self.role in ("prefill", "mixed")

    def decode_capable(self) -> bool:
        return self.role in ("decode", "mixed")


POLICY_AFFINITY = "affinity"
POLICY_AFFINITY_FALLBACK = "affinity_fallback"
POLICY_LEAST_LOADED = "least_loaded"
# Two-stage (disaggregated) routing policies: prefill hop by queue depth,
# decode hop by the same rendezvous affinity the mixed path uses.
POLICY_PREFILL_QUEUE = "prefill_queue_depth"


@sanitize.guard_class
class Router:
    """Replica table + poll loop + pick().

    Thread-safe by construction: poll writes plain attributes the pick path
    reads (worst case a pick routes on a snapshot one poll stale, which the
    retry layer above absorbs).
    """

    def __init__(self, replicas: list[tuple[str, str]], *,
                 poll_interval_s: float = 0.5,
                 poll_timeout_s: float = 1.0) -> None:
        self.replicas = [ReplicaState(n, u) for n, u in replicas]
        self.by_name = {r.name: r for r in self.replicas}
        self.poll_interval_s = poll_interval_s
        self.poll_timeout_s = poll_timeout_s
        self._halt = sanitize.event("Router._halt")
        self._thread: Optional[threading.Thread] = None
        # Run after every completed poll pass. The gateway's spillover
        # queue registers its wakeup here: a parked request retries the
        # moment a poll shows capacity returned instead of sleeping out
        # its own timer.
        self._poll_listeners: List[Callable[[], None]] = []

    def add_poll_listener(self, fn: Callable[[], None]) -> None:
        """Register a callback invoked after each poll pass (listener
        exceptions are swallowed — routing must never die to a waiter)."""
        self._poll_listeners.append(fn)

    # --- polling -----------------------------------------------------------

    def poll_once(self) -> None:
        for rep in self.replicas:
            try:
                with urllib.request.urlopen(rep.url + "/v1/stats",
                                            timeout=self.poll_timeout_s) as r:
                    stats = json.loads(r.read())
                rep.draining = bool(stats.get("draining"))
                rep.queue_depth = int(stats.get("queueDepth") or 0)
                rep.ready = bool(stats.get("ready", True)) and not rep.draining
                role = stats.get("role")
                if role in ("mixed", "prefill", "decode"):
                    rep.role = str(role)
                rep.poll_ok = True
            except Exception:  # noqa: BLE001 — an unreachable replica is routing data
                rep.poll_ok = False
                rep.ready = False
            rep.last_poll_at = time.monotonic()
        for fn in list(self._poll_listeners):
            try:
                fn()
            except Exception:  # noqa: BLE001 — a waiter must not kill polling
                pass

    def start(self) -> None:
        if self._thread is not None:
            return
        self._halt.clear()
        self._thread = threading.Thread(target=self._poll_loop, daemon=True,
                                        name="gateway-poll")
        self._thread.start()

    def stop(self) -> None:
        self._halt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _poll_loop(self) -> None:
        # First poll immediately so the gateway routes as soon as it binds.
        self.poll_once()
        while not self._halt.wait(self.poll_interval_s):
            self.poll_once()

    # --- instantaneous demotion -------------------------------------------

    def mark_unready(self, rep: ReplicaState) -> None:
        """Demote NOW on a 503 / connection failure observed while proxying
        (the poll would take up to an interval to notice); the next
        successful poll promotes it back."""
        rep.ready = False

    # --- selection ---------------------------------------------------------

    def affine(self, prefix_id: str,
               pool: Optional[str] = None) -> Optional[ReplicaState]:
        """Rendezvous hash over the FULL pool membership (not just the
        ready members): the mapping must not churn when a replica blips
        unready, or every blip would scatter warm prefixes across the
        fleet. ``pool`` narrows to a role pool (two-stage decode routing
        hashes over decode-capable replicas only); None on an empty pool."""
        members = self._pool_members(pool)
        if not members:
            return None
        return max(members, key=lambda r: hashlib.sha256(
            f"{prefix_id}|{r.name}".encode()).digest())

    def pick(self, prefix_id: Optional[str] = None,
             exclude: Union[FrozenSet[str], Set[str]] = frozenset(),
             pool: Optional[str] = None
             ) -> tuple[Optional[ReplicaState], Optional[str]]:
        """(replica, policy) — or (None, None) when nothing is routable.

        ``pool`` restricts the candidate set by role capability:
        ``"prefill"``/``"decode"`` filter to capable replicas (the
        gateway's local-decode fallback routes over the prefill-capable
        pool); None keeps the full set — the mixed-manifest default path,
        byte-identical to before roles existed."""
        members = self._pool_members(pool)
        policy = POLICY_LEAST_LOADED
        if prefix_id is not None:
            a = self.affine(prefix_id, pool=pool)
            if a is not None and a.ready and a.name not in exclude:
                return a, POLICY_AFFINITY
            policy = POLICY_AFFINITY_FALLBACK
        ready = [r for r in members
                 if r.ready and r.name not in exclude]
        if not ready:
            return None, None
        return min(ready, key=lambda r: (r.load(), r.name)), policy

    # --- two-stage (disaggregated) selection -------------------------------

    def _pool_members(self, pool: Optional[str]) -> list[ReplicaState]:
        if pool == "prefill":
            return [r for r in self.replicas if r.prefill_capable()]
        if pool == "decode":
            return [r for r in self.replicas if r.decode_capable()]
        return list(self.replicas)

    def disaggregated(self) -> bool:
        """True when the replica set declares dedicated roles — the
        gateway then drives /v1/generate as the two-stage
        prefill-export → decode-import handoff. An all-``mixed`` census
        (the default) keeps the single-hop path exactly as today."""
        return any(r.role != "mixed" for r in self.replicas)

    def pick_prefill(self,
                     exclude: Union[FrozenSet[str], Set[str]] = frozenset()
                     ) -> tuple[Optional[ReplicaState], Optional[str]]:
        """Stage-1 pick: least queue depth over the ready prefill pool.
        Prefill is compute-bound and stateless across requests — no
        affinity, just the shallowest queue."""
        ready = [r for r in self._pool_members("prefill")
                 if r.ready and r.name not in exclude]
        if not ready:
            return None, None
        return (min(ready, key=lambda r: (r.load(), r.name)),
                POLICY_PREFILL_QUEUE)

    def pick_decode(self, prefix_id: Optional[str] = None,
                    exclude: Union[FrozenSet[str], Set[str]] = frozenset()
                    ) -> tuple[Optional[ReplicaState], Optional[str]]:
        """Stage-2 pick: prefix/session affinity over the decode pool (the
        same rendezvous hash as the mixed path, so a session's imports keep
        landing on the engine holding its shared-prefix pages), least
        loaded otherwise."""
        return self.pick(prefix_id, exclude=exclude, pool="decode")

    def ready_count(self) -> int:
        return sum(1 for r in self.replicas if r.ready)
