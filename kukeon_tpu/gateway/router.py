"""Routing policy for the replica gateway.

Two inputs, one decision:

- **Measured load.** A background thread polls every replica's
  ``GET /v1/stats`` (cheap JSON the cell already serves) and keeps
  per-replica readiness, drain state, and queue depth. Routing reads the
  cached snapshot — the hot path never blocks on a poll.
- **Prefix affinity.** Requests carrying a ``prefixId`` rendezvous-hash to
  one replica (highest ``sha256(prefix_id | replica)`` wins), so an agent
  session's growing context keeps hitting the SAME engine's prefix cache.
  Rendezvous hashing keeps the mapping stable when a replica drops out:
  only the keys that hashed to the lost replica move.

Default policy is least queue depth (gateway-side in-flight counts break
ties) over the ready set; the affine replica wins when it is ready and not
excluded by an earlier failed attempt this request.

Thread model: the poll loop writes each replica's snapshot fields
(``ready``/``draining``/``queue_depth``/``poll_ok``) as plain attributes
the pick path reads — worst case a pick routes on a snapshot one poll
stale, which the retry layer above absorbs. The only mutually-written
field is the in-flight count, guarded by ``_inflight_lock`` (created
through the kukesan factory and marked *hot*: blocking calls while
holding it are sanitizer findings — the count must stay a
nanosecond-scale critical section on the proxy hot path).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import urllib.request
from typing import FrozenSet, Optional, Set, Union

from kukeon_tpu import sanitize


@sanitize.guard_class
class ReplicaState:
    """One replica's routing view: identity + the last polled snapshot."""

    def __init__(self, name: str, url: str) -> None:
        self.name = name
        self.url = url.rstrip("/")
        self.ready = False
        self.draining = False
        self.queue_depth = 0
        self.poll_ok = False
        self.last_poll_at = 0.0
        # Gateway-side in-flight proxied requests: fresher than the polled
        # queue depth, used as the tiebreaker between equally-deep queues.
        self._inflight_lock = sanitize.lock(
            "ReplicaState._inflight_lock", hot=True)
        self.inflight = 0   # guarded-by: _inflight_lock

    def begin(self) -> None:
        with self._inflight_lock:
            self.inflight += 1

    def end(self) -> None:
        with self._inflight_lock:
            self.inflight -= 1

    def load(self) -> int:
        return self.queue_depth + self.inflight

    def snapshot(self) -> dict[str, object]:
        return {
            "name": self.name,
            "url": self.url,
            "ready": self.ready,
            "draining": self.draining,
            "queueDepth": self.queue_depth,
            "inflight": self.inflight,
            "pollOk": self.poll_ok,
        }


POLICY_AFFINITY = "affinity"
POLICY_AFFINITY_FALLBACK = "affinity_fallback"
POLICY_LEAST_LOADED = "least_loaded"


@sanitize.guard_class
class Router:
    """Replica table + poll loop + pick().

    Thread-safe by construction: poll writes plain attributes the pick path
    reads (worst case a pick routes on a snapshot one poll stale, which the
    retry layer above absorbs).
    """

    def __init__(self, replicas: list[tuple[str, str]], *,
                 poll_interval_s: float = 0.5,
                 poll_timeout_s: float = 1.0) -> None:
        self.replicas = [ReplicaState(n, u) for n, u in replicas]
        self.by_name = {r.name: r for r in self.replicas}
        self.poll_interval_s = poll_interval_s
        self.poll_timeout_s = poll_timeout_s
        self._halt = sanitize.event("Router._halt")
        self._thread: Optional[threading.Thread] = None

    # --- polling -----------------------------------------------------------

    def poll_once(self) -> None:
        for rep in self.replicas:
            try:
                with urllib.request.urlopen(rep.url + "/v1/stats",
                                            timeout=self.poll_timeout_s) as r:
                    stats = json.loads(r.read())
                rep.draining = bool(stats.get("draining"))
                rep.queue_depth = int(stats.get("queueDepth") or 0)
                rep.ready = bool(stats.get("ready", True)) and not rep.draining
                rep.poll_ok = True
            except Exception:  # noqa: BLE001 — an unreachable replica is routing data
                rep.poll_ok = False
                rep.ready = False
            rep.last_poll_at = time.monotonic()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._halt.clear()
        self._thread = threading.Thread(target=self._poll_loop, daemon=True,
                                        name="gateway-poll")
        self._thread.start()

    def stop(self) -> None:
        self._halt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _poll_loop(self) -> None:
        # First poll immediately so the gateway routes as soon as it binds.
        self.poll_once()
        while not self._halt.wait(self.poll_interval_s):
            self.poll_once()

    # --- instantaneous demotion -------------------------------------------

    def mark_unready(self, rep: ReplicaState) -> None:
        """Demote NOW on a 503 / connection failure observed while proxying
        (the poll would take up to an interval to notice); the next
        successful poll promotes it back."""
        rep.ready = False

    # --- selection ---------------------------------------------------------

    def affine(self, prefix_id: str) -> ReplicaState:
        """Rendezvous hash over the FULL replica set (not just the ready
        ones): the mapping must not churn when a replica blips unready, or
        every blip would scatter warm prefixes across the fleet."""
        return max(self.replicas, key=lambda r: hashlib.sha256(
            f"{prefix_id}|{r.name}".encode()).digest())

    def pick(self, prefix_id: Optional[str] = None,
             exclude: Union[FrozenSet[str], Set[str]] = frozenset()
             ) -> tuple[Optional[ReplicaState], Optional[str]]:
        """(replica, policy) — or (None, None) when nothing is routable."""
        policy = POLICY_LEAST_LOADED
        if prefix_id is not None:
            a = self.affine(prefix_id)
            if a.ready and a.name not in exclude:
                return a, POLICY_AFFINITY
            policy = POLICY_AFFINITY_FALLBACK
        ready = [r for r in self.replicas
                 if r.ready and r.name not in exclude]
        if not ready:
            return None, None
        return min(ready, key=lambda r: (r.load(), r.name)), policy

    def ready_count(self) -> int:
        return sum(1 for r in self.replicas if r.ready)
