"""Rolling restart: drain -> wait drained -> restart -> wait ready, one
replica at a time.

The gateway makes the invariant cheap: a draining replica leaves rotation
(its /v1/stats reports draining, and any straggler request it refuses with
503 is retried on a sibling), so restarting replicas one by one — never
proceeding until the previous one is back at /readyz 200 — keeps the
replica set serving with zero failed requests throughout.

The orchestration is transport-only here (HTTP drain/ready probes + a
caller-supplied restart callable per replica) so the daemon RPC, the CLI,
and the fake-backend tests all drive the exact same state machine; only
the restart callable differs (real container restart vs fake backend).
"""

from __future__ import annotations

import dataclasses
import json
import time
import urllib.error
import urllib.request
from typing import Callable


class RolloutError(RuntimeError):
    """A replica failed to drain-exit or come back ready in time; the
    rollout stops HERE (continuing would drain the next replica while this
    one is down — exactly the capacity hole a rolling restart exists to
    avoid). ``results`` carries the per-step outcome records up to and
    including the failed step, so an aborted rollout names exactly which
    replicas were done and which one stalled — the operator can resume by
    hand instead of re-rolling finished replicas blind."""

    def __init__(self, message: str, results: list[dict] | None = None):
        super().__init__(message)
        self.results: list[dict] = results or []


@dataclasses.dataclass
class RolloutStep:
    name: str                    # replica container name (for reporting)
    url: str                     # replica base URL
    restart: Callable[[], None]  # bring the drained replica back up


@dataclasses.dataclass
class StandbyStep:
    """A parked replica pre-warmed to /readyz BEFORE the first victim
    drains, so the ready census never dips below N while a restarted
    replica boots (today's window: one full cold start per step). The
    standby rides outside the scaler's active target — ``start`` boots the
    parked container without touching ``target_replicas``; ``stop`` parks
    it again once every active replica is back."""

    name: str                    # parked replica container name
    url: str                     # parked replica base URL
    start: Callable[[], None]    # boot the parked container (idempotent)
    stop: Callable[[], None]     # park it again (idempotent)


def _post(url: str, timeout_s: float) -> None:
    req = urllib.request.Request(url, data=b"{}", method="POST",
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout_s):
        pass


def _get_json(url: str, timeout_s: float) -> dict | None:
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            return json.loads(r.read())
    except Exception:  # noqa: BLE001 — unreachable is a state, not an error
        return None


def wait_drained(url: str, timeout_s: float, *, poll_s: float = 0.1,
                 http_timeout_s: float = 2.0) -> bool:
    """True once the replica finished draining. A real serving cell shuts
    its HTTP server down when the drain completes (then exits 0), so
    *unreachable* is the authoritative drained signal; a cell still
    answering reports drained when it stopped admitting and went idle."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        stats = _get_json(url + "/v1/stats", http_timeout_s)
        if stats is None:
            return True
        if stats.get("draining") and not stats.get("inflight") \
                and not stats.get("queueDepth"):
            return True
        time.sleep(poll_s)
    return False


def wait_ready(url: str, timeout_s: float, *, poll_s: float = 0.1,
               http_timeout_s: float = 2.0) -> float | None:
    """Seconds until /readyz answered 200, or None on timeout."""
    t0 = time.monotonic()
    deadline = t0 + timeout_s
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url + "/readyz",
                                        timeout=http_timeout_s) as r:
                if r.status == 200:
                    return time.monotonic() - t0
        except Exception:  # noqa: BLE001 — booting; keep polling
            pass
        time.sleep(poll_s)
    return None


def rolling_restart(steps: list[RolloutStep], *,
                    drain_timeout_s: float = 60.0,
                    ready_timeout_s: float = 300.0,
                    poll_s: float = 0.1,
                    http_timeout_s: float = 2.0,
                    on_event: Callable[[str, str], None] | None = None,
                    standby: StandbyStep | None = None
                    ) -> list[dict]:
    """Run the drain → wait → restart → wait-ready cycle over every step in
    order. Returns one record per replica; raises RolloutError the moment a
    replica cannot be brought back ready — with the per-step records so far
    (done replicas plus the failed one, its ``error`` naming the stall)
    attached as ``.results``, so an aborted rollout is resumable by hand.

    With ``standby``, a parked replica is booted to /readyz FIRST — before
    any victim drains — so the serving census holds at N through every
    step's restart window; it is parked again on the way out (abort
    included). Every per-step record carries a ``standby`` section naming
    the pre-warm replica and whether/when it went ready, so an aborted
    rollout reports whether the standby ever covered the hole."""
    ev = on_event or (lambda _replica, _what: None)
    results: list[dict] = []
    standby_rec: dict | None = None
    if standby is not None:
        ev(standby.name, "standby")
        try:
            standby.start()
        except Exception as e:  # noqa: BLE001 — the summary must name the step
            raise RolloutError(
                f"standby {standby.name} failed to start "
                f"({type(e).__name__}: {e}); rollout not begun "
                "(no replica was drained)",
                [{"replica": standby.name, "standby": True,
                  "error": f"start failed: {type(e).__name__}: {e}"}]) from e
        ready_s = wait_ready(standby.url, ready_timeout_s, poll_s=poll_s,
                             http_timeout_s=http_timeout_s)
        if ready_s is None:
            try:
                standby.stop()
            except Exception:  # noqa: BLE001 — parking best-effort on abort
                pass
            raise RolloutError(
                f"standby {standby.name} did not become ready within "
                f"{ready_timeout_s:.0f}s; rollout not begun "
                "(no replica was drained)",
                [{"replica": standby.name, "standby": True,
                  "error": f"not ready within {ready_timeout_s:.0f}s"}])
        ev(standby.name, "ready")
        standby_rec = {"replica": standby.name, "readyS": round(ready_s, 3)}
    try:
        return _rolling_restart_steps(
            steps, results, ev, standby_rec,
            drain_timeout_s=drain_timeout_s, ready_timeout_s=ready_timeout_s,
            poll_s=poll_s, http_timeout_s=http_timeout_s)
    finally:
        if standby is not None:
            try:
                standby.stop()
            except Exception:  # noqa: BLE001 — parking best-effort
                pass


def _rolling_restart_steps(steps: list[RolloutStep], results: list[dict],
                           ev: Callable[[str, str], None],
                           standby_rec: dict | None, *,
                           drain_timeout_s: float, ready_timeout_s: float,
                           poll_s: float, http_timeout_s: float
                           ) -> list[dict]:
    for step in steps:
        ev(step.name, "drain")
        try:
            _post(step.url + "/drain", http_timeout_s)
        except (urllib.error.URLError, OSError):
            # Already down (crashed replica): the restart still runs — a
            # rollout doubles as recovery for a dead replica.
            pass
        drained = wait_drained(step.url, drain_timeout_s, poll_s=poll_s,
                               http_timeout_s=http_timeout_s)
        ev(step.name, "restart")
        try:
            step.restart()
        except Exception as e:  # noqa: BLE001 — the summary must name the step
            results.append(_step_record(
                standby_rec, replica=step.name, drained=drained,
                error=f"restart failed: {type(e).__name__}: {e}"))
            raise RolloutError(
                f"replica {step.name} restart failed "
                f"({type(e).__name__}: {e}); rollout stopped "
                f"({len(results) - 1} of {len(steps)} replicas done)",
                results) from e
        ready_s = wait_ready(step.url, ready_timeout_s, poll_s=poll_s,
                             http_timeout_s=http_timeout_s)
        if ready_s is None:
            results.append(_step_record(
                standby_rec, replica=step.name, drained=drained,
                error=f"not ready within {ready_timeout_s:.0f}s "
                      "after restart"))
            raise RolloutError(
                f"replica {step.name} did not become ready within "
                f"{ready_timeout_s:.0f}s after restart; rollout stopped "
                f"({len(results) - 1} of {len(steps)} replicas done)",
                results)
        ev(step.name, "ready")
        results.append(_step_record(
            standby_rec, replica=step.name, drained=drained,
            readyS=round(ready_s, 3)))
    return results


def _step_record(standby_rec: dict | None, **fields) -> dict:
    """One per-replica outcome record, carrying the standby pre-warm
    section when the rollout ran with one — an aborted rollout's summary
    then names whether the standby ever went ready."""
    rec = dict(fields)
    if standby_rec is not None:
        rec["standby"] = dict(standby_rec)
    return rec


def drain_replica(url: str, *, drain_timeout_s: float = 30.0,
                  poll_s: float = 0.1, http_timeout_s: float = 2.0) -> bool:
    """The scale-down primitive: ask one replica to drain and wait for it
    to finish (a drained serving cell exits its HTTP server, so
    *unreachable* is the authoritative drained signal — a replica that
    died mid-drain still counts as drained, capacity-wise it is already
    gone). True once drained; False when the replica is still serving past
    the timeout — the caller must NOT remove it (that would lose its
    in-flight requests) and should retry later."""
    try:
        _post(url + "/drain", http_timeout_s)
    except (urllib.error.URLError, OSError):
        # Already unreachable: dead-or-drained, either way removable.
        pass
    return wait_drained(url, drain_timeout_s, poll_s=poll_s,
                        http_timeout_s=http_timeout_s)
