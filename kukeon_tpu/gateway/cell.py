"""Gateway cell: the HTTP front-end over N serving replicas.

Entrypoint the runner materializes for a replicated ``ModelSpec``
(``python -m kukeon_tpu.gateway.cell --port P --replica URL ...``). One
process, no chips, stateless except for routing state — a crashed gateway
restarts in milliseconds under the runner's restart policy while the
replicas keep their engines warm.

Routes:

  GET  /healthz      -> liveness
  GET  /readyz       -> 200 while >=1 replica is ready (503 otherwise)
  GET  /v1/stats     -> gateway counters + per-replica routing snapshot
  GET  /metrics      -> Prometheus exposition (kukeon_gateway_* families)
  GET  /v1/trace     -> gateway-side proxy spans (replica attempts, retry
                        hops, shed outcomes); ?trace_id= / ?request_id=
                        filters, same surface as the serving cells
  POST /v1/generate  -> proxied to a replica; ``"stream": true`` bodies are
                        passed through byte-for-byte as ndjson
  POST /v1/embed     -> proxied (no affinity; embeddings are stateless)

Retry contract: a replica answering 429/503, or refusing the connection,
triggers a bounded retry on another replica (each replica tried at most
once per request). NEVER for mid-stream failures — by then bytes are on
the client's wire, so the failure surfaces as the in-band terminal
``{"error": ...}`` ndjson line the serving cell already speaks.

Spillover: when EVERY replica shed (or nothing was routable), the request
parks in a bounded deadline-aware queue and retries as replicas free —
a brief all-shed storm becomes latency, not client-visible 429s. Past the
request's deadline the gateway answers the in-band timeout terminal; a
full spill queue (or the armed ``gateway.spill`` fault point) degrades to
the old contract — the last replica's 429/503 passes through (with its
Retry-After), and nothing-reachable sheds 503.
"""

from __future__ import annotations

import argparse
import http.client
import itertools
import json
import math
import sys
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from kukeon_tpu import faults, sanitize
from kukeon_tpu.obs import Registry, Tracer, expo
from kukeon_tpu.obs import trace as obs_trace
from kukeon_tpu.gateway.router import Router

# Retry-After the gateway itself sheds with (no replica routable). Short:
# replicas blip for poll-interval-sized windows, not minutes.
GATEWAY_RETRY_AFTER_S = 2.0
STREAM_CHUNK = 65536

# Spillover defaults: how many all-shed requests may park at the gateway
# (past it the original 429/503 passes through — the queue is a shock
# absorber, not an unbounded backlog) and the longest a request without
# its own deadlineS waits before the in-band timeout terminal.
SPILL_CAPACITY = 64
SPILL_MAX_WAIT_S = 10.0
# Parked requests retry on every router-poll wakeup; this timed wait is
# the backstop cadence when no poll lands (and the loop's deadline check).
SPILL_WAIT_TICK_S = 0.05


class GatewayCell:
    """Routing + proxy brain behind the HTTP handler (handler-free so tests
    and bench.py can drive it in-process)."""

    def __init__(self, model: str, replica_urls: list[str], *,
                 registry: Registry | None = None,
                 poll_interval_s: float = 0.5,
                 poll_timeout_s: float = 1.0,
                 request_timeout_s: float = 120.0,
                 trace_capacity: int = 512,
                 spill_capacity: int = SPILL_CAPACITY,
                 spill_max_wait_s: float = SPILL_MAX_WAIT_S):
        self.model_name = model
        self.request_timeout_s = request_timeout_s
        self.router = Router(
            [(f"r{i}", u) for i, u in enumerate(replica_urls)],
            poll_interval_s=poll_interval_s, poll_timeout_s=poll_timeout_s)
        self.started_at = time.time()
        # Spillover: an all-shed request parks here (bounded, deadline-
        # aware) instead of handing the client the 429 — see spill_or_shed.
        self.spill_capacity = spill_capacity
        self.spill_max_wait_s = spill_max_wait_s
        self._spill_lock = sanitize.lock("GatewayCell._spill_lock")
        self._spill_cond = sanitize.condition(
            self._spill_lock, name="GatewayCell._spill_cond")
        self._spill_depth = 0   # guarded-by: _spill_lock
        self.router.add_poll_listener(self._spill_wake)
        # Distributed tracing: the gateway is where a request's trace is
        # born (or joined, when the client already carries a traceparent).
        # Its proxy span records every replica attempt + retry hop and
        # lands in this ring behind GET /v1/trace — the gateway-side half
        # of the federated timeline `kuke trace` reconstructs. request_id
        # here is a gateway-local sequence (the engine-side id is minted
        # by whichever replica wins the request).
        self.tracer = Tracer(capacity=trace_capacity)
        self._span_seq = itertools.count()

        reg = registry if registry is not None else Registry()
        self.registry = reg
        reg.gauge("kukeon_gateway_info",
                  "Static gateway identity (value always 1).",
                  labels=("model",)).set(1, model=model)
        reg.gauge("kukeon_gateway_uptime_seconds",
                  "Seconds since gateway construction.").set_function(
            lambda: time.time() - self.started_at)
        reg.gauge("kukeon_gateway_replicas",
                  "Replicas configured behind this gateway.").set(
            len(replica_urls))
        reg.gauge("kukeon_gateway_ready",
                  "1 while at least one replica is ready.").set_function(
            lambda: 1.0 if self.router.ready_count() else 0.0)
        self._m_requests = reg.counter(
            "kukeon_gateway_requests_total",
            "Proxied requests by replica and outcome.",
            labels=("replica", "outcome"))
        self._m_retries = reg.counter(
            "kukeon_gateway_retries_total",
            "Retry-on-another-replica events by reason.",
            labels=("reason",))
        self._m_shed = reg.counter(
            "kukeon_gateway_shed_total",
            "Requests shed at the gateway (no routable replica).")
        self._m_routing = reg.counter(
            "kukeon_gateway_routing_total",
            "Routing decisions by policy.", labels=("policy",))
        # Disaggregated-serving KV handoff telemetry: the gateway drives
        # the prefill-export -> decode-import hop, so the cost of moving a
        # request's KV between cells is measured HERE, where both halves
        # are visible. Families are declared unconditionally so a mixed
        # deployment scrapes stable zeros.
        self._m_handoff_pages = reg.counter(
            "kukeon_handoff_pages_total",
            "KV pages moved prefill->decode across completed handoffs "
            "(1/handoff when the exporter runs the contiguous layout).")
        self._m_handoff_bytes = reg.counter(
            "kukeon_handoff_bytes_total",
            "Serialized KV bytes moved prefill->decode.")
        self._m_handoff_seconds = reg.histogram(
            "kukeon_handoff_seconds",
            "Wall time of one KV handoff: export POST through import "
            "response headers (prefill compute + both transfer legs).")
        self._m_handoff_failures = reg.counter(
            "kukeon_handoff_failures_total",
            "Handoff stage failures (connect error / 5xx / exhausted "
            "retries), by stage.", labels=("stage",))
        self._m_handoff_fallback = reg.counter(
            "kukeon_handoff_fallback_total",
            "Requests that degraded to single-cell local decode after a "
            "handoff stage failed (the graceful path — client still gets "
            "200).")
        self._m_spill = reg.counter(
            "kukeon_gateway_spill_total",
            "All-shed requests parked in the gateway spillover queue, by "
            "final outcome (recovered = a retry won a replica; timeout = "
            "in-band deadline terminal; overflow = queue full, original "
            "shed passed through; fault = gateway.spill chaos seam "
            "degraded the path).", labels=("outcome",))
        for outcome in ("recovered", "timeout", "overflow", "fault"):
            # Declared at 0 so a quiet gateway scrapes a stable schema.
            self._m_spill.inc(0, outcome=outcome)
        reg.gauge(
            "kukeon_gateway_spill_queue_depth",
            "Requests currently parked in the spillover queue."
        ).set_function(lambda: float(self._spill_depth))
        self._m_spill_wait = reg.histogram(
            "kukeon_gateway_spill_wait_seconds",
            "Time a spilled request spent parked before its outcome "
            "(recovered, timeout, or a terminal shed).")
        ready_g = reg.gauge("kukeon_gateway_replica_ready",
                            "1 while the replica is in rotation.",
                            labels=("replica",))
        depth_g = reg.gauge("kukeon_gateway_replica_queue_depth",
                            "Last polled engine queue depth.",
                            labels=("replica",))
        for rep in self.router.replicas:
            ready_g.set_function(
                lambda r=rep: 1.0 if r.ready else 0.0, replica=rep.name)
            depth_g.set_function(
                lambda r=rep: float(r.queue_depth), replica=rep.name)
        reg.register_collector(self._trace_collect)

    def _trace_collect(self):
        ss = self.tracer.sample_stats
        yield ("kukeon_trace_tail_sampled_total", "counter",
               "Tail-sampler verdicts on finished trace spans (error/"
               "preempted/retried/slow spans are always kept).",
               [({"decision": "kept"}, float(ss["kept"])),
                ({"decision": "dropped"}, float(ss["dropped"]))])

    def start(self) -> None:
        self.router.start()

    def stop(self) -> None:
        self.router.stop()

    # --- distributed tracing ----------------------------------------------

    def begin_span(self, route: str,
                   ctx: "obs_trace.TraceContext | None"):
        """The gateway-side proxy span for one request: joins the client's
        trace when a traceparent came in, else roots a fresh one. Every
        replica attempt/retry is recorded on it; downstream hops hang
        under it via the propagated header."""
        span = self.tracer.begin(next(self._span_seq), 0, trace_ctx=ctx,
                                 component="gateway")
        span.attrs["route"] = route
        return span

    def finish_span(self, span, outcome: str, **attrs) -> None:
        if span is None:
            return
        span.attrs.update({k: v for k, v in attrs.items() if v is not None})
        self.tracer.finish(span, outcome)

    # --- proxy plumbing ----------------------------------------------------

    def _open(self, rep, path: str, body: bytes,
              headers: dict[str, str] | None = None):
        """One upstream POST; returns (conn, resp). Caller owns closing."""
        u = urlsplit(rep.url)
        conn = http.client.HTTPConnection(u.hostname, u.port,
                                          timeout=self.request_timeout_s)
        try:
            conn.request("POST", path, body=body,
                         headers={"Content-Type": "application/json",
                                  "Content-Length": str(len(body)),
                                  **(headers or {})})
            return conn, conn.getresponse()
        except Exception:
            conn.close()
            raise

    def _try_replica(self, rep, path: str, body: bytes,
                     fwd_headers: "dict[str, str] | None", span=None,
                     stage: str | None = None):
        """One dial of one replica with the shared demotion/retry
        accounting (connect error and 429/503 are retryable — demote,
        count, record the hop on the span). Returns
        ``("response", conn, resp)`` for anything else (the caller owns
        closing and ``rep.end()``), or ``("retry", last_tuple)`` with
        everything already closed."""
        stage_attrs = {"stage": stage} if stage else {}
        rep.begin()
        try:
            conn, resp = self._open(rep, path, body, fwd_headers)
        except OSError as e:
            rep.end()
            self.router.mark_unready(rep)
            self._m_requests.inc(replica=rep.name, outcome="connect_error")
            self._m_retries.inc(reason="connect_error")
            if span is not None:
                span.event("proxy_retry", replica=rep.name,
                           reason="connect_error", **stage_attrs)
                span.attrs["retries"] = (
                    span.attrs.get("retries", 0) + 1)
            return ("retry", (rep.name, None, str(e), None))
        if resp.status in (429, 503):
            payload = resp.read()
            retry_after = resp.getheader("Retry-After")
            conn.close()
            rep.end()
            if resp.status == 503:
                # Lifecycle refusal (draining / warming / wedged): out
                # of rotation until a poll says otherwise. 429 is queue
                # pressure — the replica stays routable for others.
                self.router.mark_unready(rep)
            self._m_requests.inc(
                replica=rep.name,
                outcome="shed" if resp.status == 429 else "unready")
            self._m_retries.inc(reason=f"status_{resp.status}")
            if span is not None:
                span.event("proxy_retry", replica=rep.name,
                           reason=f"status_{resp.status}", **stage_attrs)
                span.attrs["retries"] = (
                    span.attrs.get("retries", 0) + 1)
            return ("retry", (rep.name, resp.status, payload, retry_after))
        return ("response", conn, resp)

    def select_and_proxy(self, path: str, body: bytes,
                         prefix_id: str | None, span=None,
                         pool: str | None = None,
                         exclude: "set[str] | None" = None):
        """Route with bounded retry until a replica yields a non-retryable
        response. Returns one of:

          ("response", replica, conn, resp)  — pass this response through
          ("shed", status, payload, retry_after_s) — gateway-level answer

        A 2xx "response" may still be a stream the caller relays; the
        replica's inflight counter was incremented via ``rep.begin()`` and
        the caller must ``rep.end()`` when done with the response.

        ``pool`` restricts routing to a role pool (the handoff fallback
        routes over prefill-capable replicas); ``exclude`` seeds the
        per-replica once-per-request set with replicas an earlier handoff
        stage already burned, so the fallback never re-dials a replica
        this request has seen fail.
        """
        excluded: set[str] = set(exclude or ())
        last: tuple | None = None   # (replica_name, status, body, retry_after)
        repolled = False
        attempts = 0
        # Downstream hops join the gateway's trace as children of ITS span
        # (one header for every attempt of this request — the engine-side
        # spans of a retried request share one parent).
        fwd_headers = (
            {obs_trace.TRACEPARENT_HEADER: obs_trace.format_traceparent(
                span.trace_id, span.span_id)}
            if span is not None else None)
        while attempts < max(1, len(self.router.replicas)):
            rep, policy = self.router.pick(prefix_id, exclude=excluded,
                                           pool=pool)
            if rep is None:
                if not repolled:
                    # The routable set can look empty for one poll interval
                    # after a replica comes back (a rollout advances the
                    # moment /readyz flips, faster than the poll tick).
                    # Refresh the snapshot once before shedding — this is
                    # the difference between a zero-failed-request rollout
                    # and a sub-second 503 blip per replica.
                    repolled = True
                    self.router.poll_once()
                    continue
                break
            attempts += 1
            self._m_routing.inc(policy=policy)
            if span is not None:
                span.event("proxy_attempt", replica=rep.name, policy=policy)
            got = self._try_replica(rep, path, body, fwd_headers, span)
            if got[0] == "retry":
                excluded.add(rep.name)
                last = got[1]
                continue
            return ("response", rep, got[1], got[2])
        # Every replica refused or nothing was routable.
        if span is not None:
            span.event("proxy_shed")
        if last is not None and last[1] in (429, 503):
            self._m_shed.inc()
            return ("shed", last[1], last[2], last[3])
        self._m_shed.inc()
        return ("shed", 503,
                json.dumps({"error": "no replica available",
                            "retryAfterSeconds": GATEWAY_RETRY_AFTER_S}
                           ).encode(),
                str(GATEWAY_RETRY_AFTER_S))

    # --- spillover: park all-shed requests instead of 429ing ----------------

    def _spill_wake(self) -> None:
        """Router-poll listener: capacity may have returned — wake every
        parked request so it retries now, not at its timer backstop."""
        with self._spill_lock:
            self._spill_cond.notify_all()

    def spill_or_shed(self, shed, retry, deadline_s: float, span=None):
        """An all-shed verdict enters the bounded spillover queue: the
        request parks at the gateway and re-routes when a replica frees
        (router-poll wakeup, 50ms timer backstop) instead of passing the
        429/503 through — a brief storm becomes client latency, never an
        error. Three ways out:

          - a retry wins a replica: return its ("response"/"inline", ...)
            verdict (outcome ``recovered``);
          - the deadline expires while parked: ("spill_timeout", shed) —
            the handler renders the in-band timeout terminal;
          - the queue is full, or the ``gateway.spill`` fault point is
            armed: the ORIGINAL shed verdict passes through untouched
            (bounded queue + chaos both degrade to the pre-spillover
            contract, they never deadlock a handler thread).

        ``retry`` re-runs this request's routing (single-hop or the
        disaggregated two-stage driver); ``shed`` is refreshed on every
        re-shed so a final passthrough carries the newest Retry-After."""
        try:
            faults.maybe_fail("gateway.spill")
        except faults.FaultInjected:
            self._m_spill.inc(outcome="fault")
            return shed
        with self._spill_lock:
            if self._spill_depth >= self.spill_capacity:
                self._m_spill.inc(outcome="overflow")
                return shed
            self._spill_depth += 1
        t0 = time.monotonic()
        deadline = t0 + max(0.0, deadline_s)
        if span is not None:
            span.event("spill_park")
        try:
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._m_spill.inc(outcome="timeout")
                    return ("spill_timeout", shed)
                with self._spill_lock:
                    self._spill_cond.wait(
                        timeout=min(SPILL_WAIT_TICK_S, remaining))
                if not self.router.ready_count():
                    # Nothing routable at all: retrying now would only
                    # stampede the poll path. The background poll promotes
                    # a recovered replica and wakes us.
                    continue
                got = retry()
                if got[0] != "shed":
                    self._m_spill.inc(outcome="recovered")
                    if span is not None:
                        span.event("spill_resume")
                    return got
                shed = got
        finally:
            self._m_spill_wait.observe(time.monotonic() - t0)
            with self._spill_lock:
                self._spill_depth -= 1
                self._spill_cond.notify_all()

    # --- disaggregated two-stage routing (KV handoff) ----------------------

    def handoff_and_proxy(self, req: dict, body: bytes,
                          prefix_id: str | None, stream: bool, span=None):
        """Two-stage routing for ``/v1/generate`` when the replica census
        declares roles: export the prompt's KV from a prefill replica
        (picked by queue depth), import it into a decode replica (picked by
        the same rendezvous prefix affinity as the mixed path), and hand
        the decode replica's live response back for relaying. Both hops
        carry this span's traceparent, so the prefill-cell and decode-cell
        engine spans land as children of ONE gateway span.

        Degradation contract (the ``kv.handoff`` robustness satellite):
        any stage failing — import 5xx, decode replica dead or shedding,
        no decode replica ready — falls back to single-cell local decode
        on a prefill-capable replica instead of surfacing a handoff 5xx;
        the client sees 200, or the usual 429/503 shed when genuinely
        nothing has capacity.

        Returns select_and_proxy's shapes plus
        ``("inline", status, payload, content_type)`` when the gateway can
        answer from the export header alone (first token already
        terminal, or a 400 passing through)."""
        t0 = time.monotonic()
        excluded: set[str] = set()   # hard: connect error / 429 / 503
        soft: set[str] = set()       # handoff-5xx: still fallback-eligible
        fwd_headers = (
            {obs_trace.TRACEPARENT_HEADER: obs_trace.format_traceparent(
                span.trace_id, span.span_id)}
            if span is not None else None)

        def fallback(stage: str):
            if span is not None:
                span.event("handoff_fallback", stage=stage)
            self._m_handoff_fallback.inc()
            return self.select_and_proxy("/v1/generate", body, prefix_id,
                                         span=span, pool="prefill",
                                         exclude=excluded)

        # --- stage 1: prefill export (queue-depth pick) --------------------
        export_req = dict(req)
        export_req.pop("stream", None)
        ebody = json.dumps(export_req).encode()
        export = None
        last: tuple | None = None
        repolled = False
        attempts = 0
        while attempts < max(1, len(self.router._pool_members("prefill"))):
            rep, policy = self.router.pick_prefill(exclude=excluded | soft)
            if rep is None:
                if not repolled:
                    repolled = True
                    self.router.poll_once()
                    continue
                break
            attempts += 1
            self._m_routing.inc(policy=policy)
            if span is not None:
                span.event("proxy_attempt", replica=rep.name, policy=policy,
                           stage="export")
            got = self._try_replica(rep, "/v1/kv/export", ebody, fwd_headers,
                                    span, stage="export")
            if got[0] == "retry":
                excluded.add(rep.name)
                last = got[1]
                continue
            _tag, conn, resp = got
            if resp.status != 200:
                payload = resp.read()
                ctype = resp.getheader("Content-Type") or "application/json"
                conn.close()
                rep.end()
                self._m_requests.inc(replica=rep.name,
                                     outcome=f"status_{resp.status}")
                if resp.status == 400:
                    # The client's problem — pass it through untouched.
                    return ("inline", 400, payload, ctype)
                self._m_handoff_failures.inc(stage="export")
                soft.add(rep.name)
                continue
            data = resp.read()
            conn.close()
            rep.end()
            self._m_requests.inc(replica=rep.name, outcome="ok")
            nl = data.find(b"\n")
            try:
                header = json.loads(data[:max(nl, 0)])
            except ValueError:
                self._m_handoff_failures.inc(stage="export")
                soft.add(rep.name)
                continue
            export = (rep.name, header, data[nl + 1:])
            break
        if export is None:
            if last is not None and last[1] in (429, 503):
                # Every prefill-capable replica shed: same passthrough
                # semantics as the single-hop path.
                if span is not None:
                    span.event("proxy_shed")
                self._m_shed.inc()
                return ("shed", last[1], last[2], last[3])
            return fallback("export")

        prefill_name, header, raw = export
        if header.get("done"):
            # The first token is already terminal (eos / stop / one-token
            # budget): no decode hop needed — answer from the header.
            first = int(header.get("token", -1))
            text = header.get("text") or ""
            secs = round(time.monotonic() - t0, 4)
            if stream:
                payload = (
                    json.dumps({"token": first, "text": text}) + "\n"
                    + json.dumps({"done": True, "tokens": [first],
                                  "text": text, "numTokens": 1,
                                  "seconds": secs}) + "\n").encode()
                return ("inline", 200, payload, "application/x-ndjson")
            payload = json.dumps({"tokens": [first], "text": text,
                                  "numTokens": 1, "seconds": secs}).encode()
            return ("inline", 200, payload, "application/json")

        # --- stage 2: decode import (prefix affinity pick) -----------------
        imp_header = dict(header)
        imp_header["stream"] = bool(stream)
        ibody = json.dumps(imp_header).encode() + b"\n" + raw
        repolled = False
        attempts = 0
        while attempts < max(1, len(self.router._pool_members("decode"))):
            rep, policy = self.router.pick_decode(prefix_id,
                                                  exclude=excluded | soft)
            if rep is None:
                if not repolled:
                    repolled = True
                    self.router.poll_once()
                    continue
                break
            attempts += 1
            self._m_routing.inc(policy=policy)
            if span is not None:
                span.event("proxy_attempt", replica=rep.name, policy=policy,
                           stage="import")
            got = self._try_replica(rep, "/v1/kv/import", ibody, fwd_headers,
                                    span, stage="import")
            if got[0] == "retry":
                excluded.add(rep.name)
                if got[1][1] is None:
                    # Connect failure = the decode replica died mid-
                    # handoff; a 429/503 is ordinary shedding, not a
                    # handoff fault.
                    self._m_handoff_failures.inc(stage="import")
                continue
            _tag, conn, resp = got
            if resp.status != 200:
                payload = resp.read()
                ctype = resp.getheader("Content-Type") or "application/json"
                conn.close()
                rep.end()
                self._m_requests.inc(replica=rep.name,
                                     outcome=f"status_{resp.status}")
                if resp.status == 400:
                    return ("inline", 400, payload, ctype)
                self._m_handoff_failures.inc(stage="import")
                soft.add(rep.name)
                continue
            # Handoff complete: account the move and relay the live
            # response (the import stream carries the first token line
            # the moment the decode cell emits it).
            n = int(header.get("length") or 0)
            pt = int(header.get("pageTokens") or 0)
            pages = (n // pt + 1) if pt else 1
            self._m_handoff_pages.inc(pages)
            self._m_handoff_bytes.inc(len(raw))
            self._m_handoff_seconds.observe(time.monotonic() - t0)
            if span is not None:
                span.event("kv_handoff", prefill=prefill_name,
                           decode=rep.name, pages=pages, bytes=len(raw))
            return ("response", rep, conn, resp)
        return fallback("import")

    def stats(self) -> dict:
        reg = self.registry
        return {
            "model": self.model_name,
            "kind": "gateway",
            "uptimeSeconds": round(time.time() - self.started_at, 1),
            "replicas": [r.snapshot() for r in self.router.replicas],
            "readyReplicas": self.router.ready_count(),
            "requests": int(sum(
                v for _l, v in reg.get(
                    "kukeon_gateway_requests_total").samples())),
            "retries": int(sum(
                v for _l, v in reg.get(
                    "kukeon_gateway_retries_total").samples())),
            "shed": int(reg.get("kukeon_gateway_shed_total").value()),
            "spill": {
                "depth": self._spill_depth,
                "capacity": self.spill_capacity,
                **{k: int(reg.get("kukeon_gateway_spill_total").value(
                    outcome=k))
                   for k in ("recovered", "timeout", "overflow", "fault")},
            },
            # The gateway admits while >=1 replica does; surfacing the same
            # ready/draining keys as a serving cell keeps pollers uniform.
            "ready": self.router.ready_count() > 0,
            "draining": False,
            "queueDepth": sum(r.queue_depth for r in self.router.replicas),
        }


def make_gateway_handler(gw: GatewayCell):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *a):
            sys.stderr.write("gateway: " + fmt % a + "\n")

        def _send(self, code: int, obj: dict,
                  headers: dict[str, str] | None = None):
            body = json.dumps(obj).encode()
            self._send_raw(code, body, "application/json", headers)

        def _send_raw(self, code: int, body: bytes, content_type: str,
                      headers: dict[str, str] | None = None):
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path = urlsplit(self.path).path
            if path in ("/healthz", "/v1/health"):
                self._send(200, {"status": "ok", "model": gw.model_name,
                                 "kind": "gateway"})
            elif path == "/readyz":
                n = gw.router.ready_count()
                if n:
                    self._send(200, {"ready": True, "readyReplicas": n})
                else:
                    self._send(503, {"ready": False,
                                     "reason": "no replica ready"})
            elif path == "/v1/stats":
                self._send(200, gw.stats())
            elif path == "/metrics":
                self._send_raw(200, expo.render(gw.registry).encode(),
                               expo.CONTENT_TYPE)
            elif path == "/v1/trace":
                # Gateway-side proxy spans (attempts, retry hops, shed
                # outcomes) — the front-door half of the federated trace
                # timeline; same query surface as the serving cells.
                q = parse_qs(urlsplit(self.path).query)
                if "trace_id" in q:
                    self._send(200, {"spans":
                                     gw.tracer.for_trace(q["trace_id"][0])})
                    return
                if "request_id" in q:
                    try:
                        rid = int(q["request_id"][0])
                    except ValueError:
                        self._send(400, {"error":
                                         "request_id must be an integer"})
                        return
                    self._send(200, {"spans": gw.tracer.for_request(rid)})
                    return
                try:
                    n = int(q.get("n", ["50"])[0])
                except ValueError:
                    self._send(400, {"error": "n must be an integer"})
                    return
                self._send(200, {"spans": gw.tracer.recent(n)})
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            path = urlsplit(self.path).path
            if path not in ("/v1/generate", "/v1/embed"):
                self._send(404, {"error": f"no route {self.path}; this "
                                          "gateway proxies /v1/generate "
                                          "and /v1/embed"})
                return
            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n)
            try:
                req = json.loads(body or b"{}")
                if not isinstance(req, dict):
                    raise ValueError("request body must be a JSON object")
            except ValueError as e:
                self._send(400, {"error": f"invalid JSON body: {e}"})
                return
            prefix_id = None
            stream = False
            if path == "/v1/generate":
                prefix_id = req.get("prefixId")
                if prefix_id is not None and not isinstance(prefix_id, str):
                    self._send(400, {"error": "prefixId must be a string"})
                    return
                stream = bool(req.get("stream"))

            # The proxy span: joins the client's trace when a traceparent
            # header came in, else roots a fresh one; every replica
            # attempt lands on it and the downstream hop inherits it.
            span = gw.begin_span(path, obs_trace.parse_traceparent(
                self.headers.get(obs_trace.TRACEPARENT_HEADER)))
            if path == "/v1/generate" and gw.router.disaggregated():
                # Role census says this fleet is disaggregated: drive the
                # two-stage prefill-export -> decode-import handoff.
                def route():
                    return gw.handoff_and_proxy(req, body, prefix_id,
                                                stream, span=span)
            else:
                def route():
                    return gw.select_and_proxy(path, body, prefix_id,
                                               span=span)
            got = route()
            if got[0] == "shed":
                # Spillover: every replica shed (or nothing was routable).
                # Park the request and retry until a replica frees or the
                # deadline runs out, bounded by the spill queue capacity.
                d = req.get("deadlineS")
                wait = (min(float(d), gw.spill_max_wait_s)
                        if isinstance(d, (int, float)) and d > 0
                        else gw.spill_max_wait_s)
                got = gw.spill_or_shed(got, route, wait, span=span)
            if got[0] == "spill_timeout":
                # The deadline expired while parked. Mirror the serving
                # cell's timeout contract: 504 + timedOut for a plain
                # request; an in-band terminal line for a stream (the
                # client asked for ndjson and nothing has been sent yet).
                msg = {"error": "deadline exceeded while queued at the "
                                "gateway (all replicas shedding)",
                       "timedOut": True, "numTokens": 0}
                if stream:
                    self._send_raw(200, (json.dumps(msg) + "\n").encode(),
                                   "application/x-ndjson")
                else:
                    self._send(504, msg)
                gw.finish_span(span, "timeout")
                return
            if got[0] == "inline":
                # The gateway answered from the export header (terminal
                # first token) or passes a 400 through.
                _tag, status, payload, ctype = got
                self._send_raw(status, payload or b"{}", ctype)
                gw.finish_span(span, "ok" if status < 400 else "error",
                               status=status)
                return
            if got[0] == "shed":
                _tag, status, payload, retry_after = got
                secs = float(retry_after or GATEWAY_RETRY_AFTER_S)
                self._send_raw(status, payload or b"{}", "application/json",
                               {"Retry-After": str(max(1, math.ceil(secs)))})
                gw.finish_span(span, "shed", status=status)
                return
            _tag, rep, conn, resp = got
            try:
                if stream and resp.status == 200:
                    self._relay_stream(rep, resp, span)
                else:
                    payload = resp.read()
                    headers = {}
                    ra = resp.getheader("Retry-After")
                    if ra:
                        headers["Retry-After"] = ra
                    self._send_raw(
                        resp.status, payload,
                        resp.getheader("Content-Type") or "application/json",
                        headers)
                    gw._m_requests.inc(
                        replica=rep.name,
                        outcome="ok" if resp.status < 400 else
                        f"status_{resp.status}")
                    gw.finish_span(
                        span, "ok" if resp.status < 400 else "error",
                        replica=rep.name, status=resp.status)
            except OSError:
                # Client went away; nothing to tell it, but the span still
                # records the outcome (first finish wins — a stream error
                # already finished it in-band).
                gw.finish_span(span, "error", replica=rep.name,
                               detail="client disconnected")
            finally:
                conn.close()
                rep.end()

        def _relay_stream(self, rep, resp, span=None):
            """Byte-for-byte ndjson passthrough. The replica frames the
            stream by connection close (its handler speaks HTTP/1.0), so
            copying raw body chunks until EOF reproduces the payload
            exactly — UTF-8 split-codepoint holdback, in-band error lines
            and all. A replica dying mid-stream surfaces as an in-band
            terminal error line, never a retry (partial tokens are already
            on the client's wire) and never a second status line."""
            self.send_response(200)
            self.send_header("Content-Type",
                             resp.getheader("Content-Type")
                             or "application/x-ndjson")
            self.end_headers()
            trailing_newline = True
            try:
                while True:
                    # read1, not read: read(n) blocks for n bytes or EOF,
                    # which would buffer the whole close-framed stream and
                    # destroy token-streaming latency; read1 relays each
                    # token line the moment the replica flushes it.
                    chunk = resp.read1(STREAM_CHUNK)
                    if not chunk:
                        break
                    trailing_newline = chunk.endswith(b"\n")
                    self.wfile.write(chunk)
                    self.wfile.flush()
                gw._m_requests.inc(replica=rep.name, outcome="ok")
                gw.finish_span(span, "ok", replica=rep.name, stream=True)
            except Exception as e:  # noqa: BLE001 — headers are out; stay in-band
                gw._m_requests.inc(replica=rep.name, outcome="stream_error")
                gw.finish_span(span, "error", replica=rep.name, stream=True,
                               detail=f"{type(e).__name__}: {e}")
                gw.router.mark_unready(rep)
                try:
                    line = json.dumps({"error": "replica failed mid-stream: "
                                                f"{type(e).__name__}: {e}"})
                    if not trailing_newline:
                        # Keep the client's line parser intact: never glue
                        # the terminal error onto a half-written record.
                        self.wfile.write(b"\n")
                    self.wfile.write((line + "\n").encode())
                    self.wfile.flush()
                except OSError:
                    pass

    return Handler


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kukeon-gateway")
    ap.add_argument("--model", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--replica", action="append", required=True,
                    help="replica base URL (repeat per replica)")
    ap.add_argument("--poll-interval-s", type=float, default=0.5)
    ap.add_argument("--request-timeout-s", type=float, default=600.0)
    ap.add_argument("--spill-capacity", type=int, default=SPILL_CAPACITY,
                    help="max all-shed requests parked in the spillover "
                         "queue (past it the shed passes through)")
    ap.add_argument("--spill-max-wait-s", type=float,
                    default=SPILL_MAX_WAIT_S,
                    help="longest a spilled request without its own "
                         "deadlineS waits before the timeout terminal")
    args = ap.parse_args(argv)

    gw = GatewayCell(args.model, args.replica,
                     poll_interval_s=args.poll_interval_s,
                     request_timeout_s=args.request_timeout_s,
                     spill_capacity=args.spill_capacity,
                     spill_max_wait_s=args.spill_max_wait_s)
    gw.start()
    server = ThreadingHTTPServer((args.host, args.port),
                                 make_gateway_handler(gw))

    import signal as _signal
    import threading as _threading

    # The gateway is stateless: SIGTERM just stops the listener (off-thread
    # — shutdown() blocks until serve_forever returns, and the signal
    # handler runs on the serving thread). In-flight proxied requests ride
    # their own handler threads to completion.
    _signal.signal(_signal.SIGTERM, lambda *_a: _threading.Thread(
        target=server.shutdown, daemon=True).start())

    print(f"gateway: {args.model} routing {len(args.replica)} replicas "
          f"on {args.host}:{args.port}", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        gw.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
