"""kukeon-tpu: a TPU-native runtime for AI agent workloads.

A ground-up rebuild of the capabilities of eminwux/kukeon (a single-host
containerd "cell" runtime for AI coding agents) designed TPU-first:

- ``kukeon_tpu.models`` / ``ops`` / ``parallel`` / ``serving`` / ``training``:
  the JAX/XLA/Pallas compute path — the in-tree model-serving engine that
  runs inside model cells (the reference has no model math; the TPU build's
  north star adds an in-tree JetStream-style serving cell — see BASELINE.json).
- ``kukeon_tpu.runtime``: the orchestration control plane — manifests,
  daemon, controller, reconciler, cells, secrets, volumes, teams — the
  capability-parity layer with the reference's Go daemon (kukeond).

The compute path is pure JAX: SPMD over a ``jax.sharding.Mesh``, pjit/GSPMD
sharding for tensor/data/FSDP parallelism, ``shard_map`` + ``ppermute`` ring
attention for sequence parallelism, and Pallas kernels for the hot ops.
"""

__version__ = "0.1.0"
