"""Block-table KV page allocator: HBM as fixed-size pages, not slot slabs.

The legacy engine layout reserves ``num_slots * max_seq_len`` KV rows up
front — a slot serving a 40-token chat pins the same HBM as one serving a
4k-token agent context, so mixed-length traffic fragments the cache and
caps concurrency far below what the chip could hold. This module owns the
host-side bookkeeping for the paged layout instead:

- **Pages**: the engine's device pool is ``[L, P, page_tokens, KV, D]`` —
  ``P`` fixed-size pages of ``page_tokens`` KV rows each, allocated and
  freed page-granularly as requests are admitted, grow, and finish.
- **Page 0 is scratch**: never allocated. Block-table entries of released
  slots point at it (a stale in-flight decode write lands in scratch, not
  in a page that was re-issued to another request), and insert-time
  scatters redirect shared-prefix and padding pages to it so shared pages
  are physically read-only.
- **Refcounts**: a page may be held by the slot that wrote it AND by any
  number of prefix-cache entries / later sessions reading it. ``alloc``
  hands out pages at refcount 1; ``ref``/``unref`` move the count; a page
  returns to the free list only at zero. N agent sessions on one shared
  prefix therefore pay its KV cost once — the prefix entry pins the pages,
  sessions add references, nobody copies.
- **Exhaustion is a first-class outcome**: ``alloc`` raises
  :class:`PagePoolExhausted` (and the ``kv.alloc`` fault point can inject
  it) — the engine responds by evicting prefix entries, preempting the
  lowest-priority in-flight request, or shedding, never by deadlocking.

Import-light on purpose (numpy only): allocation decisions are host-side
scheduler work; nothing here touches a device.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from collections.abc import Iterable

import numpy as np

from kukeon_tpu import faults

# The reserved scratch page: gather/scatter targets for "nowhere" — stale
# writes from released slots, shared-prefix redirects, bucket padding.
SCRATCH_PAGE = 0


class PagePoolExhausted(RuntimeError):
    """Not enough free KV pages to satisfy an allocation.

    Recoverable by design: pages free as requests finish, prefix entries
    evict, or a victim is preempted. The engine decides which; the
    allocator only reports the fact."""


def pages_for(n_tokens: int, page_tokens: int) -> int:
    """Pages needed to hold ``n_tokens`` KV rows (ceil)."""
    return -(-max(0, int(n_tokens)) // int(page_tokens))


class PageAllocator:
    """Free-list + refcount bookkeeping over ``num_pages`` usable pages.

    Page ids run 1..num_pages (0 is :data:`SCRATCH_PAGE`, never issued).
    The free list is FIFO so a just-freed page is re-issued as late as
    possible — defense in depth under the double-buffered decode dispatch,
    on top of the device-order argument that makes immediate reuse safe.

    Driver-thread only (like every other piece of engine scheduling state);
    no locking.
    """

    def __init__(self, num_pages: int, page_tokens: int) -> None:
        if num_pages < 1:
            raise ValueError(f"need at least 1 usable page, got {num_pages}")
        if page_tokens < 1:
            raise ValueError(f"page_tokens must be >= 1, got {page_tokens}")
        self.num_pages = int(num_pages)
        self.page_tokens = int(page_tokens)
        self._free: deque[int] = deque(range(1, self.num_pages + 1))
        self._ref: dict[int, int] = {}

    # --- introspection ----------------------------------------------------

    @property
    def free(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.num_pages - len(self._free)

    def refcount(self, page: int) -> int:
        return self._ref.get(page, 0)

    def pages_for(self, n_tokens: int) -> int:
        return pages_for(n_tokens, self.page_tokens)

    # --- alloc / ref / free ----------------------------------------------

    def alloc(self, n: int) -> list[int]:
        """``n`` fresh pages at refcount 1, or :class:`PagePoolExhausted`.

        All-or-nothing: a partial grant would leave the caller holding
        pages it cannot use while blocking everyone else. The ``kv.alloc``
        fault point injects exhaustion here so shedding/preemption paths
        are testable without actually filling HBM."""
        try:
            faults.maybe_fail("kv.alloc")
        except faults.FaultInjected as e:
            raise PagePoolExhausted(str(e)) from e
        if n <= 0:
            return []
        if n > len(self._free):
            raise PagePoolExhausted(
                f"need {n} KV pages, {len(self._free)}/{self.num_pages} free"
            )
        out = [self._free.popleft() for _ in range(n)]
        for p in out:
            self._ref[p] = 1
        return out

    def ref(self, pages: Iterable[int]) -> None:
        """Add one reference to each page (a new reader of shared pages)."""
        for p in pages:
            if p == SCRATCH_PAGE:
                continue
            if p not in self._ref:
                raise ValueError(f"ref of unallocated page {p}")
            self._ref[p] += 1

    def unref(self, pages: Iterable[int]) -> int:
        """Drop one reference from each page; pages reaching zero return to
        the free list. Returns how many were freed."""
        freed = 0
        for p in pages:
            if p == SCRATCH_PAGE:
                continue
            c = self._ref.get(p)
            if c is None:
                raise ValueError(f"unref of unallocated page {p}")
            if c <= 1:
                del self._ref[p]
                self._free.append(p)
                freed += 1
            else:
                self._ref[p] = c - 1
        return freed


@dataclasses.dataclass
class SharedPrefix:
    """One prefix-cache entry in the paged layout: a *view* over pool pages,
    not a tensor copy. ``pages`` hold one reference each (taken by the
    engine at store time); ``length`` is page-aligned — the trailing
    partial page of a prompt stays private to the slot that wrote it,
    because decode writes the positions right after the prompt into that
    page and sharing it would let one session corrupt another's KV."""

    tokens: np.ndarray           # the aligned prefix the pages encode (int32)
    pages: list[int]             # pool page ids, in sequence order
    length: int                  # == len(pages) * page_tokens

    def nbytes(self, page_bytes: int) -> int:
        return len(self.pages) * page_bytes
