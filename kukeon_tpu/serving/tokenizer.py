"""Tokenizer loading for serving cells.

Real checkpoints ship a ``tokenizer.json`` (HF tokenizers format); load it
with the ``tokenizers`` runtime when present. Hosts without a checkpoint
(random-init shape benchmarking, tests) fall back to a byte tokenizer so
the serving stack exercises identical code paths either way.
"""

from __future__ import annotations

import os


class ByteTokenizer:
    """Trivial fallback: one token per byte, offset to keep 0 reserved."""

    vocab_size = 258
    bos_id = 256
    eos_id = 257

    def encode(self, text: str) -> list[int]:
        return [self.bos_id] + list(text.encode("utf-8", errors="replace"))

    def decode(self, ids: list[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """tokenizer.json wrapper (Llama-3 style BPE)."""

    def __init__(self, path: str):
        from tokenizers import Tokenizer

        self.tk = Tokenizer.from_file(path)
        self.vocab_size = self.tk.get_vocab_size()
        # [CLS]/[SEP] cover BERT-family tokenizers (bge embedding models):
        # prepending [CLS] is what makes CLS-pooling meaningful.
        self.bos_id = self._special("<|begin_of_text|>", "<s>", "<bos>", "[CLS]")
        self.eos_id = self._special("<|end_of_text|>", "</s>", "<eos>",
                                    "<|eot_id|>", "[SEP]")

    def _special(self, *names: str) -> int | None:
        for name in names:
            tid = self.tk.token_to_id(name)
            if tid is not None:
                return tid
        return None

    def encode(self, text: str) -> list[int]:
        ids = self.tk.encode(text, add_special_tokens=False).ids
        if self.bos_id is not None:
            return [self.bos_id] + ids
        return ids

    def decode(self, ids: list[int]) -> str:
        drop = {i for i in (self.bos_id, self.eos_id) if i is not None}
        # Out-of-vocab ids are dropped, not fatal: a random-init model (or a
        # model whose vocab exceeds the tokenizer's, as padded checkpoints
        # do) samples ids the tokenizer never minted, and /v1/generate must
        # degrade to partial text rather than 500.
        return self.tk.decode([
            i for i in ids if i not in drop and 0 <= i < self.vocab_size
        ])


def load_tokenizer(checkpoint_dir: str | None):
    """HFTokenizer when the checkpoint ships tokenizer.json, else bytes."""
    if checkpoint_dir:
        path = os.path.join(checkpoint_dir, "tokenizer.json")
        if os.path.exists(path):
            return HFTokenizer(path)
    return ByteTokenizer()
