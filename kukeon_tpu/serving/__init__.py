from kukeon_tpu.serving.engine import (  # noqa: F401
    DecodeState,
    Request,
    ServingEngine,
    bucket_length,
)
from kukeon_tpu.serving.sampling import (  # noqa: F401
    SamplingParams,
    sample,
    sample_per_slot,
)
from kukeon_tpu.serving.embedding import (  # noqa: F401
    EMBED_BUCKETS,
    EmbeddingEngine,
)
