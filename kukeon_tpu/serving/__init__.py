from kukeon_tpu.serving.engine import (  # noqa: F401
    DeadlineExceeded,
    DecodeState,
    RejectedError,
    Request,
    ServingEngine,
    bucket_length,
)
from kukeon_tpu.serving.kv_pages import (  # noqa: F401
    PageAllocator,
    PagePoolExhausted,
    SharedPrefix,
)
from kukeon_tpu.serving.sampling import (  # noqa: F401
    SamplingParams,
    sample,
    sample_per_slot,
    slot_sampling_arrays,
)
from kukeon_tpu.serving.tuning import ServingTune  # noqa: F401
from kukeon_tpu.serving.embedding import (  # noqa: F401
    EMBED_BUCKETS,
    EmbeddingEngine,
)
