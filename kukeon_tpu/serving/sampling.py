"""Token sampling: greedy, temperature, top-k, top-p — all jit-friendly."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0   # 0 => greedy
    top_k: int = 0             # 0 => disabled
    top_p: float = 1.0         # 1 => disabled
    max_new_tokens: int = 256
    # Per-request stop tokens (host-side check in the engine's emit path —
    # the slot frees the moment one is generated; the stop token itself is
    # included in the output, clients strip it if unwanted).
    stop_tokens: tuple[int, ...] = ()


def slot_sampling_arrays(
    slot_requests, num_slots: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-slot (temps, top_ks, top_ps) host arrays for
    :func:`sample_per_slot`, from (slot, request) pairs whose requests carry
    a :class:`SamplingParams`. Empty slots sample greedily (temp 0), which
    is also a no-op for inactive slots in the decode program."""
    temps = np.zeros((num_slots,), np.float32)
    top_ks = np.zeros((num_slots,), np.int32)
    top_ps = np.ones((num_slots,), np.float32)
    for slot, req in slot_requests:
        sp = req.sampling
        temps[slot] = sp.temperature
        top_ks[slot] = sp.top_k
        top_ps[slot] = sp.top_p
    return temps, top_ks, top_ps


def sample(logits: jnp.ndarray, key: jax.Array, params: SamplingParams) -> jnp.ndarray:
    """Sample next tokens from [B, V] logits -> [B] int32.

    All branches are trace-time (params is static), so each SamplingParams
    value compiles one specialization.
    """
    if params.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    logits = logits / params.temperature

    if params.top_k > 0:
        top_vals, _ = jax.lax.top_k(logits, params.top_k)
        kth = top_vals[:, -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)

    if params.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep the smallest prefix with cumulative mass >= top_p (always keep 1).
        cutoff_idx = jnp.sum(cum < params.top_p, axis=-1)
        cutoff_logit = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < cutoff_logit, -jnp.inf, logits)

    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def sample_per_slot(
    logits: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
) -> jnp.ndarray:
    """Vectorized sampling with *dynamic per-slot* parameters.

    One compiled program covers any mix of greedy/temperature/top-k/top-p
    across the batch — the serving engine's decode path uses this so slot
    composition never recompiles.

    Args:
      logits: [B, V] float32.
      temperature: [B]; <= 0 means greedy for that slot.
      top_k: [B] int32; 0 disables.
      top_p: [B]; >= 1 disables.
    """
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / t

    def filter_topk_topp(scaled):
        # top-k: mask logits below the k-th largest (k==0 -> keep all).
        sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
        kk = jnp.clip(top_k, 1, V) - 1
        kth = jnp.take_along_axis(sorted_desc, kk[:, None], axis=-1)
        scaled = jnp.where((top_k > 0)[:, None] & (scaled < kth), -jnp.inf, scaled)

        # top-p on the (re-sorted) top-k-filtered distribution: smallest
        # prefix with mass >= top_p (matches the static ``sample`` semantics).
        sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
        probs_sorted = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs_sorted, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p[:, None], axis=-1)
        cutoff_logit = jnp.take_along_axis(sorted_desc, cutoff_idx[:, None], axis=-1)
        apply_p = (top_p < 1.0)[:, None]
        return jnp.where(apply_p & (scaled < cutoff_logit), -jnp.inf, scaled)

    # The sorts are expensive over a 128k vocab; skip them at runtime unless
    # some slot actually uses top-k/top-p.
    needs_filter = jnp.any(top_k > 0) | jnp.any(top_p < 1.0)
    scaled = jax.lax.cond(needs_filter, filter_topk_topp, lambda s: s, scaled)

    # Categorical = gumbel noise over the whole [B, V] block (an RNG sweep
    # per decode step) — skip it too when every slot is greedy.
    any_stochastic = jnp.any(temperature > 0)
    sampled = jax.lax.cond(
        any_stochastic,
        lambda s: jax.random.categorical(key, s, axis=-1).astype(jnp.int32),
        lambda s: greedy,
        scaled,
    )
    return jnp.where(temperature > 0, sampled, greedy)
