"""Persisted serving-tune profiles (the autotune → production seam).

``bench.py --autotune`` sweeps the serving perf levers (decode-chunk size,
int8 KV cache, prefill bucket ladder) on whatever backend is up and persists
the winning configuration here; ``ServingEngine``/``ServingCell`` consult the
profile at boot. A one-time sweep therefore permanently configures production
serving — no operator has to re-derive the chunk size per model/chip-count.

The profile file (default ``~/.kuke/serving_tune.json``, override with
``KUKEON_TUNE_PATH``) is a single JSON object keyed by
``model|backend|n_chips``: a profile tuned for llama3-8b on one TPU chip is
never applied to a CPU smoke of the same model, a different model, or a
different slice size — stale keys are simply ignored. This module is
import-light on purpose (no jax): the bench orchestrator reads/writes
profiles without touching any accelerator runtime.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import time

_DEFAULT_PATH = os.path.join("~", ".kuke", "serving_tune.json")


@dataclasses.dataclass(frozen=True)
class ServingTune:
    """One winning serving configuration for a (model, backend, chips) key."""

    decode_chunk: int = 16
    kv_cache_int8: bool = False
    # None keeps the engine's default bucket ladder.
    prefill_buckets: tuple[int, ...] | None = None
    # Paged KV cache page size (serving/kv_pages.py): None/0 keeps the
    # legacy slot-contiguous layout; > 0 serves from a block-table page
    # pool with pages of this many KV rows. A swept page size is an HBM/
    # concurrency lever like the others — it must tile max_seq_len and the
    # prefill buckets, which the engine validates at boot.
    kv_page_tokens: int | None = None
    # Sharding layout (the multi-chip sweep): tensor-axis size of the
    # winning mesh (None = whatever the cell's chip grant dictates) and
    # whether the KV pool shards over it (None = the engine's divisibility
    # default, False = replicate the cache — bigger HBM, no gathers).
    mesh_tensor: int | None = None
    kv_shard: bool | None = None
    # Provenance (not consumed by the engine, kept for operators/debugging).
    tok_per_s: float | None = None
    tuned_at: str | None = None

    def to_dict(self) -> dict:
        d = {
            "decode_chunk": int(self.decode_chunk),
            "kv_cache_int8": bool(self.kv_cache_int8),
        }
        if self.prefill_buckets:
            d["prefill_buckets"] = [int(b) for b in self.prefill_buckets]
        if self.kv_page_tokens:
            d["kv_page_tokens"] = int(self.kv_page_tokens)
        if self.mesh_tensor:
            d["mesh_tensor"] = int(self.mesh_tensor)
        if self.kv_shard is not None:
            d["kv_shard"] = bool(self.kv_shard)
        if self.tok_per_s is not None:
            d["tok_per_s"] = round(float(self.tok_per_s), 2)
        if self.tuned_at:
            d["tuned_at"] = self.tuned_at
        return d

    @staticmethod
    def from_dict(d: dict) -> "ServingTune":
        buckets = d.get("prefill_buckets")
        return ServingTune(
            decode_chunk=max(1, int(d["decode_chunk"])),
            kv_cache_int8=bool(d.get("kv_cache_int8", False)),
            prefill_buckets=(tuple(sorted({int(b) for b in buckets}))
                             if buckets else None),
            kv_page_tokens=(int(d["kv_page_tokens"])
                            if d.get("kv_page_tokens") else None),
            mesh_tensor=(int(d["mesh_tensor"])
                         if d.get("mesh_tensor") else None),
            kv_shard=(bool(d["kv_shard"])
                      if d.get("kv_shard") is not None else None),
            tok_per_s=(float(d["tok_per_s"])
                       if d.get("tok_per_s") is not None else None),
            tuned_at=d.get("tuned_at"),
        )


def profile_path(path: str | None = None) -> str:
    return os.path.expanduser(
        path or os.environ.get("KUKEON_TUNE_PATH") or _DEFAULT_PATH
    )


def profile_key(model: str, backend: str, n_chips: int) -> str:
    return f"{model}|{backend}|{int(n_chips)}"


def _read_all(path: str) -> dict:
    try:
        with open(path) as f:
            d = json.load(f)
        return d if isinstance(d, dict) else {}
    except (OSError, ValueError):
        # Missing or corrupt profile: serving must boot with defaults, never
        # die to a bad tuning file.
        return {}


def load(model: str | None, backend: str, n_chips: int,
         path: str | None = None) -> ServingTune | None:
    """The stored tune for this exact (model, backend, chips) key, or None.

    Any mismatch — other model, other backend, other slice size, unreadable
    file, malformed entry — is a miss, not an error: a stale profile must
    degrade to defaults silently."""
    if not model:
        return None
    entry = _read_all(profile_path(path)).get(
        profile_key(model, backend, n_chips)
    )
    if not isinstance(entry, dict):
        return None
    try:
        return ServingTune.from_dict(entry)
    except (KeyError, TypeError, ValueError):
        return None


_LAYER_PROFILE_DEFAULT_PATH = os.path.join("~", ".kuke", "layer_profile.json")


def layer_profile_path(path: str | None = None) -> str:
    return os.path.expanduser(
        path or os.environ.get("KUKEON_LAYER_PROFILE_PATH")
        or _LAYER_PROFILE_DEFAULT_PATH
    )


def load_layer_profile(model: str | None, backend: str, n_chips: int,
                       path: str | None = None) -> dict | None:
    """The persisted per-layer cost profile (obs/profile.profile_layers)
    for this exact (model, backend, chips) key, or None — same miss-not-
    error contract as the serving tune next door."""
    if not model:
        return None
    entry = _read_all(layer_profile_path(path)).get(
        profile_key(model, backend, n_chips)
    )
    return entry if isinstance(entry, dict) else None


def load_layer_profiles(path: str | None = None) -> dict[str, dict]:
    """Every persisted layer profile, keyed ``model|backend|n_chips`` —
    what `kuke profile layers` lists and substring-matches against."""
    return {k: v for k, v in _read_all(layer_profile_path(path)).items()
            if isinstance(v, dict)}


def save_layer_profile(model: str, backend: str, n_chips: int,
                       profile: dict, path: str | None = None) -> str:
    """Merge one per-layer cost profile under its key; returns the path.
    Same atomic read-modify-write as :func:`save` — the pipeline-split
    planner reading this file mid-write must never see a torn JSON."""
    p = layer_profile_path(path)
    os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
    entries = _read_all(p)
    profile = dict(profile)
    profile.setdefault(
        "profiled_at", time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
    entries[profile_key(model, backend, n_chips)] = profile
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(p) or ".",
                               prefix=".layer_profile-")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(entries, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, p)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return p


def save(model: str, backend: str, n_chips: int, tune: ServingTune,
         path: str | None = None) -> str:
    """Merge ``tune`` into the profile file under its key; returns the path.

    Read-modify-write of the whole file with an atomic rename, so profiles
    for other models/backends survive and a crashed writer never leaves a
    truncated file behind."""
    p = profile_path(path)
    os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
    entries = _read_all(p)
    if tune.tuned_at is None:
        tune = dataclasses.replace(
            tune, tuned_at=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        )
    entries[profile_key(model, backend, n_chips)] = tune.to_dict()
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(p) or ".",
                               prefix=".serving_tune-")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(entries, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, p)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return p
