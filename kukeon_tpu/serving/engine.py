"""Continuous-batching serving engine (the JetStream-style model-cell core).

The reference runtime (eminwux/kukeon) has no model math; the TPU build's
north star adds an in-tree JAX serving cell (BASELINE.json: Llama-3-8B agent
serving at >=1500 aggregate tok/s on v5e-8). This module is that serving
core, designed for TPU:

- **Slot-based decode batch**: a fixed [B_slots] decode batch with a
  fixed-shape KV cache [L, B, S_max, KV, D]. Static shapes => one compiled
  decode program; occupancy changes never recompile.
- **Paged KV cache (``kv_page_tokens > 0``)**: instead of reserving
  ``num_slots * S_max`` contiguous rows, HBM is owned as fixed-size pages
  ([L, P, page_tokens, KV, D], serving/kv_pages.py) with a per-slot block
  table threaded into the jitted programs — gather/scatter by page index
  replaces slot-contiguous cache views. Pages alloc/free page-granularly as
  requests are admitted, grow, and finish, so mixed-length agent traffic
  packs the chip instead of fragmenting it; under memory pressure the
  lowest-priority in-flight request is *preempted* (pages reclaimed,
  request requeued ahead of new admissions, re-prefilled on resume), and
  prefix-cache entries become shared read-only pages with refcounts — N
  sessions on one agent prefix pay its KV cost once. The block table is a
  [B, S_max/page_tokens] int32 array with static shape, so the decode
  program still never recompiles across occupancy churn, and it is
  device-cached with a dirty flag like the sampling arrays, so steady-state
  chunks still perform exactly one blocking transfer (the token fetch).
- **Disaggregated prefill/insert/decode programs**: prefill runs per request
  at a small set of bucketed lengths (bounded compile cache), its KV block is
  inserted into a free slot, and the decode program generates tokens for
  every active slot.
- **Chunked multi-step decode**: decode runs K steps in one ``lax.scan`` on
  device, sampling included, and transfers a single [B, K] token block back.
  One dispatch per K tokens instead of per token — this is what makes the
  engine fast when the host-device link has latency (remote/tunneled chips)
  and removes Python from the inner loop entirely.
- **Double-buffered dispatch**: chunk N+1 is dispatched *before* chunk N's
  token block is fetched, so the host->device round-trip (~70ms on a
  tunneled chip) overlaps the next chunk's compute instead of serializing
  with it. Tokens therefore emit one chunk behind the device; a request
  finishing mid-flight overshoots at most one extra chunk, whose tokens are
  discarded (same overshoot contract the scheduler already has).
- **Donation**: decode state (cache) is donated, so the multi-GB cache is
  updated in place in HBM.
- **Sharding**: params tensor-sharded over the mesh; cache sharded on
  kv-heads over ``tensor``; decode batch replicated (latency path) — XLA
  inserts the psums over ICI.

Python's role is only orchestration: queueing requests, picking slots,
copying sampled token blocks out. All math is inside three jitted programs.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from collections.abc import Mapping
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from kukeon_tpu import faults, sanitize
from kukeon_tpu.models import llama
from kukeon_tpu.serving.kv_pages import (
    SCRATCH_PAGE,
    PageAllocator,
    PagePoolExhausted,
    SharedPrefix,
)
from kukeon_tpu.obs import (
    CompileTracker,
    FlightRecorder,
    ProgramTimers,
    Registry,
    Tracer,
    device_memory_collector,
    faults_collector,
)
from kukeon_tpu.parallel import sharding as shd
from kukeon_tpu.parallel.mesh import set_mesh
from kukeon_tpu.serving.sampling import (
    SamplingParams,
    sample_per_slot,
    slot_sampling_arrays,
)

PREFILL_BUCKETS = (64, 128, 256, 512, 1024, 2048, 4096)

_LOG = logging.getLogger("kukeon.serving.engine")


class _CounterMapView(Mapping):
    """Read-only dict view over a labelled registry counter.

    PR 2's ``shed_stats`` dict migrated onto the metrics registry; this
    keeps every existing reader (``/v1/stats``, tests, operators poking the
    engine in a REPL) working unchanged while the registry is the single
    source of truth the Prometheus exposition scrapes."""

    def __init__(self, counter, label: str, keys: tuple[str, ...]):
        self._counter = counter
        self._label = label
        self._keys = keys

    def __getitem__(self, key: str) -> int:
        if key not in self._keys:
            raise KeyError(key)
        return int(self._counter.value(**{self._label: key}))

    def __iter__(self):
        return iter(self._keys)

    def __len__(self) -> int:
        return len(self._keys)


class RejectedError(RuntimeError):
    """Request shed by admission control (queue full, draining, or unready).

    Carries ``retry_after_s`` so HTTP front-ends can answer 429/503 with a
    concrete ``Retry-After`` instead of inviting an immediate retry storm.
    """

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class DeadlineExceeded(RuntimeError):
    """A request's deadline passed before it finished generating."""


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DecodeState:
    """Whole-engine decode state; lives sharded in HBM between steps."""

    cache: llama.KVCache          # [L, B, S_max, KV, D] + lengths [B]
    tokens: jnp.ndarray           # [B] int32 — last emitted token per slot
    active: jnp.ndarray           # [B] bool — slot currently generating


@dataclasses.dataclass
class Request:
    """One generation request, as tracked by the engine."""

    id: int
    prompt: np.ndarray
    sampling: SamplingParams
    # (token, done); a cancelled request's terminal event is (-1, True).
    emit: Callable[[int, bool], None] | None = None
    generated: list[int] = dataclasses.field(default_factory=list)
    error: Exception | None = None
    slot: int = -1
    submitted_at: float = 0.0
    first_token_at: float = 0.0
    last_token_at: float = 0.0
    # Observability: the request's trace span (obs/trace.py). The engine
    # driver stamps lifecycle events on it; /v1/trace exports it.
    trace: Any = None
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    cancelled: bool = False
    # Absolute monotonic deadline (None = no deadline). Checked at dequeue
    # and once per driver iteration (i.e. per decode chunk): an expired
    # request emits the in-band timeout terminal event and frees its slot.
    deadline: float | None = None
    timed_out: bool = False
    # Prefix-cache participation (agent sessions share a system prompt /
    # growing conversation): requests with the same prefix_id reuse the
    # stored prompt KV and prefill only the new suffix.
    prefix_id: str | None = None
    # Paged-KV preemption (kv_pages): a preempted request lost its slot and
    # pages under memory pressure; it sits in the resume queue (ahead of new
    # admissions) and re-prefills prompt+generated when re-admitted.
    # ``requeued`` also marks that the request already left the _pending_n
    # admission count — terminal paths must not decrement it again.
    preemptions: int = 0
    requeued: bool = False
    # Disaggregated serving (KV handoff). ``export=True`` runs prefill ONLY:
    # no slot is seated, no pages are allocated — the dense prefill KV block
    # is fetched to host (through the counted ``_fetch`` seam) and handed
    # back on ``export_payload`` with the first sampled token; a decode cell
    # imports it and continues generation without re-running prefill.
    export: bool = False
    export_payload: "dict | None" = None
    # Import side: {"token", "length", "k", "v"} — host numpy KV rows
    # [L, 1, length, KV, D] from a prefill cell's export. The request seats
    # directly into a decode slot (``insert_paged``/``insert`` scatter the
    # block home); if it is later preempted, ``generated`` is non-empty and
    # the resume path re-prefills locally like any preempted request.
    kv_import: "dict | None" = None

    def cancel(self) -> None:
        """Ask the engine to stop generating for this request. Thread-safe:
        only sets a flag; the driver (step loop) acts on it on its next
        iteration — releasing the slot for an active request, or completing
        a still-queued one without waiting for a slot — so engine state is
        never touched off-thread. Waiters wake via ``done``."""
        self.cancelled = True


@dataclasses.dataclass
class _CachedPrefix:
    """Stored prompt KV for one prefix_id (device arrays)."""

    tokens: np.ndarray               # the exact prompt this KV encodes (int32)
    kv_k: Any                        # [L, 1, Pb, KV, D], Pb a CANONICAL bucket
    kv_v: Any
    length: int                      # valid positions in the block

    @property
    def nbytes(self) -> int:
        return int(self.kv_k.nbytes + self.kv_v.nbytes)


@dataclasses.dataclass
class _InflightChunk:
    """A dispatched-but-unfetched decode chunk (double buffering)."""

    tokens: Any                              # device array [B, K]
    k: int
    slots: list[tuple[int, "Request"]]       # (slot, request) at dispatch time


def bucket_length(n: int, buckets: tuple[int, ...] = PREFILL_BUCKETS) -> int:
    for b in buckets:
        if n <= b:
            return b
    # Beyond the largest bucket: round up to a multiple of it (rare path;
    # still a bounded compile cache because lengths are multiples of the
    # largest bucket).
    last = buckets[-1]
    return ((n + last - 1) // last) * last


@sanitize.guard_class
class ServingEngine:
    """Slot-based continuous-batching engine over a jitted Llama.

    Thread model: callers enqueue via :meth:`submit`; a single engine thread
    (or the caller via :meth:`step`) drives prefill+decode. One engine owns
    its params/cache; run one engine per model cell. ``_lock`` guards the
    admission state (``_pending_n``/``_next_id``/``_requests``/
    ``last_progress``/``_running``) and doubles as the ``_work`` condition's
    lock — the engine loop sleeps on ``_work`` when idle and submit/stop
    notify it. Under ``KUKEON_SANITIZE=1`` the lock is a kukesan recording
    proxy (hot: blocking calls while holding it are findings) and this
    class's guarded-by contract is enforced on every attribute write.
    """

    def __init__(
        self,
        cfg: llama.LlamaConfig,
        params: Any,
        mesh: Mesh,
        *,
        num_slots: int = 8,
        max_seq_len: int | None = None,
        eos_ids: tuple[int, ...] = (),
        decode_chunk: int | None = None,
        seed: int = 0,
        int8_pallas: bool | None = None,
        kv_cache_int8: bool | None = None,
        async_load: bool = False,
        forward_fn=None,
        param_specs=None,
        prefix_cache_size: int = 8,
        prefix_cache_bytes: int = 2 << 30,
        prefill_buckets: tuple[int, ...] | None = None,
        model_name: str | None = None,
        max_pending: int | None = None,
        registry: Registry | None = None,
        trace_capacity: int = 512,
        kv_page_tokens: int | None = None,
        kv_pool_pages: int | None = None,
        kv_shard: bool | None = None,
    ):
        # Model pluggability: any forward with llama.forward's signature
        # ((params, cfg, tokens, positions, cache) -> (logits, cache')) and
        # the shared KVCache layout serves through this engine —
        # models/moe.py is the second family. ``param_specs`` supplies the
        # matching PartitionSpec tree (default: the Llama specs).
        self._forward = forward_fn or llama.forward
        self._param_specs = param_specs
        # Streamed checkpoint boot (models/checkpoints.CheckpointStream,
        # duck-typed on .abstract_params): the constructor sees only the
        # manifest-derived abstract tree — shardings and _abstract_params
        # come from shapes alone, so precompile() can start before any
        # tensor byte is read — while the async_load thread drains the
        # stream leaf-by-leaf through the counted _upload seam.
        self._ckpt_stream = (params if hasattr(params, "abstract_params")
                             else None)
        ptree = (self._ckpt_stream.abstract_params
                 if self._ckpt_stream is not None else params)
        # Forwards that accept ``logit_positions`` let prefill compute the
        # LM head at ONE position instead of all S bucket rows — at 8B
        # shapes that removes a [S, 128k] f32 logits tensor (and its S×H×V
        # matmul) from every prefill, work that otherwise stalls decode.
        import inspect

        try:
            self._fwd_logit_positions = (
                "logit_positions" in inspect.signature(self._forward).parameters
            )
        except (TypeError, ValueError):
            self._fwd_logit_positions = False

        # Tuning profile: levers not pinned by the caller fall back to the
        # persisted autotune winner for this (model, backend, chip-count),
        # then to defaults. bench.py --autotune writes the profile; a stale
        # or missing one silently degrades to defaults (serving/tuning.py).
        self.tune: "Any | None" = None
        if model_name and (decode_chunk is None or kv_cache_int8 is None
                           or prefill_buckets is None
                           or kv_page_tokens is None or kv_shard is None):
            from kukeon_tpu.serving import tuning

            self.tune = tuning.load(
                model_name, jax.default_backend(),
                mesh.size if mesh is not None else 0,
            )
        if self.tune is not None:
            if decode_chunk is None:
                decode_chunk = self.tune.decode_chunk
            if kv_cache_int8 is None:
                kv_cache_int8 = self.tune.kv_cache_int8
            if prefill_buckets is None:
                prefill_buckets = self.tune.prefill_buckets
            # kv_page_tokens: None = let the profile decide, 0 = force the
            # legacy contiguous layout, > 0 = paged with that page size.
            if kv_page_tokens is None:
                kv_page_tokens = self.tune.kv_page_tokens
            # kv_shard: None = profile (then the divisibility default),
            # False = replicate the KV cache even on a sharded mesh.
            if kv_shard is None:
                kv_shard = self.tune.kv_shard
        decode_chunk = 16 if decode_chunk is None else decode_chunk
        kv_cache_int8 = bool(kv_cache_int8)
        self.model_name = model_name
        self.prefill_buckets = (
            tuple(sorted({int(b) for b in prefill_buckets}))
            if prefill_buckets else PREFILL_BUCKETS
        )
        # int8_pallas=None -> auto: route quantized decode matmuls through
        # the Pallas kernel on a single-chip TPU mesh when the operator opts
        # in (KUKEON_INT8_PALLAS=1). Microbenchmarks on v5e measured the
        # kernel at parity with XLA 0.9's dequant-fused dot (both at the
        # HBM roof), so the default stays on the XLA path; the env knob
        # exists for XLA versions whose fusion regresses. Multi-chip meshes
        # always keep XLA's dot: GSPMD partitions it, while a pallas_call
        # would force all-gathers of the sharded weights. Explicit
        # True/False is authoritative either way — False must clear a flag
        # already set on cfg.
        if int8_pallas is None:
            import os as _os

            env_wants = (
                _os.environ.get("KUKEON_INT8_PALLAS", "").lower()
                in ("1", "true", "yes", "on")
                and jax.default_backend() == "tpu"
                and llama._is_q(ptree.get("layers", {}).get("wq"))
            )
            # The mesh guard applies to BOTH triggers: auto mode must clear
            # a pallas-enabled cfg on a multi-chip mesh (per-layer weight
            # all-gathers), not just decline to set it.
            int8_pallas = (
                (cfg.int8_pallas or env_wants)
                and mesh is not None
                and mesh.size == 1
            )
        if cfg.int8_pallas != int8_pallas:
            cfg = dataclasses.replace(cfg, int8_pallas=int8_pallas)
        self.cfg = cfg
        self.mesh = mesh
        # KV-shard lever (autotune sweeps it): None = shard over the mesh's
        # tensor axis when the KV-head count divides it, False = replicate
        # the cache (more HBM, no gather in the attention dots), True =
        # shard — still subject to the divisibility fallback below.
        self.kv_shard = kv_shard
        self.num_slots = num_slots
        self.max_seq_len = max_seq_len or cfg.max_seq_len
        self.eos_ids = set(eos_ids)
        self.decode_chunk = max(1, decode_chunk)
        # Paged KV cache (serving/kv_pages.py): pages of ``kv_page_tokens``
        # rows replace the slot-contiguous [B, S_max] reservation. Shapes
        # stay static — the decode view is always [B, S_max] — but only the
        # pages a request actually uses are allocated, so the pool can be
        # sized well below num_slots * S_max and preemption absorbs the
        # overflow. page size must tile max_seq_len and every usable prefill
        # bucket, or insert-time scatters would split a page across slots.
        self.page_tokens = int(kv_page_tokens or 0)
        self.paged = self.page_tokens > 0
        self._pool: PageAllocator | None = None
        if self.paged:
            pt = self.page_tokens
            if self.max_seq_len % pt:
                raise ValueError(
                    f"kv_page_tokens {pt} must divide max_seq_len "
                    f"{self.max_seq_len}")
            bad = [b for b in self.prefill_buckets
                   if b < self.max_seq_len and b % pt]
            if bad:
                raise ValueError(
                    f"kv_page_tokens {pt} must divide every prefill bucket "
                    f"below max_seq_len; offending buckets: {bad}")
            self.max_pages_per_slot = self.max_seq_len // pt
            self.kv_pool_pages = int(
                kv_pool_pages or num_slots * self.max_pages_per_slot)
            self._pool = PageAllocator(self.kv_pool_pages, pt)
            # Per-page HBM bytes (K + V + scales): what a prefix entry pins
            # against the prefix-cache byte budget in paged mode.
            row = cfg.num_layers * cfg.num_kv_heads * cfg.head_dim
            itemsize = 1 if kv_cache_int8 else np.dtype(cfg.dtype).itemsize
            self._page_bytes = 2 * pt * row * itemsize
            if kv_cache_int8:
                self._page_bytes += (
                    2 * pt * cfg.num_layers * cfg.num_kv_heads * 4)
        else:
            self.max_pages_per_slot = 0
            self.kv_pool_pages = 0
        # int8 KV cache: halves the cache's HBM bytes per decode step (the
        # stream that grows with context length and slot count); dequant is
        # fused into the decode attention dots. Prefill stays full-precision;
        # quantization happens once, at slot insert.
        self.kv_cache_int8 = kv_cache_int8
        self._key = jax.random.key(seed)
        # Transfer-counting seam (the decode roofline contract): every
        # blocking device→host readback goes through _fetch and every
        # host→device array upload through _upload, so tests can assert the
        # decode loop performs ≤1 blocking transfer per chunk instead of
        # guessing from timings. "chunks" counts dispatched decode chunks.
        # *_s accumulate wall time spent blocked in each transfer kind
        # (scraped as kukeon_engine_host_sync_seconds_total).
        self.sync_stats = {"fetches": 0, "uploads": 0, "chunks": 0,
                           "fetch_s": 0.0, "upload_s": 0.0}
        # Streamed-boot upload accounting, separate from sync_stats so the
        # serving-path host-sync budget and the one-off checkpoint transfer
        # never share a ledger (kukeon_checkpoint_load_seconds{stage=upload}
        # reads this; the cell's boot breakdown sums it with the stream's
        # own disk/cast numbers).
        self.load_stats = {"upload_s": 0.0, "bytes": 0, "tensors": 0}

        if mesh is None:
            raise ValueError("ServingEngine requires a mesh (use make_mesh(tensor=1) for one device)")
        # Abstract (shape+sharding) view of the params, available before any
        # byte reaches the device — what precompile() lowers against.
        self._shardings = shd.param_shardings(ptree, mesh, specs=self._param_specs)
        self._abstract_params = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            ptree, self._shardings,
        )
        self._load_exc: Exception | None = None
        self._loaded = sanitize.event("ServingEngine._loaded")
        if async_load:
            # Weight transfer off-thread so cold start can overlap it with
            # precompile(): the boot pays max(transfer, compile), not the
            # sum. On a tunneled chip both are minutes; this matters. With
            # a CheckpointStream the same thread consumes device-ready
            # leaves AS THEY ARRIVE off disk, collapsing the whole boot to
            # max(disk, transfer, compile).
            self.params = None

            def _load():
                try:
                    if self._ckpt_stream is not None:
                        self.params = self._consume_stream(self._ckpt_stream)
                    else:
                        self.params = shd.shard_params(
                            params, mesh, specs=self._param_specs)
                    with set_mesh(mesh):
                        self.state = self._init_state()
                except Exception as e:  # noqa: BLE001 — surfaced by _ensure_loaded
                    self._load_exc = e
                finally:
                    self._loaded.set()

            threading.Thread(target=_load, daemon=True,
                             name="engine-weight-load").start()
        else:
            if self._ckpt_stream is not None:
                self.params = self._consume_stream(self._ckpt_stream)
            else:
                self.params = shd.shard_params(params, mesh,
                                               specs=self._param_specs)
            with set_mesh(mesh):
                self.state = self._init_state()
            self._loaded.set()

        self._requests: dict[int, Request] = {}
        self._slot_req: list[Request | None] = [None] * num_slots
        self._slot_len: list[int] = [0] * num_slots    # host-side cache lengths
        self._inflight: _InflightChunk | None = None
        # Device-resident sampling arrays, re-uploaded only when the slot
        # composition changes (each host->device upload costs a link RT).
        # The dirty flag is set exactly where composition changes (slot
        # insert/release, failure sweep) so steady-state chunks touch no
        # host memory at all — not even a numpy rebuild-and-compare.
        self._sampling_dev: tuple | None = None
        self._sampling_dirty = True
        # Paged block tables: host truth is a [B, max_pages] int32 array
        # (released slots zeroed -> their in-flight writes land in scratch);
        # the device copy re-uploads only when a slot's page list changed —
        # same dirty-flag discipline as the sampling arrays, so steady-state
        # decode chunks still touch no host memory.
        self._bt = (np.zeros((num_slots, self.max_pages_per_slot), np.int32)
                    if self.paged else None)
        self._bt_dev = None
        self._bt_dirty = True
        self._slot_pages: list[list[int]] = [[] for _ in range(num_slots)]
        # Device-side length each slot's dispatched work will have reached
        # (insert length + every dispatched chunk step): what page growth is
        # planned against.
        self._slot_disp: list[int] = [0] * num_slots
        # Preempted requests wait here and are re-admitted BEFORE anything
        # in _pending — a preempted request resumes ahead of new admissions.
        from collections import deque as _deque

        self._resume: "Any" = _deque()
        self._pending: queue.Queue[Request] = queue.Queue()
        self._next_id = 0   # guarded-by: _lock
        self._lock = sanitize.lock("ServingEngine._lock", hot=True)
        # Work signal for the engine loop: notified on submit and stop so
        # the idle loop wakes immediately instead of sleep-polling
        # (KUKE009). Shares _lock — the predicate it waits on
        # (_pending_n, slot occupancy) is _lock-guarded state.
        self._work = sanitize.condition(self._lock,
                                        name="ServingEngine._work")
        self._running = False   # guarded-by: _lock
        self._thread: threading.Thread | None = None
        self.error: Exception | None = None   # last engine-loop failure
        # Admission control: with max_pending set, submit() sheds (raises
        # RejectedError) once that many requests are queued but not yet
        # slotted — bounded memory and bounded queueing delay instead of an
        # unbounded backlog that OOMs or serves nobody within deadline.
        # _pending_n is the exact count of admitted-not-yet-slotted requests
        # (queue.qsize() is wrong during the sweep's drain-and-refill).
        self.max_pending = max_pending
        self._pending_n = 0   # guarded-by: _lock
        self.retry_after_s = 1.0

        # --- observability (obs/) -------------------------------------
        # Per-engine registry by default: tests and multi-engine processes
        # must never cross-pollute; the serving cell injects its own so
        # cell-level and engine-level metrics share one /metrics scrape.
        self.registry = registry or Registry()
        self.tracer = Tracer(capacity=trace_capacity)
        reg = self.registry
        self._m_queue_wait = reg.histogram(
            "kukeon_engine_queue_wait_seconds",
            "Submit -> dequeued-for-a-slot wait.")
        self._m_prefill = reg.histogram(
            "kukeon_engine_prefill_seconds",
            "Prefill dispatch latency by padded prompt bucket.",
            labels=("bucket",))
        self._m_ttft = reg.histogram(
            "kukeon_engine_ttft_seconds",
            "Submit -> first token emitted (time to first token).")
        self._m_itl = reg.histogram(
            "kukeon_engine_inter_token_seconds",
            "Gap between consecutive emitted tokens of one request.")
        self._m_e2e = reg.histogram(
            "kukeon_engine_e2e_seconds",
            "Submit -> terminal event (any outcome).")
        self._m_tokens = reg.counter(
            "kukeon_engine_tokens_total", "Tokens emitted.")
        self._m_requests = reg.counter(
            "kukeon_engine_requests_total",
            "Requests reaching a terminal event, by outcome.",
            labels=("outcome",))
        self._m_shed = reg.counter(
            "kukeon_engine_shed_total",
            "Load-shedding events (rejected = queue full at submit, "
            "timed_out = deadline expired).", labels=("reason",))
        # The PR-2 shed dict is now a registry view (same keys, same reads;
        # kv_exhausted joined with the paged allocator — a request shed
        # because the KV page pool ran dry with nothing reclaimable).
        self.shed_stats = _CounterMapView(
            self._m_shed, "reason", ("rejected", "timed_out", "kv_exhausted"))
        # Paged-KV telemetry. Families are declared in every mode so the
        # scrape schema is stable; a legacy engine reports a 0-page pool.
        reg.gauge("kukeon_kv_pages_total",
                  "Usable KV pool pages (0 = legacy contiguous layout)."
                  ).set(self.kv_pool_pages)
        reg.gauge("kukeon_kv_pages_in_use",
                  "KV pool pages currently allocated.").set_function(
            lambda: float(self._pool.in_use) if self._pool else 0.0)
        reg.gauge("kukeon_kv_prefix_shared_pages",
                  "Distinct pool pages pinned by prefix-cache entries "
                  "(shared read-only across sessions).").set_function(
            self._prefix_shared_pages)
        self._m_preempt = reg.counter(
            "kukeon_preemptions_total",
            "In-flight requests preempted (pages reclaimed, request "
            "requeued ahead of new admissions), by reason.",
            labels=("reason",))
        reg.gauge("kukeon_engine_mesh_chips",
                  "Devices in this engine's serving mesh (1 = single-chip; "
                  "> 1 = tensor-parallel sharded programs and KV pool)."
                  ).set(mesh.size)
        reg.gauge("kukeon_engine_slots_total",
                  "Decode slots in the fixed batch.").set(num_slots)
        reg.gauge("kukeon_engine_slots_free",
                  "Slots with no active request.").set_function(
            lambda: len(self._free_slots()))
        reg.gauge("kukeon_engine_queue_depth",
                  "Requests waiting for a slot (admitted-not-yet-slotted "
                  "plus preempted-awaiting-resume).").set_function(
            lambda: self._pending_n + len(self._resume))
        reg.gauge("kukeon_engine_max_pending",
                  "Admission bound (-1 = unbounded).").set(
            -1 if max_pending is None else max_pending)
        # Transfer/prefix-cache counters surface at scrape time from the
        # live dicts (zero extra work on the decode hot path — the roofline
        # budget in test_decode_host_sync_budget stays untouched). The
        # fault-point family rides along: most fault seams live in this
        # module, so an engine scrape is complete without a cell wrapper.
        reg.register_collector(self._obs_collect)
        reg.register_collector(faults_collector)
        # Device-level telemetry (obs/device.py): HBM gauges read from
        # jax.Device.memory_stats() at scrape time, and compile tracking
        # around the jitted programs — the docstring's "occupancy changes
        # never recompile" promise is a measurable invariant
        # (kukeon_compiles_total{program="decode"} flat after warmup; a
        # tier-1 test asserts it across slot churn).
        reg.register_collector(device_memory_collector)
        self.compiles = CompileTracker(reg)
        # Roofline instruments (obs/profile.py): per-program dispatch
        # timers settled inside the counted _fetch seam (zero new host
        # syncs — the decode budget tests pass with timers armed), and
        # the step flight recorder the cells expose as /v1/timeline.
        self.timers = ProgramTimers(reg)
        self.recorder = FlightRecorder(registry=reg)
        # Step-local counters the flight recorder snapshots at the end of
        # each working step (driver thread only — no lock needed).
        self._step_tokens = 0
        self._step_preempts = 0
        # Progress heartbeat for the TPU watchdog: bumped on submit and on
        # every step() that did work. A wedged runtime blocks the driver
        # inside a device call, so this goes stale while work is queued —
        # exactly the signal stalled_s() exposes.
        self.last_progress = time.monotonic()   # guarded-by: _lock

        # Prefix cache: prefix_id -> stored prompt KV (LRU, driver-thread
        # only). Agent sessions re-send a large shared/growing context with
        # every request; reusing its KV turns an O(context) prefill into an
        # O(new tokens) one. Bounded by BOTH entry count and device bytes —
        # HBM is the constrained resource (one 8B entry at 8k context is
        # ~1 GiB of K+V), so the byte budget is what prevents an OOM.
        from collections import OrderedDict

        self._prefix_cache: "OrderedDict[str, _CachedPrefix]" = OrderedDict()
        self._prefix_cache_size = max(0, prefix_cache_size)
        self._prefix_cache_bytes = max(0, prefix_cache_bytes)
        self.prefix_hits = 0
        self.prefix_misses = 0

        self._build_programs()

    # --- jitted programs ---------------------------------------------------

    def _cache_shardings(self) -> tuple[NamedSharding, NamedSharding]:
        """(k/v sharding, scale sharding) for the decode cache."""
        spec = shd.kv_cache_spec()
        tensor_size = self.mesh.shape.get(shd.AXIS_TENSOR, 1)
        if (self.kv_shard is False
                or self.cfg.num_kv_heads % max(tensor_size, 1)):
            # Replicate the cache when the tuner says so or when the KV
            # heads don't divide the tensor axis (correct, just more HBM)
            # instead of failing device_put.
            spec = PartitionSpec()
        # Scales [L, B, S, KV] shard like k/v minus the head_dim axis.
        return (NamedSharding(self.mesh, spec),
                NamedSharding(self.mesh, PartitionSpec(*spec[:4])))

    def _state_shardings(self) -> DecodeState:
        """NamedSharding mirror of DecodeState — the jitted programs'
        explicit in/out sharding tree. The KV pool (legacy slots or paged
        pool alike) lives over the mesh's tensor axis on its kv-head dim;
        everything host-logical — per-slot lengths, last tokens, active
        flags — is replicated, because the host block table / slot map is
        the source of truth and every chip must see all of it."""
        kv_sh, sc_sh = self._cache_shardings()
        repl = NamedSharding(self.mesh, PartitionSpec())
        cache = llama.KVCache(
            k=kv_sh, v=kv_sh, lengths=repl,
            k_scale=sc_sh if self.kv_cache_int8 else None,
            v_scale=sc_sh if self.kv_cache_int8 else None,
        )
        return DecodeState(cache=cache, tokens=repl, active=repl)

    def _init_state(self) -> DecodeState:
        if self.paged:
            # Pool layout: page axis where the legacy cache has its slot
            # axis ([L, P, page_tokens, KV, D]); lengths stay per-SLOT [B]
            # (the pool has no per-page length — the block table says which
            # pages a slot's logical [0, S_max) range maps to). Page 0 is
            # the scratch page (kv_pages.SCRATCH_PAGE).
            cache = llama.KVCache.create(
                self.cfg, self.kv_pool_pages + 1, self.page_tokens,
                quantized=self.kv_cache_int8,
            )
            cache = llama.KVCache(
                k=cache.k, v=cache.v,
                lengths=jnp.zeros((self.num_slots,), jnp.int32),
                k_scale=cache.k_scale, v_scale=cache.v_scale,
            )
        else:
            cache = llama.KVCache.create(
                self.cfg, self.num_slots, self.max_seq_len,
                quantized=self.kv_cache_int8,
            )
        kv_sharding, sc_sharding = self._cache_shardings()
        cache = llama.KVCache(
            k=jax.device_put(cache.k, kv_sharding),
            v=jax.device_put(cache.v, kv_sharding),
            lengths=cache.lengths,
            k_scale=(jax.device_put(cache.k_scale, sc_sharding)
                     if cache.k_scale is not None else None),
            v_scale=(jax.device_put(cache.v_scale, sc_sharding)
                     if cache.v_scale is not None else None),
        )
        return DecodeState(
            cache=cache,
            tokens=jnp.zeros((self.num_slots,), jnp.int32),
            active=jnp.zeros((self.num_slots,), bool),
        )

    def _build_programs(self):
        cfg = self.cfg
        fwd = self._forward
        last_pos_ok = self._fwd_logit_positions

        def last_logits(params, tokens, positions, cache, length):
            """(last-position logits [V], cache') — via the forward's
            single-position LM head when it has one (prefill then never
            materializes the [S_bucket, V] f32 logits block), else by
            slicing the full logits."""
            if last_pos_ok:
                logits, cache = fwd(
                    params, cfg, tokens, positions, cache,
                    logit_positions=jnp.reshape(length - 1, (1,)),
                )
                return logits[0, 0], cache
            logits, cache = fwd(params, cfg, tokens, positions, cache)
            last = jax.lax.dynamic_index_in_dim(
                logits[0], length - 1, keepdims=False)
            return last, cache

        def prefill(params, tokens, length, key, temp, top_k, top_p):
            """tokens [1, S_bucket] -> (first sampled token, kv block)."""
            S = tokens.shape[1]
            positions = jnp.arange(S, dtype=jnp.int32)[None, :]
            cache = llama.KVCache.create(cfg, 1, S)
            last, cache = last_logits(params, tokens, positions, cache, length)
            first = sample_per_slot(
                last[None, :], key, temp[None], top_k[None], top_p[None]
            )[0]
            return first, cache.k, cache.v

        def prefill_ext(params, kv_k, kv_v, plen, tokens, length, key,
                        temp, top_k, top_p):
            """Prefill a suffix against a pre-seeded prefix KV block.

            kv_k/kv_v: [L, 1, Pb, KV, D] stored prefix (Pb bucketed, first
            ``plen`` rows valid); tokens: [1, S_tail] at positions
            plen..plen+S_tail-1. The tail's K/V overwrite rows starting at
            plen; rows past plen+length are masked by kv_length. Returns
            (first sampled token, full kv block [L, 1, Pb+S_tail, ...])."""
            S = tokens.shape[1]
            Pb = kv_k.shape[2]
            base = llama.KVCache.create(cfg, 1, Pb + S)
            cache = llama.KVCache(
                k=jax.lax.dynamic_update_slice(base.k, kv_k, (0, 0, 0, 0, 0)),
                v=jax.lax.dynamic_update_slice(base.v, kv_v, (0, 0, 0, 0, 0)),
                lengths=jnp.full((1,), plen, jnp.int32),
            )
            positions = plen + jnp.arange(S, dtype=jnp.int32)[None, :]
            last, cache = last_logits(params, tokens, positions, cache, length)
            first = sample_per_slot(
                last[None, :], key, temp[None], top_k[None], top_p[None]
            )[0]
            # Re-bucket the output block to a CANONICAL shape inside the
            # program (shapes are static at trace time): without this, a
            # growing conversation would mint a new (Pb, S) pair — and a
            # fresh full-model compile — every turn, and an eager reshape
            # on a GSPMD-sharded output can hit unparseable named-sharding
            # conversions. Canonical shapes keep the (Pb, S_tail) compile
            # set small and shared with the miss path's insert shapes.
            out_S = min(self._bucket(Pb + S), self.max_seq_len)
            out_k, out_v = cache.k, cache.v
            if Pb + S > out_S:
                out_k = out_k[:, :, :out_S]
                out_v = out_v[:, :, :out_S]
            elif Pb + S < out_S:
                pad = [(0, 0), (0, 0), (0, out_S - (Pb + S)), (0, 0), (0, 0)]
                out_k = jnp.pad(out_k, pad)
                out_v = jnp.pad(out_v, pad)
            return first, out_k, out_v

        def insert(state: DecodeState, kv_k, kv_v, length, slot, token):
            """Copy a prefill's KV block into ``slot`` and activate it.

            Prefill produces full-precision K/V (its self-attention is
            exact); a quantized state cache quantizes the block here, once,
            as it lands in the slot."""
            ks = vs = None
            if state.cache.quantized:
                kv_k, ks = llama.quantize_kv(kv_k)   # [L, 1, S, KV(, D)]
                kv_v, vs = llama.quantize_kv(kv_v)
            k = jax.lax.dynamic_update_slice(state.cache.k, kv_k, (0, slot, 0, 0, 0))
            v = jax.lax.dynamic_update_slice(state.cache.v, kv_v, (0, slot, 0, 0, 0))
            cache = llama.KVCache(
                k=k, v=v, lengths=state.cache.lengths.at[slot].set(length),
                k_scale=(jax.lax.dynamic_update_slice(
                    state.cache.k_scale, ks, (0, slot, 0, 0))
                    if ks is not None else state.cache.k_scale),
                v_scale=(jax.lax.dynamic_update_slice(
                    state.cache.v_scale, vs, (0, slot, 0, 0))
                    if vs is not None else state.cache.v_scale),
            )
            return DecodeState(
                cache=cache,
                tokens=state.tokens.at[slot].set(token),
                active=state.active.at[slot].set(True),
            )

        def decode_chunk_fn(params, state: DecodeState, key, temps, top_ks, top_ps, n_steps):
            """K decode steps in one on-device scan -> tokens [B, K].

            Sampling parameters are dynamic per-slot arrays, so any mix of
            greedy/temperature/top-k/top-p requests shares this one program.
            """

            def body(carry, _):
                state, key = carry
                tokens = state.tokens[:, None]
                lengths_before = state.cache.lengths
                positions = lengths_before[:, None]
                logits, cache = fwd(
                    params, cfg, tokens, positions, state.cache
                )
                # Inactive slots must not advance their cache length.
                cache = dataclasses.replace(
                    cache,
                    lengths=jnp.where(state.active, cache.lengths, lengths_before),
                )
                key, k1 = jax.random.split(key)
                next_tokens = sample_per_slot(logits[:, 0, :], k1, temps, top_ks, top_ps)
                next_tokens = jnp.where(state.active, next_tokens, state.tokens)
                new_state = DecodeState(
                    cache=cache, tokens=next_tokens, active=state.active
                )
                return (new_state, key), next_tokens

            (state, _), toks = jax.lax.scan(body, (state, key), length=n_steps)
            return state, toks.T  # [B, K]

        # --- paged variants (block-table gather/scatter) ------------------
        # The pool is [L, P, pt, KV, D]; a slot's logical [0, S_max) range
        # is the concatenation of its block-table pages. All three programs
        # keep static shapes (the block table is always [B, max_pages]), so
        # occupancy churn and page churn never recompile.
        pt_sz = self.page_tokens
        B_slots = self.num_slots
        S_max = self.max_seq_len

        def gather_block(pool_k, pool_v, pool_ks, pool_vs, page_ids):
            """Pool pages -> one dense full-precision block [L, 1, n*pt,
            KV, D] (the prefix-extension prefill's input). Scratch-padded
            page_ids gather garbage rows that the consumer masks by length;
            a quantized pool is dequantized here (f32 product, cast down —
            the same recipe the fused decode path applies)."""
            k = pool_k[:, page_ids]          # [L, n, pt, KV, D]
            v = pool_v[:, page_ids]
            L, n = k.shape[0], page_ids.shape[0]
            k = k.reshape(L, 1, n * pt_sz, *k.shape[3:])
            v = v.reshape(L, 1, n * pt_sz, *v.shape[3:])
            if pool_ks is not None:
                ks = pool_ks[:, page_ids].reshape(L, 1, n * pt_sz, -1)
                vs = pool_vs[:, page_ids].reshape(L, 1, n * pt_sz, -1)
                k = (k.astype(jnp.float32)
                     * ks[..., None].astype(jnp.float32)).astype(cfg.dtype)
                v = (v.astype(jnp.float32)
                     * vs[..., None].astype(jnp.float32)).astype(cfg.dtype)
            return k, v

        def insert_paged(state: DecodeState, kv_k, kv_v, length, page_ids,
                         slot, token):
            """Scatter a prefill's [L, 1, Sb, KV, D] block into the pool by
            page index and activate ``slot``.

            page_ids[i] is the pool destination of block rows
            [i*pt, (i+1)*pt) — the host passes SCRATCH_PAGE for pages it
            must not write (shared prefix pages stay read-only, bucket
            padding goes nowhere), so one compiled program per bucket covers
            every share/pad combination."""
            ks = vs = None
            if state.cache.quantized:
                kv_k, ks = llama.quantize_kv(kv_k)
                kv_v, vs = llama.quantize_kv(kv_v)
            L = kv_k.shape[0]
            nb = page_ids.shape[0]
            cache = state.cache
            new_k = cache.k.at[:, page_ids].set(
                kv_k.reshape(L, nb, pt_sz, *kv_k.shape[3:]))
            new_v = cache.v.at[:, page_ids].set(
                kv_v.reshape(L, nb, pt_sz, *kv_v.shape[3:]))
            k_scale, v_scale = cache.k_scale, cache.v_scale
            if ks is not None:
                k_scale = k_scale.at[:, page_ids].set(
                    ks.reshape(L, nb, pt_sz, -1))
                v_scale = v_scale.at[:, page_ids].set(
                    vs.reshape(L, nb, pt_sz, -1))
            cache = llama.KVCache(
                k=new_k, v=new_v,
                lengths=cache.lengths.at[slot].set(length),
                k_scale=k_scale, v_scale=v_scale,
            )
            return DecodeState(
                cache=cache,
                tokens=state.tokens.at[slot].set(token),
                active=state.active.at[slot].set(True),
            )

        def decode_chunk_paged(params, state: DecodeState, bt, key, temps,
                               top_ks, top_ps, n_steps):
            """K decode steps over the paged pool, dense-view pipelined:
            gather every slot's pages into the [L, B, S_max, KV, D] view
            the model forward already speaks ONCE, run the whole chunk on
            that view (the exact per-step cost of the legacy layout), then
            scatter the chunk's new K/V rows back to their (page, offset)
            homes in one flattened vectorized write. Amortizing the
            gather/scatter over K steps is what keeps the paged layout's
            per-token cost at parity with the contiguous one; the dense
            view is a transient buffer that lives only for the chunk —
            persistent HBM is still just the page pool.

            Inactive slots' lengths never advance, and released slots'
            block tables are zeroed host-side, so their stray write-back
            rows flat-map into the scratch page (duplicate scratch
            destinations are harmless — nobody reads scratch) — never
            into a page that was re-issued to another request."""
            pool = state.cache
            L = pool.k.shape[0]
            start_lengths = pool.lengths
            view_k = pool.k[:, bt].reshape(
                L, B_slots, S_max, *pool.k.shape[3:])
            view_v = pool.v[:, bt].reshape(
                L, B_slots, S_max, *pool.v.shape[3:])
            vks = vvs = None
            if pool.quantized:
                vks = pool.k_scale[:, bt].reshape(L, B_slots, S_max, -1)
                vvs = pool.v_scale[:, bt].reshape(L, B_slots, S_max, -1)
            view = llama.KVCache(k=view_k, v=view_v, lengths=start_lengths,
                                 k_scale=vks, v_scale=vvs)
            vstate = DecodeState(cache=view, tokens=state.tokens,
                                 active=state.active)

            def body(carry, _):
                st, key = carry
                tokens = st.tokens[:, None]
                lengths_before = st.cache.lengths
                positions = lengths_before[:, None]
                logits, cache = fwd(params, cfg, tokens, positions, st.cache)
                # Inactive slots must not advance their cache length.
                cache = dataclasses.replace(
                    cache,
                    lengths=jnp.where(st.active, cache.lengths,
                                      lengths_before),
                )
                key, k1 = jax.random.split(key)
                next_tokens = sample_per_slot(
                    logits[:, 0, :], k1, temps, top_ks, top_ps)
                next_tokens = jnp.where(st.active, next_tokens, st.tokens)
                new_state = DecodeState(cache=cache, tokens=next_tokens,
                                        active=st.active)
                return (new_state, key), next_tokens

            (vstate, _), toks = jax.lax.scan(body, (vstate, key),
                                             length=n_steps)

            # Write-back: row t of slot b (absolute position
            # start_lengths[b] + t) lands at flat pool row
            # bt[b, pos // pt] * pt + pos % pt. Positions are clamped to
            # the view bound for slots frozen near S_max — their zeroed /
            # stale table rows route the write to scratch anyway.
            bidx = jnp.arange(B_slots)
            pos = jnp.minimum(
                start_lengths[:, None] + jnp.arange(n_steps)[None, :],
                S_max - 1,
            )                                                  # [B, K]
            page = bt[bidx[:, None],
                      jnp.minimum(pos // pt_sz, bt.shape[1] - 1)]
            dest = (page * pt_sz + pos % pt_sz).reshape(-1)    # [B*K]
            rows_k = vstate.cache.k[:, bidx[:, None], pos]     # [L, B, K, ...]
            rows_v = vstate.cache.v[:, bidx[:, None], pos]
            pk = pool.k.reshape(L, -1, *pool.k.shape[3:]).at[:, dest].set(
                rows_k.reshape(L, -1, *rows_k.shape[3:])
            ).reshape(pool.k.shape)
            pv = pool.v.reshape(L, -1, *pool.v.shape[3:]).at[:, dest].set(
                rows_v.reshape(L, -1, *rows_v.shape[3:])
            ).reshape(pool.v.shape)
            pks, pvs = pool.k_scale, pool.v_scale
            if pks is not None:
                rows_ks = vstate.cache.k_scale[:, bidx[:, None], pos]
                rows_vs = vstate.cache.v_scale[:, bidx[:, None], pos]
                pks = pks.reshape(L, -1, pks.shape[3]).at[:, dest].set(
                    rows_ks.reshape(L, -1, rows_ks.shape[3])
                ).reshape(pool.k_scale.shape)
                pvs = pvs.reshape(L, -1, pvs.shape[3]).at[:, dest].set(
                    rows_vs.reshape(L, -1, rows_vs.shape[3])
                ).reshape(pool.v_scale.shape)
            new_cache = llama.KVCache(k=pk, v=pv,
                                      lengths=vstate.cache.lengths,
                                      k_scale=pks, v_scale=pvs)
            new_state = DecodeState(cache=new_cache, tokens=vstate.tokens,
                                    active=state.active)
            return new_state, toks.T  # [B, K]

        # Every program dispatches through the compile tracker: a dispatch
        # that grew the jit tracing cache is counted + timed by program
        # (prefill covers both the cold and prefix-extend variants). The
        # wrapper forwards .lower/.compile so precompile() is unchanged.
        #
        # Every jit names explicit in/out shardings (KUKE014): params by
        # the model's PartitionSpec tree, KV blocks and the pool over the
        # mesh's tensor axis (kv-head dim), and everything host-shaped —
        # tokens, lengths, RNG keys, sampling arrays, block tables —
        # replicated. On a 1-chip mesh these degenerate to the one device;
        # on an N-chip mesh they make the layout a statement rather than a
        # GSPMD inference, so the paged pool is *placed* where
        # _init_state put it and donation reuses the sharded buffers.
        ct = self.compiles
        tm = self.timers
        p_sh = self._shardings
        st_sh = self._state_shardings()
        kv_sh, sc_sh = self._cache_shardings()
        repl = NamedSharding(self.mesh, PartitionSpec())
        # Every wrap registers with BOTH seams: the coarse compile label
        # (prefill|insert|decode — bench.py and the compile-flat tests
        # consume that vocabulary, do not change it) and the per-program
        # roofline timer (kukelint KUKE015 requires the timer= keyword).
        self._prefill = ct.wrap(jax.jit(
            prefill,
            in_shardings=(p_sh, repl, repl, repl, repl, repl, repl),
            out_shardings=(repl, kv_sh, kv_sh),
        ), "prefill", timer=tm.track("prefill"))
        self._prefill_ext = ct.wrap(jax.jit(
            prefill_ext,
            in_shardings=(p_sh, kv_sh, kv_sh, repl, repl, repl, repl,
                          repl, repl, repl),
            out_shardings=(repl, kv_sh, kv_sh),
        ), "prefill", timer=tm.track("prefill_ext"))
        self._insert = ct.wrap(jax.jit(
            insert, donate_argnums=(0,),
            in_shardings=(st_sh, kv_sh, kv_sh, repl, repl, repl),
            out_shardings=st_sh,
        ), "insert", timer=tm.track("insert"))
        self._decode_chunk = ct.wrap(jax.jit(
            decode_chunk_fn, static_argnums=(6,), donate_argnums=(1,),
            in_shardings=(p_sh, st_sh, repl, repl, repl, repl),
            out_shardings=(st_sh, repl),
        ), "decode", timer=tm.track("decode_chunk"))
        self._gather_block = ct.wrap(jax.jit(
            gather_block,
            in_shardings=(kv_sh, kv_sh, sc_sh, sc_sh, repl),
            out_shardings=(kv_sh, kv_sh),
        ), "prefill", timer=tm.track("gather_block"))
        self._insert_paged = ct.wrap(jax.jit(
            insert_paged, donate_argnums=(0,),
            in_shardings=(st_sh, kv_sh, kv_sh, repl, repl, repl, repl),
            out_shardings=st_sh,
        ), "insert", timer=tm.track("insert_paged"))
        self._decode_chunk_paged = ct.wrap(jax.jit(
            decode_chunk_paged, static_argnums=(7,), donate_argnums=(1,),
            in_shardings=(p_sh, st_sh, repl, repl, repl, repl, repl),
            out_shardings=(st_sh, repl),
        ), "decode", timer=tm.track("decode_chunk_paged"))

    def _bucket(self, n: int) -> int:
        return bucket_length(n, self.prefill_buckets)

    def _fetch(self, x) -> np.ndarray:
        """Blocking device→host readback, counted and timed (the roofline
        budget is ≤1 per decode chunk — tests/test_serving.py asserts it
        here)."""
        faults.maybe_fail("engine.fetch")
        sanitize.blocking("engine._fetch device transfer")
        t0 = time.monotonic()
        out = np.asarray(x)
        self.sync_stats["fetches"] += 1
        self.sync_stats["fetch_s"] += time.monotonic() - t0
        # Retire pending program-timer marks: device execution is in
        # dispatch order, so everything enqueued before the array we just
        # materialized is complete — the readiness probes below are
        # non-blocking and this stays the budget's ≤1 sync per chunk.
        self.timers.settle()
        return out

    def _upload(self, x, sharding=None):
        """Host→device array upload, counted and timed. ``sharding`` routes
        the upload through a per-leaf sharded device_put — the streamed
        checkpoint path's placement primitive; plain serving-path uploads
        keep the default-device jnp.asarray."""
        faults.maybe_fail("engine.upload")
        sanitize.blocking("engine._upload device transfer")
        t0 = time.monotonic()
        if sharding is None:
            out = jnp.asarray(x)
        else:
            out = jax.device_put(x, sharding)
        self.sync_stats["uploads"] += 1
        self.sync_stats["upload_s"] += time.monotonic() - t0
        return out

    def _consume_stream(self, stream):
        """Drain a CheckpointStream into the device param tree: each leaf
        goes through the counted _upload seam with its own NamedSharding
        the moment its bytes arrive off disk, so tensor i+1's read (the
        stream's reader threads) overlaps tensor i's device transfer.
        Raises the stream's CheckpointStreamError through to _load_exc —
        a half-streamed boot fails clean, it never serves."""
        from kukeon_tpu.models.checkpoints import _walk_tree

        flat_sh = dict(_walk_tree(self._shardings))
        flat: dict[tuple, Any] = {}
        for path, arr in stream:
            t0 = time.monotonic()
            flat[path] = self._upload(arr, sharding=flat_sh[path])
            self.load_stats["upload_s"] += time.monotonic() - t0
            self.load_stats["bytes"] += arr.nbytes
            self.load_stats["tensors"] += 1
        tree: dict = {}
        for path, leaf in flat.items():
            node = tree
            for k in path[:-1]:
                node = node.setdefault(k, {})
            node[path[-1]] = leaf
        return tree

    def _obs_collect(self):
        """Scrape-time counter families sourced from the live dicts the
        hot path already maintains (sync_stats is bumped inside _fetch /
        _upload with no lock; mirroring it here instead of double-counting
        keeps the decode loop's instrumentation overhead at zero)."""
        s = self.sync_stats
        yield ("kukeon_engine_host_sync_total", "counter",
               "Blocking host<->device transfers (fetch = device->host "
               "readback, upload = host->device array).",
               [({"kind": "fetch"}, float(s["fetches"])),
                ({"kind": "upload"}, float(s["uploads"]))])
        yield ("kukeon_engine_host_sync_seconds_total", "counter",
               "Wall time spent blocked in host<->device transfers.",
               [({"kind": "fetch"}, float(s["fetch_s"])),
                ({"kind": "upload"}, float(s["upload_s"]))])
        yield ("kukeon_engine_decode_chunks_total", "counter",
               "Dispatched multi-step decode chunks.",
               [({}, float(s["chunks"]))])
        # Streamed-checkpoint boot pipeline accounting: per-stage wall time
        # (stages OVERLAP — their sum exceeds the load's wall clock by
        # design) and bytes moved. All-zero on a non-streamed boot.
        ls = self.load_stats
        cs = (self._ckpt_stream.stat_snapshot()
              if self._ckpt_stream is not None else {})
        yield ("kukeon_checkpoint_load_bytes_total", "counter",
               "Checkpoint bytes streamed host->device during boot.",
               [({}, float(max(int(cs.get("bytes", 0)), ls["bytes"])))])
        yield ("kukeon_checkpoint_load_seconds", "counter",
               "Streamed checkpoint load wall time by pipeline stage "
               "(disk = reader-thread file reads, cast = host dtype "
               "casts/quantize, upload = sharded device_put). Stages run "
               "concurrently: their sum exceeds the load wall clock.",
               [({"stage": "disk"}, float(cs.get("disk_s", 0.0))),
                ({"stage": "cast"}, float(cs.get("cast_s", 0.0))),
                ({"stage": "upload"}, float(ls["upload_s"]))])
        yield ("kukeon_engine_prefix_cache_total", "counter",
               "Prefix-KV cache lookups by result.",
               [({"result": "hit"}, float(self.prefix_hits)),
                ({"result": "miss"}, float(self.prefix_misses))])
        ss = self.tracer.sample_stats
        yield ("kukeon_trace_tail_sampled_total", "counter",
               "Tail-sampler verdicts on finished trace spans (error/"
               "preempted/retried/slow spans are always kept).",
               [({"decision": "kept"}, float(ss["kept"])),
                ({"decision": "dropped"}, float(ss["dropped"]))])

    def _observe_terminal(self, req: Request, outcome: str) -> None:
        """Record a request's terminal event on every instrument at once:
        e2e histogram, outcome counter, trace span, correlated log line.
        Exactly one terminal per request — callers run on the driver
        thread (or hold the failure path), and Tracer.finish is idempotent
        so a double-fault keeps the first verdict."""
        if req.submitted_at:
            self._m_e2e.observe(
                time.monotonic() - req.submitted_at,
                exemplar=(req.trace.trace_id
                          if req.trace is not None else None))
        self._m_requests.inc(outcome=outcome)
        if req.trace is not None:
            self.tracer.finish(
                req.trace, outcome, tokens=len(req.generated),
                error=(f"{type(req.error).__name__}: {req.error}"
                       if req.error is not None else None),
            )
        _LOG.debug("request %d %s (%d tokens)", req.id, outcome,
                   len(req.generated),
                   extra={"request_id": req.id, "phase": outcome,
                          "trace_id": (req.trace.trace_id
                                       if req.trace is not None else None)})

    def _ensure_loaded(self):
        """Block until the (possibly async) weight transfer finished."""
        if not self._loaded.is_set():
            self._loaded.wait()
        if self._load_exc is not None:
            raise RuntimeError("engine weight load failed") from self._load_exc

    def _abstract_state(self) -> DecodeState:
        """ShapeDtypeStruct mirror of _init_state (no device bytes)."""
        if self.paged:
            shapes = jax.eval_shape(
                lambda: llama.KVCache.create(
                    self.cfg, self.kv_pool_pages + 1, self.page_tokens,
                    quantized=self.kv_cache_int8,
                )
            )
            shapes = llama.KVCache(
                k=shapes.k, v=shapes.v,
                lengths=jax.ShapeDtypeStruct((self.num_slots,), jnp.int32),
                k_scale=shapes.k_scale, v_scale=shapes.v_scale,
            )
        else:
            shapes = jax.eval_shape(
                lambda: llama.KVCache.create(
                    self.cfg, self.num_slots, self.max_seq_len,
                    quantized=self.kv_cache_int8,
                )
            )
        kv_sh, sc_sh = self._cache_shardings()
        repl = NamedSharding(self.mesh, PartitionSpec())

        def sds(x, sh):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)

        cache = llama.KVCache(
            k=sds(shapes.k, kv_sh), v=sds(shapes.v, kv_sh),
            lengths=sds(shapes.lengths, repl),
            k_scale=(sds(shapes.k_scale, sc_sh)
                     if shapes.k_scale is not None else None),
            v_scale=(sds(shapes.v_scale, sc_sh)
                     if shapes.v_scale is not None else None),
        )
        B = self.num_slots
        return DecodeState(
            cache=cache,
            tokens=jax.ShapeDtypeStruct((B,), jnp.int32, sharding=repl),
            active=jax.ShapeDtypeStruct((B,), jnp.bool_, sharding=repl),
        )

    def precompile(self, prompt_lens: tuple[int, ...] = (64,)):
        """AOT-compile the engine's programs from shapes alone — no weights
        needed, so with ``async_load`` this runs WHILE the multi-GB param
        transfer streams in the background and the cold boot pays
        max(transfer, compile) instead of their sum. The compiled
        executables land in the persistent compilation cache; the first
        real dispatch is then a cache hit, not a compile.
        """
        aparams = self._abstract_params
        astate = self._abstract_state()
        cfg = self.cfg
        B = self.num_slots
        key = jax.random.key(0)
        temps = jnp.zeros((B,), jnp.float32)
        top_ks = jnp.zeros((B,), jnp.int32)
        top_ps = jnp.ones((B,), jnp.float32)

        with set_mesh(self.mesh):
            buckets = sorted({
                min(self._bucket(max(1, n)), self.max_seq_len)
                for n in prompt_lens
            })
            for L in buckets:
                tokens = jax.ShapeDtypeStruct((1, L), jnp.int32)
                compiled = self._prefill.lower(
                    aparams, tokens, L // 2, key,
                    jnp.float32(0.0), jnp.int32(0), jnp.float32(1.0),
                ).compile()
                # Static roofline cost at the largest precompiled bucket
                # (the per-dispatch cost the MFU gauges divide by; later
                # iterations overwrite earlier, so the biggest L wins).
                self.timers.note_cost("prefill", compiled)
                kv_shape = (cfg.num_layers, 1, L, cfg.num_kv_heads, cfg.head_dim)
                kv = jax.ShapeDtypeStruct(kv_shape, cfg.dtype)
                if self.paged:
                    ids = jax.ShapeDtypeStruct((L // self.page_tokens,),
                                               jnp.int32)
                    compiled = self._insert_paged.lower(
                        astate, kv, kv, L // 2, ids, 0, jnp.int32(1),
                    ).compile()
                    self.timers.note_cost("insert_paged", compiled)
                else:
                    compiled = self._insert.lower(
                        astate, kv, kv, L // 2, 0, jnp.int32(1),
                    ).compile()
                    self.timers.note_cost("insert", compiled)
            chunk_sizes = {1, 4}
            size = 1
            while size * 4 <= self.decode_chunk:
                size *= 4
                chunk_sizes.add(size)
            bt = jax.ShapeDtypeStruct(
                (B, self.max_pages_per_slot), jnp.int32)
            for k in sorted(chunk_sizes):
                if self.paged:
                    compiled = self._decode_chunk_paged.lower(
                        aparams, astate, bt, key, temps, top_ks, top_ps, k,
                    ).compile()
                    self.timers.note_cost("decode_chunk_paged", compiled)
                else:
                    compiled = self._decode_chunk.lower(
                        aparams, astate, key, temps, top_ks, top_ps, k,
                    ).compile()
                    self.timers.note_cost("decode_chunk", compiled)

    # --- public API --------------------------------------------------------

    def submit(
        self,
        prompt: np.ndarray | list[int],
        sampling: SamplingParams | None = None,
        emit: Callable[[int, bool], None] | None = None,
        prefix_id: str | None = None,
        deadline_s: float | None = None,
        trace_ctx: "Any | None" = None,
        export: bool = False,
        kv_import: "dict | None" = None,
    ) -> Request:
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError("prompt must be a non-empty 1-D token array")
        if prompt.size >= self.max_seq_len:
            raise ValueError(
                f"prompt length {prompt.size} >= engine max_seq_len {self.max_seq_len}"
            )
        if export and kv_import is not None:
            raise ValueError("a request cannot both export and import KV")
        if kv_import is not None and int(kv_import["length"]) != prompt.size:
            raise ValueError(
                f"kv_import length {kv_import['length']} != prompt length "
                f"{prompt.size} — the imported block must cover exactly the "
                "prompt rows")
        if self.paged and not export:
            need = self._pool.pages_for(int(prompt.size) + 1)
            if need > self._pool.num_pages:
                # Even an empty pool could never hold this prompt: fail at
                # submit like the max_seq_len check — waiting would deadlock.
                raise ValueError(
                    f"prompt needs {need} KV pages but the pool holds "
                    f"{self._pool.num_pages} (kv_page_tokens="
                    f"{self.page_tokens})"
                )
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError("deadline_s must be positive")
        now = time.monotonic()
        shed_depth = None
        with self._lock:
            if (self.max_pending is not None
                    and self._pending_n >= self.max_pending):
                shed_depth = self._pending_n
            else:
                req = Request(
                    id=self._next_id, prompt=prompt,
                    sampling=sampling or SamplingParams(),
                    emit=emit, submitted_at=now,
                    prefix_id=prefix_id,
                    deadline=(now + deadline_s)
                    if deadline_s is not None else None,
                    export=export, kv_import=kv_import,
                )
                self._next_id += 1
                self._requests[req.id] = req
                self._pending_n += 1
                self.last_progress = now
        if shed_depth is not None:
            # Shed accounting outside the lock: counter + a zero-length
            # trace span (id -1: the request never earned one) so the shed
            # path is visible in /v1/trace, not just as a counter. The
            # span joins the caller's trace when a context came with the
            # request — a gateway retry's shed hop is part of ONE trace.
            self._m_shed.inc(reason="rejected")
            self._m_requests.inc(outcome="shed")
            self.tracer.finish(
                self.tracer.begin(-1, prompt.size, trace_ctx=trace_ctx),
                "shed")
            raise RejectedError(
                f"pending queue full ({shed_depth}/"
                f"{self.max_pending}); shedding load",
                retry_after_s=self.retry_after_s,
            )
        req.trace = self.tracer.begin(req.id, int(prompt.size),
                                      trace_ctx=trace_ctx)
        self._pending.put(req)
        with self._lock:
            # Wake an idle engine loop parked on the work condition.
            self._work.notify()
        return req

    @property
    def queue_depth(self) -> int:
        """Requests waiting for a slot: fresh admissions plus preempted
        requests parked for resume. The admission bound (max_pending)
        counts only the former — preemption must never cause sheds."""
        return self._pending_n + len(self._resume)

    def stalled_s(self) -> float:
        """Seconds since the engine last made progress WHILE work is
        outstanding; 0.0 when idle (an idle engine is never stalled)."""
        if self._pending_n == 0 and not self._resume and not any(
            r is not None for r in self._slot_req
        ):
            return 0.0
        return max(0.0, time.monotonic() - self.last_progress)

    def generate(self, prompt, sampling: SamplingParams | None = None) -> list[int]:
        """Blocking convenience wrapper: submit + drive until done."""
        req = self.submit(prompt, sampling)
        if self._running:
            req.done.wait()
        else:
            while not req.done.is_set():
                self.step()
        if req.error is not None:
            raise RuntimeError(f"generation failed: {req.error}") from req.error
        return req.generated

    def warmup(self, prompt_len: int, sampling: SamplingParams | None = None):
        """Pre-compile prefill (at prompt_len's bucket), insert, and every
        decode-chunk program, so cold-start cost doesn't hit live traffic.

        Decoding with no active slot is semantically a no-op (inactive slots
        neither advance cache lengths nor change their last token), so the
        chunk programs can be compiled against the live state. Sampling
        parameters are dynamic, so one warmup covers all request mixes.
        """
        self._ensure_loaded()
        sp = sampling or SamplingParams()
        req = self.submit(
            np.ones((max(1, prompt_len),), np.int32),
            dataclasses.replace(sp, max_new_tokens=1),
        )
        while not req.done.is_set():
            self.step()
        # Every chunk size _chunk_size can produce: powers of 4 up to
        # decode_chunk, plus the pending-queue clamp value.
        chunk_sizes = {1, 4}
        size = 1
        while size * 4 <= self.decode_chunk:
            size *= 4
            chunk_sizes.add(size)
        # Through the counted dirty-flag seam (kukelint KUKE002): one
        # _upload of the three sampling arrays, reused across every chunk
        # size, instead of six raw jnp.asarray transfers the budget never
        # saw.
        temps_d, top_ks_d, top_ps_d = self._sampling_dev_arrays()
        with set_mesh(self.mesh):
            for k in sorted(chunk_sizes):
                self._key, k1 = jax.random.split(self._key)
                if self.paged:
                    self.state, _ = self._decode_chunk_paged(
                        self.params, self.state, self._bt_dev_array(), k1,
                        temps_d, top_ks_d, top_ps_d, k,
                    )
                else:
                    self.state, _ = self._decode_chunk(
                        self.params, self.state, k1,
                        temps_d, top_ks_d, top_ps_d, k,
                    )

    def start(self):
        """Run the engine loop on a background thread."""
        with self._lock:
            self._running = True
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="serving-engine"
        )
        self._thread.start()

    def stop(self):
        with self._lock:
            self._running = False
            # Wake an idle loop parked on the work condition NOW; without
            # the notify it would only notice _running on the safety-net
            # wait timeout.
            self._work.notify_all()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    def _idle_locked(self) -> bool:
        """True when the loop has nothing to do (caller holds _lock):
        no admitted-unslotted requests, no preempted requests parked for
        resume, no active slots, no unflushed inflight chunk. Cancelled
        or expiring queued requests keep _pending_n nonzero until swept,
        so the loop never parks while any request still needs a sweep."""
        return (self._pending_n == 0 and not self._resume
                and self._inflight is None
                and all(r is None for r in self._slot_req))

    def _loop(self):
        while self._running:
            try:
                if not self.step():
                    # Idle: park on the work condition instead of
                    # sleep-polling (KUKE009). submit()/stop() notify; the
                    # timeout is a safety net for wake paths that predate
                    # the signal (nothing correctness-bearing relies on
                    # it — a lost notify only costs one timeout).
                    with self._work:
                        if self._running and self._idle_locked():
                            self._work.wait(timeout=0.05)
            except Exception as e:  # noqa: BLE001 — the engine thread must not die silently
                import traceback

                traceback.print_exc()
                self.error = e
                self._fail_all(e)
                # Keep serving: state may be poisoned, so rebuild it.
                try:
                    with set_mesh(self.mesh):
                        self.state = self._init_state()
                    self._slot_req = [None] * self.num_slots
                    self._slot_len = [0] * self.num_slots
                    self._inflight = None
                    self._sampling_dirty = True
                    if self.paged:
                        # The pool device tensor was rebuilt: every page and
                        # every prefix entry pointing into the old one is
                        # void. Start the allocator over.
                        self._pool = PageAllocator(self.kv_pool_pages,
                                                   self.page_tokens)
                        self._slot_pages = [[] for _ in range(self.num_slots)]
                        self._slot_disp = [0] * self.num_slots
                        self._bt[:] = 0
                        self._bt_dirty = True
                        self._prefix_cache.clear()
                except Exception:  # noqa: BLE001
                    with self._lock:
                        self._running = False
                    raise

    def _fail_request(self, req: Request, exc: Exception) -> None:
        """Fail ONE request (terminal emit + done), tolerating a bad sink."""
        req.error = exc
        with self._lock:
            self._requests.pop(req.id, None)
        self._observe_terminal(req, "error")
        if req.emit:
            try:
                req.emit(-1, True)
            except Exception:  # noqa: BLE001 — a bad sink must not stop the sweep
                pass
        req.done.set()

    def _fail_all(self, exc: Exception):
        """Fail every active + pending request so callers don't hang.

        Streaming consumers block on their emit channel, not on ``done`` —
        each one must receive the terminal (-1, True) event or it waits
        forever (same contract as the cancel paths)."""
        for slot, req in list(self._active_requests()):
            self._slot_req[slot] = None
            if self.paged:
                self._pool.unref(self._slot_pages[slot])
                self._slot_pages[slot] = []
                self._slot_disp[slot] = 0
                self._bt[slot, :] = 0
                self._bt_dirty = True
            self._fail_request(req, exc)
        self._sampling_dirty = True
        while self._resume:
            self._fail_request(self._resume.popleft(), exc)
        while True:
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                break
            with self._lock:
                self._pending_n -= 1
            self._fail_request(req, exc)

    # --- engine core -------------------------------------------------------

    def _expired(self, req: Request, now: float | None = None) -> bool:
        return (req.deadline is not None
                and (now if now is not None else time.monotonic())
                >= req.deadline)

    def _sweep_cancelled(self) -> bool:
        """Driver-thread cancellation + deadline expiry: release active
        cancelled/expired slots and complete queued ones NOW — a queued
        cancel (or an already-expired request) must not wait for a slot to
        free before its waiter wakes. Runs once per step, i.e. once per
        decode chunk — that is the deadline-check granularity for active
        requests."""
        did = False
        now = time.monotonic()
        for _slot, req in self._active_requests():
            if req.done.is_set():
                continue
            if req.cancelled:
                self._release_slot(req, cancelled=True)
                did = True
            elif self._expired(req, now):
                self._m_shed.inc(reason="timed_out")
                req.timed_out = True
                req.error = DeadlineExceeded(
                    f"request {req.id} deadline exceeded after "
                    f"{now - req.submitted_at:.2f}s "
                    f"({len(req.generated)} tokens generated)"
                )
                self._release_slot(req, timed_out=True)
                did = True
        # Preempted requests parked for resume observe cancellation and
        # deadlines too — a preempted request must still respect its
        # deadline while it waits for pages.
        if self._resume:
            kept_resume = []
            for req in self._resume:
                if req.cancelled:
                    self._finish_cancelled(req, counted=False)
                    did = True
                elif self._expired(req, now):
                    self._finish_timeout(req, counted=False)
                    did = True
                else:
                    kept_resume.append(req)
            self._resume.clear()
            self._resume.extend(kept_resume)
        # Drain-and-refill: Queue supports no removal. Concurrent submits
        # during the refill just land behind the kept entries.
        kept: list[Request] = []
        while True:
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                break
            if req.cancelled:
                self._finish_cancelled(req)
                did = True
            elif self._expired(req, now):
                self._finish_timeout(req)
                did = True
            else:
                kept.append(req)
        for req in kept:
            self._pending.put(req)
        return did

    def _finish_cancelled(self, req: Request, counted: bool = True) -> None:
        """Complete an unslotted cancelled request (``counted=False`` for
        preempted requests, which already left the admission count)."""
        with self._lock:
            self._requests.pop(req.id, None)
            if counted:
                self._pending_n -= 1
        self._observe_terminal(req, "cancelled")
        if req.emit:
            req.emit(-1, True)
        req.done.set()

    def _finish_timeout(self, req: Request, counted: bool = True) -> None:
        """Complete an unslotted request whose deadline already passed:
        in-band timeout terminal event, no slot consumed (``counted=False``
        for preempted requests — already out of the admission count)."""
        with self._lock:
            self._requests.pop(req.id, None)
            if counted:
                self._pending_n -= 1
        self._m_shed.inc(reason="timed_out")
        req.timed_out = True
        req.error = DeadlineExceeded(
            f"request {req.id} deadline exceeded while queued "
            f"({time.monotonic() - req.submitted_at:.2f}s in queue)"
        )
        self._observe_terminal(req, "timeout")
        if req.emit:
            req.emit(-1, True)
        req.done.set()

    def _pop_waiting(self) -> tuple[Request | None, bool, bool]:
        """(next live request, came-from-resume, swept-any-dead-entries).

        Preempted requests resume BEFORE anything in the pending queue;
        dead entries (cancelled, already expired) are completed on the spot
        so a burst of them never costs a free slot a step each."""
        swept = False
        while self._resume:
            req = self._resume.popleft()
            if req.cancelled:
                self._finish_cancelled(req, counted=False)
                swept = True
            elif self._expired(req):
                self._finish_timeout(req, counted=False)
                swept = True
            else:
                return req, True, swept
        while True:
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                return None, False, swept
            if req.cancelled:
                self._finish_cancelled(req)
                swept = True
            elif self._expired(req):
                self._finish_timeout(req)
                swept = True
            else:
                return req, False, swept

    def _shed_kv_exhausted(self, req: Request, cause: Exception) -> None:
        """Terminal shed for a request the allocator can never serve right
        now (pool dry with nothing in flight to free it — including the
        injected ``kv.alloc`` fault): RejectedError with Retry-After rides
        req.error so HTTP front-ends answer 429, and the emit channel gets
        its terminal event so nobody hangs."""
        self._m_shed.inc(reason="kv_exhausted")
        req.error = RejectedError(
            f"KV page pool exhausted: {cause}",
            retry_after_s=self.retry_after_s,
        )
        with self._lock:
            self._requests.pop(req.id, None)
        self._observe_terminal(req, "shed")
        if req.emit:
            try:
                req.emit(-1, True)
            except Exception:  # noqa: BLE001 — a bad sink must not kill the driver
                pass
        req.done.set()

    def _free_slots(self) -> list[int]:
        return [i for i, r in enumerate(self._slot_req) if r is None]

    def _active_requests(self) -> list[tuple[int, Request]]:
        return [(i, r) for i, r in enumerate(self._slot_req) if r is not None]

    def step(self) -> bool:
        """One scheduler iteration, pipelined for link latency:

          1. dispatch prefill+insert for every free slot with a waiting
             request (device work queued, nothing fetched yet);
          2. dispatch the next decode chunk for the active slots;
          3. fetch + emit the prefills' first tokens (overlaps 2's compute);
          4. fetch + emit the PREVIOUS chunk's tokens (double buffering —
             the block for the chunk dispatched in 2 lands next step).

        Returns True if any work was done.
        """
        self._ensure_loaded()
        # Flight-recorder baselines: sync_stats / timer deltas over this
        # step become the step record's transfer counts and per-program
        # wall times (driver thread only — plain reads, no lock).
        step_t0 = time.monotonic()
        fetches0 = self.sync_stats["fetches"]
        uploads0 = self.sync_stats["uploads"]
        busy0 = self.timers.busy_seconds()
        self._step_tokens = 0
        self._step_preempts = 0
        did_work = self._sweep_cancelled()
        prefills = []
        exports = []
        free = list(self._free_slots())
        while free:
            req, resumed, swept = self._pop_waiting()
            did_work = did_work or swept
            if req is None:
                break
            if not resumed:
                with self._lock:
                    self._pending_n -= 1   # leaving the queue for a slot
                self._m_queue_wait.observe(
                    time.monotonic() - req.submitted_at)
            if req.trace is not None:
                req.trace.event("admitted")
            if req.export:
                # Prefill-only (KV handoff export): no slot, no pages —
                # the loop's free list is untouched, so a prefill cell
                # drains export bursts without decode-slot contention.
                try:
                    exports.append(self._dispatch_prefill_export(req))
                except Exception as e:
                    self._fail_request(req, e)
                    raise
                did_work = True
                continue
            slot = free.pop(0)
            try:
                got = self._dispatch_prefill(req, slot)
                # Import seats emit host-side (the first token came with
                # the block) and return None — nothing to fetch later.
                if got is not None:
                    prefills.append(got)
            except PagePoolExhausted as e:
                # No pages for this prompt right now. If anything is in
                # flight, pages WILL free (requests finish, preemption,
                # prefix eviction) — park the request at the FRONT so it
                # retries next step ahead of everyone. If the engine is
                # otherwise idle, nothing will ever free pages: shed with
                # RejectedError + Retry-After rather than deadlocking.
                req.requeued = True
                if (self._active_requests() or prefills
                        or self._inflight is not None):
                    self._resume.appendleft(req)
                else:
                    self._shed_kv_exhausted(req, e)
                did_work = True
                break
            except Exception as e:
                # The request is out of the queue but not yet slotted: fail
                # it HERE or nobody ever wakes its waiter (_fail_all only
                # sees slots and the queue).
                self._fail_request(req, e)
                raise
            did_work = True

        new_inflight = None
        try:
            if self._active_requests():
                new_inflight = self._dispatch_decode_chunk()
                did_work = True

            if prefills:
                # One stacked fetch for every prefill's first token
                # (per-request int() would pay one link round-trip each);
                # the decode chunk dispatched above is already running
                # behind it on the device.
                with set_mesh(self.mesh):
                    firsts = self._fetch(jnp.stack([f for _, f in prefills]))
                for (req, _), first in zip(prefills, firsts):
                    self._emit(req, int(first))
        except Exception as e:
            # Dispatched-but-unfetched exports hold no slot and sit in no
            # queue, so _fail_all cannot find them — fail them HERE or
            # their waiters hang when this exception unwinds the step.
            for exp in exports:
                self._fail_request(exp[0], e)
            raise

        for exp in exports:
            # Export readbacks happen after the decode dispatch for the
            # same reason as the prefill fetch above: the host-bounce DMA
            # overlaps the chunk already running on the device.
            self._finish_export(*exp)
            did_work = True

        if self._inflight is not None:
            self._flush_inflight()
            did_work = True
        self._inflight = new_inflight
        if did_work:
            self._record_step(step_t0, fetches0, uploads0, busy0,
                              len(prefills), new_inflight)
            # Heartbeat writes stay under the admission lock everywhere
            # (kukelint KUKE005): submit() already updates it locked, and a
            # torn read on stalled_s()'s watchdog path is not worth the
            # nanoseconds an uncontended acquire costs per step.
            with self._lock:
                self.last_progress = time.monotonic()
        return did_work

    def _record_step(self, step_t0: float, fetches0: int, uploads0: int,
                     busy0: dict, prefills: int, inflight) -> None:
        """One flight-recorder record for a step that did work: occupancy,
        chunk size, tokens, transfer deltas, per-program wall-time deltas,
        preemptions, and the trace ids of everything seated — the
        postmortem `kuke timeline` reconstructs from. Driver thread only;
        the recorder's own short lock is the only synchronization."""
        seated = self._active_requests()
        programs = {}
        for name, busy in self.timers.busy_seconds().items():
            dt = busy - busy0.get(name, 0.0)
            if dt > 0.0:
                programs[name] = round(dt, 6)
        self.recorder.record({
            "wall_s": round(time.monotonic() - step_t0, 6),
            "occupancy": len(seated),
            "slots": self.num_slots,
            "queue_depth": self._pending_n + len(self._resume),
            "prefills": prefills,
            "chunk_k": inflight.k if inflight is not None else 0,
            "tokens": self._step_tokens,
            "fetches": self.sync_stats["fetches"] - fetches0,
            "uploads": self.sync_stats["uploads"] - uploads0,
            "preemptions": self._step_preempts,
            "programs": programs,
            "traces": [req.trace.trace_id for _slot, req in seated
                       if req.trace is not None],
        })

    def _prefix_lookup(self, req: Request) -> "_CachedPrefix | None":
        """Stored prefix usable for this request: its tokens must be a
        strict prefix of the prompt (equal would leave nothing to prefill,
        and the stored block carries no logits)."""
        if req.prefix_id is None:
            return None
        e = self._prefix_cache.get(req.prefix_id)
        if (
            e is not None
            and req.prompt.size > e.length
            and np.array_equal(req.prompt[: e.length], e.tokens)
        ):
            self._prefix_cache.move_to_end(req.prefix_id)
            return e
        return None

    def _prefix_store(self, prefix_id: str, prompt: np.ndarray,
                      kv_k, kv_v) -> None:
        if self._prefix_cache_size == 0 or self._prefix_cache_bytes == 0:
            return
        self._prefix_cache[prefix_id] = _CachedPrefix(
            tokens=prompt.copy(),
            kv_k=kv_k, kv_v=kv_v, length=int(prompt.size),
        )
        self._prefix_cache.move_to_end(prefix_id)
        # Evict LRU-first past either bound. An entry that alone exceeds the
        # byte budget evicts itself immediately — caching it would pin more
        # HBM than the operator allowed.
        while self._prefix_cache and (
            len(self._prefix_cache) > self._prefix_cache_size
            or sum(e.nbytes for e in self._prefix_cache.values())
            > self._prefix_cache_bytes
        ):
            self._prefix_cache.popitem(last=False)

    # --- paged prefix cache (shared refcounted pages, no tensor copies) ----

    def _prefix_shared_pages(self) -> float:
        """Distinct pool pages pinned by prefix entries (the scrape-time
        kukeon_kv_prefix_shared_pages gauge)."""
        if not self.paged:
            return 0.0
        pages: set[int] = set()
        for e in self._prefix_cache.values():
            pages.update(e.pages)
        return float(len(pages))

    def _prefix_lookup_paged(self, req: Request,
                             seq: np.ndarray) -> "SharedPrefix | None":
        """Usable stored prefix for ``seq``: its (page-aligned) tokens must
        be a strict prefix — equal would leave nothing to prefill."""
        if req.prefix_id is None:
            return None
        e = self._prefix_cache.get(req.prefix_id)
        if (
            e is not None
            and e.length > 0
            and seq.size > e.length
            and np.array_equal(seq[: e.length], e.tokens)
        ):
            self._prefix_cache.move_to_end(req.prefix_id)
            return e
        return None

    def _prefix_store_paged(self, prefix_id: str, seq: np.ndarray,
                            pages: list[int]) -> None:
        """(Re)point ``prefix_id`` at the slot's prompt pages — a refcount
        bump, not a copy. Only FULL pages are shared: the trailing partial
        page is about to receive the slot's decode writes, and sharing it
        would let one session corrupt another's KV."""
        if self._prefix_cache_size == 0 or self._prefix_cache_bytes == 0:
            return
        full = int(seq.size) // self.page_tokens
        if full == 0:
            return
        entry_pages = list(pages[:full])
        self._pool.ref(entry_pages)
        old = self._prefix_cache.pop(prefix_id, None)
        if old is not None:
            self._pool.unref(old.pages)
        self._prefix_cache[prefix_id] = SharedPrefix(
            tokens=np.asarray(seq[: full * self.page_tokens]).copy(),
            pages=entry_pages,
            length=full * self.page_tokens,
        )
        while self._prefix_cache and (
            len(self._prefix_cache) > self._prefix_cache_size
            or sum(e.nbytes(self._page_bytes)
                   for e in self._prefix_cache.values())
            > self._prefix_cache_bytes
        ):
            _k, e = self._prefix_cache.popitem(last=False)
            self._pool.unref(e.pages)

    def _reclaim_prefix_pages(self, need: int) -> bool:
        """Evict prefix entries LRU-first until ``need`` pages are free (or
        nothing evictable remains); True when the pages materialized. Only
        entries whose pages the cache alone holds are evicted: an entry
        pinned by a live slot would free ZERO pages now (the slot's
        references keep them resident) while losing the shared prefix for
        every admission behind it — strictly worse than leaving it be."""
        while self._pool.free < need and self._prefix_cache:
            victim = None
            for key, e in self._prefix_cache.items():       # LRU order
                if all(self._pool.refcount(p) == 1 for p in e.pages):
                    victim = key
                    break
            if victim is None:
                break
            e = self._prefix_cache.pop(victim)
            self._pool.unref(e.pages)
        return self._pool.free >= need

    def _dispatch_prefill_paged(self, req: Request, slot: int):
        """Paged admission: allocate the prompt's pages, prefill (suffix-
        only over gathered shared pages on a prefix hit), scatter the block
        into the pool by page index, and activate the slot.

        A preempted request re-enters here with ``prompt + generated`` as
        its sequence — its KV was reclaimed, so the whole context re-
        prefills and generation continues where it stopped."""
        faults.maybe_fail("engine.prefill")
        t0 = time.monotonic()
        seq = (req.prompt if not req.generated else
               np.concatenate([req.prompt,
                               np.asarray(req.generated, np.int32)]))
        n = int(seq.size)
        pt = self.page_tokens
        sp = req.sampling
        cached = self._prefix_lookup_paged(req, seq)
        shared = list(cached.pages) if cached is not None else []
        plen = cached.length if cached is not None else 0
        n_total = n // pt + 1            # pages covering positions [0, n]
        n_priv = n_total - len(shared)
        try:
            priv = self._pool.alloc(n_priv)
        except PagePoolExhausted:
            if not self._reclaim_prefix_pages(n_priv):
                raise
            # Eviction may have taken the entry we planned to share from;
            # the refcounts we hold nothing of yet make a clean retry.
            cached = self._prefix_lookup_paged(req, seq)
            shared = list(cached.pages) if cached is not None else []
            plen = cached.length if cached is not None else 0
            n_priv = n_total - len(shared)
            priv = self._pool.alloc(n_priv)
        self._pool.ref(shared)           # the slot now also holds them
        pages = shared + priv
        with set_mesh(self.mesh):
            self._key, k1 = jax.random.split(self._key)
            if cached is not None:
                self.prefix_hits += 1
                # Gather the shared pages into the canonical prefix-bucket
                # block the extension prefill speaks (scratch-padded ids
                # keep one compile per bucket).
                Pb = min(self._bucket(plen), self.max_seq_len)
                gid = np.full((Pb // pt,), SCRATCH_PAGE, np.int32)
                gid[: len(shared)] = shared
                kv_k, kv_v = self._gather_block(
                    self.state.cache.k, self.state.cache.v,
                    self.state.cache.k_scale, self.state.cache.v_scale,
                    self._upload(gid),
                )
                tail = seq[plen:]
                bucket = min(self._bucket(tail.size), self.max_seq_len)
                tokens = np.zeros((1, bucket), np.int32)
                tokens[0, : tail.size] = tail
                first, out_k, out_v = self._prefill_ext(
                    self.params, kv_k, kv_v, plen,
                    self._upload(tokens), tail.size, k1,
                    jnp.float32(sp.temperature), jnp.int32(sp.top_k),
                    jnp.float32(sp.top_p),
                )
            else:
                if req.prefix_id is not None:
                    self.prefix_misses += 1
                bucket = min(self._bucket(n), self.max_seq_len)
                tokens = np.zeros((1, bucket), np.int32)
                tokens[0, :n] = seq
                first, out_k, out_v = self._prefill(
                    self.params, self._upload(tokens), n, k1,
                    jnp.float32(sp.temperature), jnp.int32(sp.top_k),
                    jnp.float32(sp.top_p),
                )
            # Scatter destinations for the block's pages: shared prefix
            # pages and bucket padding redirect to scratch (shared pages
            # are read-only; padding goes nowhere), private prompt pages
            # land in their pool slots.
            out_s = int(out_k.shape[2])
            ids = np.full((out_s // pt,), SCRATCH_PAGE, np.int32)
            prompt_pages = -(-n // pt)   # ceil: pages holding prompt rows
            for i in range(len(shared), prompt_pages):
                ids[i] = pages[i]
            self.state = self._insert_paged(
                self.state, out_k, out_v, n, self._upload(ids), slot, first)
        self._slot_pages[slot] = pages
        self._bt[slot, :] = SCRATCH_PAGE
        self._bt[slot, : len(pages)] = pages
        self._bt_dirty = True
        self._slot_disp[slot] = n
        if req.prefix_id is not None and cached is None:
            # Store only on a miss: a hit entry is already serving this
            # prefix_id, and re-pointing it at THIS session's page-aligned
            # prompt would fold the session's private tail into the entry —
            # poisoning the lookup for every sibling session whose prompt
            # diverges after the genuinely shared part.
            self._prefix_store_paged(req.prefix_id, seq, pages)
        req.slot = slot
        self.timers.note_tokens("prefill", bucket)
        self._m_prefill.observe(time.monotonic() - t0, bucket=str(bucket))
        if req.trace is not None:
            req.trace.event("prefill_dispatched")
        self._slot_req[slot] = req
        self._slot_len[slot] = n + 1
        self._sampling_dirty = True
        return req, first

    def _dispatch_prefill(self, req: Request, slot: int):
        """Queue prefill+insert on device; returns (req, first-token device
        value) to fetch after other dispatches.

        With a prefix-cache hit, only the prompt's new suffix runs through
        the model (an agent session's shared context prefills once); the
        resulting prompt KV is (re)stored under the request's prefix_id
        either way."""
        if req.kv_import is not None and not req.generated:
            # KV handoff import: the prompt's KV arrived from a prefill
            # cell — seat it directly, never re-run prefill. A preempted
            # import re-enters with ``generated`` non-empty and takes the
            # normal re-prefill path below (its imported block is stale by
            # then; local prefill of prompt+generated rebuilds it).
            return self._dispatch_import(req, slot)
        if self.paged:
            return self._dispatch_prefill_paged(req, slot)
        faults.maybe_fail("engine.prefill")
        t0 = time.monotonic()
        n = req.prompt.size
        sp = req.sampling
        cached = self._prefix_lookup(req)
        with set_mesh(self.mesh):
            self._key, k1 = jax.random.split(self._key)
            if cached is not None:
                self.prefix_hits += 1
                tail = req.prompt[cached.length:]
                bucket = min(self._bucket(tail.size), self.max_seq_len)
                tokens = np.zeros((1, bucket), np.int32)
                tokens[0, : tail.size] = tail
                first, kv_k, kv_v = self._prefill_ext(
                    self.params, cached.kv_k, cached.kv_v, cached.length,
                    self._upload(tokens), tail.size, k1,
                    jnp.float32(sp.temperature), jnp.int32(sp.top_k),
                    jnp.float32(sp.top_p),
                )
            else:
                if req.prefix_id is not None:
                    self.prefix_misses += 1
                bucket = min(self._bucket(n), self.max_seq_len)
                tokens = np.zeros((1, bucket), np.int32)
                tokens[0, :n] = req.prompt
                first, kv_k, kv_v = self._prefill(
                    self.params, self._upload(tokens), n, k1,
                    jnp.float32(sp.temperature), jnp.int32(sp.top_k),
                    jnp.float32(sp.top_p),
                )
            if req.prefix_id is not None:
                self._prefix_store(req.prefix_id, req.prompt, kv_k, kv_v)
            self.state = self._insert(self.state, kv_k, kv_v, n, slot, first)
        req.slot = slot
        # Dispatch latency by padded bucket (host-side dispatch + any
        # compile; the device-side wait lands in the TTFT histogram).
        self.timers.note_tokens("prefill", bucket)
        self._m_prefill.observe(time.monotonic() - t0, bucket=str(bucket))
        if req.trace is not None:
            req.trace.event("prefill_dispatched")
        self._slot_req[slot] = req
        self._slot_len[slot] = n + 1   # prompt + the first generated token's kv-to-be
        self._sampling_dirty = True
        return req, first

    # --- disaggregated serving: KV handoff export / import -----------------

    def _dispatch_prefill_export(self, req: Request):
        """Prefill-only dispatch for a KV handoff export (disaggregated
        serving): run the prefill program, never seat a slot or touch the
        page pool — the caller fetches the dense KV block to host in
        :meth:`_finish_export`. Works in both layouts (the cold prefill
        program exists regardless of paging); on a legacy engine the
        prefix cache still participates, so N agent sessions exporting one
        shared context prefill only its suffix."""
        faults.maybe_fail("engine.prefill")
        t0 = time.monotonic()
        n = int(req.prompt.size)
        sp = req.sampling
        cached = None if self.paged else self._prefix_lookup(req)
        with set_mesh(self.mesh):
            self._key, k1 = jax.random.split(self._key)
            if cached is not None:
                self.prefix_hits += 1
                tail = req.prompt[cached.length:]
                bucket = min(self._bucket(tail.size), self.max_seq_len)
                tokens = np.zeros((1, bucket), np.int32)
                tokens[0, : tail.size] = tail
                first, kv_k, kv_v = self._prefill_ext(
                    self.params, cached.kv_k, cached.kv_v, cached.length,
                    self._upload(tokens), tail.size, k1,
                    jnp.float32(sp.temperature), jnp.int32(sp.top_k),
                    jnp.float32(sp.top_p),
                )
            else:
                if req.prefix_id is not None and not self.paged:
                    self.prefix_misses += 1
                bucket = min(self._bucket(n), self.max_seq_len)
                tokens = np.zeros((1, bucket), np.int32)
                tokens[0, :n] = req.prompt
                first, kv_k, kv_v = self._prefill(
                    self.params, self._upload(tokens), n, k1,
                    jnp.float32(sp.temperature), jnp.int32(sp.top_k),
                    jnp.float32(sp.top_p),
                )
            if req.prefix_id is not None and not self.paged:
                self._prefix_store(req.prefix_id, req.prompt, kv_k, kv_v)
        self.timers.note_tokens("prefill", bucket)
        self._m_prefill.observe(time.monotonic() - t0, bucket=str(bucket))
        if req.trace is not None:
            req.trace.event("prefill_dispatched")
        return req, first, kv_k, kv_v, n

    def _finish_export(self, req: Request, first_dev, kv_k, kv_v, n: int):
        """Fetch an export's first token + prompt KV rows to host — both
        through the counted ``_fetch`` seam, so the handoff's transfer cost
        is visible in ``sync_stats`` and on /metrics — and complete the
        request with the payload the serving cell serializes over
        ``/v1/kv/export``."""
        try:
            with set_mesh(self.mesh):
                first = int(self._fetch(first_dev))
                k_host = self._fetch(kv_k[:, :, :n])
                v_host = self._fetch(kv_v[:, :, :n])
        except Exception as e:  # noqa: BLE001 — fail THIS request, keep serving
            self._fail_request(req, e)
            return
        req.export_payload = {
            "token": first, "length": n, "k": k_host, "v": v_host,
            "pageTokens": self.page_tokens,
        }
        if req.trace is not None:
            req.trace.event("kv_exported",
                            bytes=int(k_host.nbytes + v_host.nbytes))
        with self._lock:
            self._requests.pop(req.id, None)
        self._observe_terminal(req, "ok")
        if req.emit:
            try:
                req.emit(first, True)
            except Exception:  # noqa: BLE001 — a bad sink must not kill the driver
                pass
        req.done.set()

    def _dispatch_import(self, req: Request, slot: int):
        """Seat a KV-handoff import directly into a decode slot: upload the
        prefill cell's block through the counted ``_upload`` seam, scatter
        it home with the existing ``insert_paged`` program (page-granular
        alloc, scratch-padded ids — one compile per bucket, shared with the
        local prefill path) or ``insert`` on the legacy layout, then emit
        the imported first token through the normal machinery. Prefill
        never re-runs here — that is the point of the handoff.

        ``PagePoolExhausted`` propagates to step()'s admission handler, so
        an import under pool pressure parks for resume (or sheds 429 when
        idle) exactly like a local prefill."""
        faults.maybe_fail("engine.prefill")
        imp = req.kv_import
        n = int(imp["length"])
        first = int(imp["token"])
        k_np, v_np = imp["k"], imp["v"]
        bucket = min(self._bucket(n), self.max_seq_len)
        want = np.dtype(self.cfg.dtype)

        def to_bucket(block):
            """Pad/trim the exporter's [L, 1, n, KV, D] rows to THIS
            engine's bucket shape and cache dtype (the two cells may run
            different bucket ladders or dtypes)."""
            out = block
            if out.dtype != want:
                out = out.astype(want)
            if out.shape[2] != bucket:
                padded = np.zeros(
                    (out.shape[0], 1, bucket) + out.shape[3:], dtype=want)
                rows = min(n, bucket)
                padded[:, :, :rows] = out[:, :, :rows]
                out = padded
            return out

        if self.paged:
            pt = self.page_tokens
            n_total = n // pt + 1      # pages covering positions [0, n]
            try:
                pages = self._pool.alloc(n_total)
            except PagePoolExhausted:
                if not self._reclaim_prefix_pages(n_total):
                    raise
                pages = self._pool.alloc(n_total)
            with set_mesh(self.mesh):
                ids = np.full((bucket // pt,), SCRATCH_PAGE, np.int32)
                prompt_pages = -(-n // pt)   # ceil: pages holding KV rows
                ids[:prompt_pages] = pages[:prompt_pages]
                self.state = self._insert_paged(
                    self.state, self._upload(to_bucket(k_np)),
                    self._upload(to_bucket(v_np)), n,
                    self._upload(ids), slot, jnp.int32(first))
            self._slot_pages[slot] = pages
            self._bt[slot, :] = SCRATCH_PAGE
            self._bt[slot, : len(pages)] = pages
            self._bt_dirty = True
            self._slot_disp[slot] = n
        else:
            with set_mesh(self.mesh):
                self.state = self._insert(
                    self.state, self._upload(to_bucket(k_np)),
                    self._upload(to_bucket(v_np)), n, slot,
                    jnp.int32(first))
        req.slot = slot
        self._slot_req[slot] = req
        self._slot_len[slot] = n + 1
        self._sampling_dirty = True
        if req.trace is not None:
            req.trace.event("kv_imported",
                            bytes=int(k_np.nbytes + v_np.nbytes),
                            pages=(len(self._slot_pages[slot])
                                   if self.paged else 0))
        # The imported first token flows through the normal emit machinery:
        # TTFT on this engine measures submit -> seated (the import cost),
        # and the finished checks (eos / stop tokens / max_new_tokens /
        # context cap) behave exactly as if this engine had produced the
        # token itself — including an immediate release when it is
        # terminal.
        self._emit(req, first)
        return None

    def _chunk_size(self) -> int:
        """Largest safe K, bounded by decode_chunk and cache capacity.

        A request's max_new_tokens budget deliberately does NOT bound K:
        overshooting a finishing request wastes a few decode steps but keeps
        steady state on one compiled program (the freed slot's cache is reset
        by the next insert, so the overshoot KV is never observed).
        """
        k = self.decode_chunk
        # New requests should not wait for a long chunk to finish — but
        # only when a free slot could actually seat one: with the batch
        # full, the waiting request can't be admitted until someone
        # finishes anyway, and short chunks would just multiply the
        # per-chunk overhead (dispatch, and the paged layout's per-chunk
        # gather/scatter) without buying any admission latency.
        if (not self._pending.empty() or self._resume) and self._free_slots():
            k = min(k, 4)
        # Capacity must count the un-flushed inflight chunk: the device cache
        # is already k_inflight steps ahead of the host's _slot_len.
        inflight_k = self._inflight.k if self._inflight is not None else 0
        for slot, _req in self._active_requests():
            k = min(k, self.max_seq_len - self._slot_len[slot] - inflight_k)
        k = max(1, k)
        # Round down to a power of 4 ({1, 4, 16, ...}) so the compile cache
        # stays tiny and warmup() can pre-compile every variant.
        size = 1
        while size * 4 <= k:
            size *= 4
        return size

    def _slot_sampling_arrays(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        return slot_sampling_arrays(self._active_requests(), self.num_slots)

    def _sampling_dev_arrays(self):
        """Device copies of the per-slot sampling arrays, re-uploaded only
        when the slot->request mapping changed since the last chunk."""
        if self._sampling_dev is None or self._sampling_dirty:
            temps, top_ks, top_ps = self._slot_sampling_arrays()
            self._sampling_dev = (
                self._upload(temps), self._upload(top_ks), self._upload(top_ps)
            )
            self._sampling_dirty = False
        return self._sampling_dev

    def _bt_dev_array(self):
        """Device copy of the block table, re-uploaded only when a slot's
        page list changed (insert/release/preempt/page growth) — the same
        dirty-flag discipline as the sampling arrays, so steady-state decode
        chunks perform no uploads at all."""
        if self._bt_dev is None or self._bt_dirty:
            self._bt_dev = self._upload(self._bt)
            self._bt_dirty = False
        return self._bt_dev

    def _preempt_victim(self, exclude: int) -> int | None:
        """Slot of the lowest-priority preemptable request: latest-submitted
        wins the axe (oldest requests keep their progress), never the slot
        we are allocating for."""
        victim, latest = None, -1.0
        for slot, req in self._active_requests():
            if slot == exclude or req.done.is_set():
                continue
            if req.submitted_at >= latest:
                victim, latest = slot, req.submitted_at
        return victim

    def _preempt_slot(self, slot: int, reason: str = "kv_pressure") -> None:
        """Pause an in-flight request and reclaim its pages: the request
        re-enters the queue AHEAD of new admissions and re-prefills
        prompt+generated when pages free. The inflight chunk was flushed by
        the caller, so every token already decoded for the victim has been
        emitted — nothing is lost but the KV, which re-prefill rebuilds."""
        req = self._slot_req[slot]
        if req is None or req.done.is_set():
            return
        self._m_preempt.inc(reason=reason)
        self._step_preempts += 1
        req.preemptions += 1
        req.requeued = True
        if req.trace is not None:
            req.trace.event("preempted")
        self._slot_req[slot] = None
        self._sampling_dirty = True
        self.state = DecodeState(
            cache=self.state.cache,
            tokens=self.state.tokens,
            active=self.state.active.at[slot].set(False),
        )
        self._pool.unref(self._slot_pages[slot])
        self._slot_pages[slot] = []
        self._slot_disp[slot] = 0
        self._slot_len[slot] = 0
        self._bt[slot, :] = SCRATCH_PAGE
        self._bt_dirty = True
        req.slot = -1
        self._resume.append(req)
        _LOG.debug("request %d preempted (%s), %d tokens so far",
                   req.id, reason, len(req.generated),
                   extra={"request_id": req.id, "phase": "preempted",
                          "trace_id": (req.trace.trace_id
                                       if req.trace is not None else None)})

    def _ensure_decode_pages(self, k: int) -> None:
        """Grow every active slot's block table to cover the next ``k``
        decode steps, reclaiming under pressure in escalating order: flush
        the inflight chunk (a finishing request frees its pages), evict
        prefix-cache entries LRU-first, preempt the lowest-priority other
        request, and — when one lone request simply cannot grow — finish it
        at its current length rather than wedging the engine."""
        for slot, req in self._active_requests():
            # Pressure handling for an earlier slot may have preempted or
            # finished this one mid-loop — skip anything no longer seated.
            if self._slot_req[slot] is not req or req.done.is_set():
                continue
            # Plan k steps ahead, but never past the request's own final
            # length (prompt + its max_new_tokens budget): rows an
            # overshooting chunk writes beyond the block table's last page
            # flat-map to scratch and are discarded with the overshoot
            # tokens, so allocating real pages for them would only
            # manufacture preemption pressure.
            limit = min(self.max_seq_len,
                        int(req.prompt.size) + req.sampling.max_new_tokens)
            need = min(
                self._pool.pages_for(min(self._slot_disp[slot] + k, limit)),
                self.max_pages_per_slot)
            while need > len(self._slot_pages[slot]):
                delta = need - len(self._slot_pages[slot])
                try:
                    got = self._pool.alloc(delta)
                except PagePoolExhausted:
                    if self._inflight is not None:
                        self._flush_inflight()
                        self._inflight = None
                        if req.done.is_set():
                            break       # the flush finished this request
                        continue        # retry: the flush may have freed pages
                    if self._reclaim_prefix_pages(delta):
                        continue
                    victim = self._preempt_victim(exclude=slot)
                    if victim is not None:
                        self._preempt_slot(victim)
                        continue
                    # Last resort: nobody else to reclaim from — finish
                    # this request at the tokens it already has.
                    self._release_slot(req, exhausted=True)
                    break
                base = len(self._slot_pages[slot])
                self._slot_pages[slot].extend(got)
                self._bt[slot, base: base + len(got)] = got
                self._bt_dirty = True

    def _dispatch_decode_chunk(self) -> "_InflightChunk | None":
        faults.maybe_fail("engine.decode")
        k = self._chunk_size()
        if self.paged:
            self._ensure_decode_pages(k)
            if not self._active_requests():
                return None      # pressure handling drained the batch
        temps_d, top_ks_d, top_ps_d = self._sampling_dev_arrays()
        with set_mesh(self.mesh):
            self._key, k1 = jax.random.split(self._key)
            if self.paged:
                bt = self._bt_dev_array()
                self.state, toks = self._decode_chunk_paged(
                    self.params, self.state, bt, k1,
                    temps_d, top_ks_d, top_ps_d, k,
                )
                for slot, _req in self._active_requests():
                    self._slot_disp[slot] += k
            else:
                self.state, toks = self._decode_chunk(
                    self.params, self.state, k1,
                    temps_d, top_ks_d, top_ps_d, k,
                )
        self.sync_stats["chunks"] += 1
        self.timers.note_tokens(
            "decode_chunk_paged" if self.paged else "decode_chunk",
            len(self._active_requests()) * k)
        for _slot, req in self._active_requests():
            if req.trace is not None:
                req.trace.decode_chunks += 1
        # Start the device→host DMA of the token block now: by the time
        # _flush_inflight wants it (after the NEXT chunk is dispatched), the
        # copy has overlapped device compute instead of serializing with it.
        try:
            toks.copy_to_host_async()
        except AttributeError:
            pass
        return _InflightChunk(tokens=toks, k=k, slots=self._active_requests())

    def _flush_inflight(self):
        """Fetch + emit the previously dispatched chunk's token block."""
        chunk = self._inflight
        toks = self._fetch(chunk.tokens)  # [B, K] — single transfer per chunk
        for slot, req in chunk.slots:
            if req.done.is_set():
                continue   # finished meanwhile (overshoot chunk) — discard
            base = self._slot_len[slot]
            for t in range(chunk.k):
                # Per-token length bookkeeping so a request finishing mid-chunk
                # keeps every token generated before the limit.
                self._slot_len[slot] = base + t + 1
                self._emit(req, int(toks[slot, t]))
                if req.done.is_set():
                    break
            else:
                self._slot_len[slot] = base + chunk.k

    def _emit(self, req: Request, token: int):
        now = time.monotonic()
        if not req.generated:
            req.first_token_at = now
            self._m_ttft.observe(
                now - req.submitted_at,
                exemplar=(req.trace.trace_id
                          if req.trace is not None else None))
            if req.trace is not None:
                req.trace.event("first_token")
        elif req.last_token_at:
            self._m_itl.observe(now - req.last_token_at)
        req.last_token_at = now
        self._m_tokens.inc()
        self._step_tokens += 1
        req.generated.append(token)
        finished = (
            token in self.eos_ids
            or token in req.sampling.stop_tokens
            or len(req.generated) >= req.sampling.max_new_tokens
            or self._slot_len[req.slot] >= self.max_seq_len
        )
        if req.emit:
            req.emit(token, finished)
        if finished:
            self._release_slot(req)

    def _release_slot(self, req: Request, cancelled: bool = False,
                      timed_out: bool = False, exhausted: bool = False):
        slot = req.slot
        self._slot_req[slot] = None
        self._sampling_dirty = True
        self.state = DecodeState(
            cache=self.state.cache,
            tokens=self.state.tokens,
            active=self.state.active.at[slot].set(False),
        )
        if self.paged:
            # Page-granular free: the slot's references drop; pages still
            # pinned by a prefix entry (or a sibling session) stay resident,
            # everything else returns to the pool. Zeroing the block-table
            # row points any still-inflight decode write at scratch.
            self._pool.unref(self._slot_pages[slot])
            self._slot_pages[slot] = []
            self._slot_disp[slot] = 0
            self._bt[slot, :] = SCRATCH_PAGE
            self._bt_dirty = True
        with self._lock:
            self._requests.pop(req.id, None)
        self._observe_terminal(
            req, "timeout" if timed_out else
            "cancelled" if cancelled else "ok")
        if (cancelled or timed_out or exhausted) and req.emit:
            # Streaming consumers need a terminal event on their channel;
            # cancellation/expiry (and a pool-exhausted early finish)
            # produces no token, so the sentinel is (-1, True) — a timeout
            # itself travels on req.timed_out.
            req.emit(-1, True)
        req.done.set()
