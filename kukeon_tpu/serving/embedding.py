"""Embedding engine: batched sentence-embedding serving over a BERT encoder.

The encoder-side sibling of :class:`~kukeon_tpu.serving.engine.ServingEngine`
(BASELINE config 5: "Llama-3-8B chat + bge-base embedding cell"). Encoders
have no decode loop, so the engine's whole job is shaping traffic onto the
MXU:

- **Fixed-shape programs**: requests are padded to (batch_size, bucket)
  grids — one compiled program per sequence bucket, never per request mix.
- **Micro-batching**: a burst of N texts runs in ceil(N / batch_size) grid
  dispatches; the padding mask keeps ragged tails exact.
- **Sharded params**: megatron column->row over the mesh's 'tensor' axis
  (parallel.sharding.bert_param_specs); XLA inserts the psums over ICI.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from kukeon_tpu.models import bert
from kukeon_tpu.parallel import sharding as shd
from kukeon_tpu.parallel.mesh import set_mesh

EMBED_BUCKETS = (16, 32, 64, 128, 256, 512)


def bucket_length(n: int, max_len: int) -> int:
    for b in EMBED_BUCKETS:
        if n <= b:
            return min(b, max_len)
    return max_len


class EmbeddingEngine:
    """Batched embed over a jitted BERT; one engine per model cell."""

    def __init__(
        self,
        cfg: bert.BertConfig,
        params,
        mesh: Mesh,
        *,
        batch_size: int = 16,
        pooling: str = "cls",
    ):
        if mesh is None:
            raise ValueError("EmbeddingEngine requires a mesh")
        self.cfg = cfg
        self.mesh = mesh
        self.batch_size = batch_size
        self.pooling = pooling
        self.params = shd.shard_bert_params(params, mesh)

        def embed_fn(params, tokens, mask):
            return bert.embed(params, cfg, tokens, mask, pooling=self.pooling)

        self._embed = jax.jit(embed_fn)

    def warmup(self, lengths: tuple[int, ...] = (64,)) -> None:
        """Pre-compile the grid program for each bucket the lengths hit."""
        for n in lengths:
            b = bucket_length(n, self.cfg.max_position_embeddings)
            tokens = np.zeros((self.batch_size, b), np.int32)
            mask = np.zeros((self.batch_size, b), np.int32)
            mask[:, 0] = 1
            with set_mesh(self.mesh):
                self._embed(self.params, jnp.asarray(tokens), jnp.asarray(mask))

    def embed_batch(self, prompts: list[np.ndarray]) -> np.ndarray:
        """Embed N token sequences -> [N, H] f32 unit vectors."""
        if not prompts:
            return np.zeros((0, self.cfg.hidden_size), np.float32)
        max_pos = self.cfg.max_position_embeddings
        out = np.empty((len(prompts), self.cfg.hidden_size), np.float32)
        order = sorted(range(len(prompts)), key=lambda i: len(prompts[i]))
        for start in range(0, len(order), self.batch_size):
            idx = order[start:start + self.batch_size]
            longest = max(len(prompts[i]) for i in idx)
            if longest > max_pos:
                raise ValueError(
                    f"sequence length {longest} exceeds the encoder's "
                    f"max_position_embeddings {max_pos}"
                )
            b = bucket_length(longest, max_pos)
            tokens = np.zeros((self.batch_size, b), np.int32)
            mask = np.zeros((self.batch_size, b), np.int32)
            for row, i in enumerate(idx):
                p = np.asarray(prompts[i], np.int32)
                tokens[row, : p.size] = p
                mask[row, : p.size] = 1
            # Fully padded rows still flow through softmax: give them one
            # live position so the bias row isn't all -inf.
            for row in range(len(idx), self.batch_size):
                mask[row, 0] = 1
            with set_mesh(self.mesh):
                vecs = np.asarray(
                    self._embed(self.params, jnp.asarray(tokens), jnp.asarray(mask))
                )
            for row, i in enumerate(idx):
                out[i] = vecs[row]
        return out
