"""Dataclass <-> camelCase-dict conversion for wire/YAML types.

The manifest surface uses camelCase keys (``restartPolicy``, ``hostNetwork``)
like the reference's YAML; Python code uses snake_case fields. This module
provides the generic, typing-driven converter so each kind doesn't hand-roll
(de)serialization. Unknown keys are rejected — manifests fail loudly on
typos (the reference's parser does per-kind structural validation;
internal/apply/parser/parser.go:220+).
"""

from __future__ import annotations

import dataclasses
import types as _types
import typing
from typing import Any, TypeVar

from kukeon_tpu.runtime.errors import InvalidArgument

T = TypeVar("T")


def camel(name: str) -> str:
    head, *rest = name.split("_")
    return head + "".join(w.capitalize() for w in rest)


def _unwrap_optional(tp):
    origin = typing.get_origin(tp)
    if origin in (typing.Union, _types.UnionType):
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def to_wire(obj: Any) -> Any:
    """Dataclass tree -> plain dict with camelCase keys; drops None/defaults-empty."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            if v is None:
                continue
            if v == [] or v == {}:
                continue
            out[camel(f.name)] = to_wire(v)
        return out
    if isinstance(obj, list):
        return [to_wire(x) for x in obj]
    if isinstance(obj, dict):
        return {k: to_wire(v) for k, v in obj.items()}
    return obj


def from_wire(cls: type[T], data: Any, context: str = "") -> T:
    """camelCase dict -> dataclass, strict about unknown keys, recursive."""
    if data is None:
        data = {}
    if not isinstance(data, dict):
        raise InvalidArgument(f"{context or cls.__name__}: expected a mapping, got {type(data).__name__}")

    fields = {camel(f.name): f for f in dataclasses.fields(cls)}
    unknown = set(data) - set(fields)
    if unknown:
        raise InvalidArgument(
            f"{context or cls.__name__}: unknown field(s) {sorted(unknown)}; "
            f"known: {sorted(fields)}"
        )

    hints = typing.get_type_hints(cls)
    kwargs = {}
    for key, f in fields.items():
        if key not in data:
            continue
        v = data[key]
        kwargs[f.name] = _coerce(hints[f.name], v, f"{context or cls.__name__}.{key}")
    try:
        return cls(**kwargs)
    except TypeError as e:
        raise InvalidArgument(f"{context or cls.__name__}: {e}") from None


def _coerce(tp, v, ctx: str):
    tp = _unwrap_optional(tp)
    if v is None:
        return None
    origin = typing.get_origin(tp)
    if dataclasses.is_dataclass(tp):
        return from_wire(tp, v, ctx)
    if origin is list:
        (item_tp,) = typing.get_args(tp)
        if not isinstance(v, list):
            raise InvalidArgument(f"{ctx}: expected a list")
        return [_coerce(item_tp, x, f"{ctx}[{i}]") for i, x in enumerate(v)]
    if origin is dict:
        _, val_tp = typing.get_args(tp)
        if not isinstance(v, dict):
            raise InvalidArgument(f"{ctx}: expected a mapping")
        return {k: _coerce(val_tp, x, f"{ctx}.{k}") for k, x in v.items()}
    if tp is float and isinstance(v, int):
        return float(v)
    if tp in (int, str, bool, float) and not isinstance(v, tp):
        raise InvalidArgument(f"{ctx}: expected {tp.__name__}, got {type(v).__name__}")
    return v
