"""Wire/YAML types for every manifest kind (v1beta1 equivalent).

Parity surface with the reference's pkg/api/model/v1beta1 (11 kinds,
consts.go:24-80; ContainerSpec field list container.go:34-237; SpaceSpec
space.go:38-104; Volume volume.go:61-83), re-designed for a TPU-VM host:

- ``Resources.tpu_chips`` is first-class: a container can request N chips;
  the runner's device manager partitions chip visibility per cell the way
  the reference partitions memory/cpu via cgroups (SURVEY.md section 5.8).
- ``CellSpec.model`` declares an in-tree model-serving cell (the JetStream
  analog from BASELINE.json's north star): the runner materializes a
  serving container running kukeon_tpu.serving with the requested chips.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field

API_VERSION = "kukeon.io/v1beta1"
TEAMS_API_VERSION = "kuketeams.io/v1"

KIND_REALM = "Realm"
KIND_SPACE = "Space"
KIND_STACK = "Stack"
KIND_CELL = "Cell"
KIND_CONTAINER = "Container"
KIND_SECRET = "Secret"
KIND_CELL_BLUEPRINT = "CellBlueprint"
KIND_CELL_CONFIG = "CellConfig"
KIND_VOLUME = "Volume"
KIND_SERVER_CONFIGURATION = "ServerConfiguration"
KIND_CLIENT_CONFIGURATION = "ClientConfiguration"

ALL_KINDS = (
    KIND_REALM, KIND_SPACE, KIND_STACK, KIND_CELL, KIND_CONTAINER,
    KIND_SECRET, KIND_CELL_BLUEPRINT, KIND_CELL_CONFIG, KIND_VOLUME,
    KIND_SERVER_CONFIGURATION, KIND_CLIENT_CONFIGURATION,
)

# Apply order: parents before children (reference: documents.go:30).
KIND_APPLY_ORDER = (
    KIND_REALM, KIND_SPACE, KIND_STACK, KIND_VOLUME, KIND_SECRET,
    KIND_CELL_BLUEPRINT, KIND_CELL_CONFIG, KIND_CELL, KIND_CONTAINER,
)


@dataclass
class Metadata:
    name: str = ""
    realm: str | None = None
    space: str | None = None
    stack: str | None = None
    cell: str | None = None
    labels: dict[str, str] = field(default_factory=dict)


# --- container -----------------------------------------------------------


@dataclass
class EnvVar:
    name: str = ""
    value: str = ""


@dataclass
class SecretRef:
    """Mount a scoped Secret; staged read-only at /run/kukeon/secrets/<name>
    (reference: ctr/secrets.go:30-60) and/or exported as env."""

    name: str = ""
    env: str | None = None           # export as this env var
    path: str | None = None          # or stage at this path


@dataclass
class VolumeMount:
    name: str | None = None          # reference to a Volume kind
    host_path: str | None = None     # direct bind (trusted manifests only)
    path: str = ""                   # mount point inside the workload
    read_only: bool = False
    tmpfs: bool = False


@dataclass
class PortSpec:
    port: int = 0
    protocol: str = "tcp"
    name: str | None = None


@dataclass
class RepoSpec:
    """Git repo cloned into the workload before start (kuketty runOn:create
    stages; reference: cmd/kuketty/repos.go)."""

    url: str = ""
    path: str = ""
    ref: str | None = None


@dataclass
class Resources:
    memory: str | None = None        # e.g. "2Gi"
    cpu: float | None = None         # cores
    pids: int | None = None
    tpu_chips: int | None = None     # TPU-native: chips granted to this container


@dataclass
class RestartPolicy:
    policy: str = "never"            # always | on-failure | never
    backoff_seconds: float = 1.0
    max_retries: int | None = None


@dataclass
class TTYSpec:
    prompt: str | None = None
    on_init: list[str] = field(default_factory=list)   # stage commands
    log_file: str | None = None
    log_level: str | None = None


@dataclass
class ContainerSpec:
    name: str = ""
    image: str | None = None         # image-backed (containerd backend) or
    command: list[str] = field(default_factory=list)   # process-backed
    args: list[str] = field(default_factory=list)
    env: list[EnvVar] = field(default_factory=list)
    workdir: str | None = None
    user: str | None = None
    ports: list[PortSpec] = field(default_factory=list)
    volumes: list[VolumeMount] = field(default_factory=list)
    networks: list[str] = field(default_factory=list)
    privileged: bool = False
    host_network: bool = False
    host_pid: bool = False
    read_only_root_filesystem: bool = False
    capabilities: list[str] = field(default_factory=list)
    # reference: ContainerSpec.securityOpts (container.go) / OCI seccomp.
    # Supported: "seccomp=default" (denylist filter) | "seccomp=unconfined".
    security_opts: list[str] = field(default_factory=list)
    devices: list[str] = field(default_factory=list)
    resources: Resources = field(default_factory=Resources)
    secrets: list[SecretRef] = field(default_factory=list)
    repos: list[RepoSpec] = field(default_factory=list)
    restart_policy: RestartPolicy = field(default_factory=RestartPolicy)
    attachable: bool = False
    tty: TTYSpec | None = None


# --- model-serving cell (TPU-native) -------------------------------------


@dataclass
class ModelSpec:
    """In-tree serving cell: the runner materializes a container running the
    kukeon_tpu serving engine with these settings (north-star JetStream
    analog; no reference equivalent — kukeon has no model cells)."""

    model: str = ""                  # e.g. "llama3-8b", "llama3-1b", "tiny"
    # Chips per replica. 1 = single-chip (the classic shape); N > 1 builds
    # an N-chip tensor-parallel serving mesh inside each replica (params +
    # KV pool sharded over the tensor axis). The runner checks at start
    # that N divides the host's chip count so every replica's grant is a
    # whole N-chip slice; validate keeps the static >= 1 floor.
    chips: int = 1
    port: int = 9000
    # Scale-out: N > 1 materializes N serving containers (each granted
    # ``chips`` chips, listening on port+1 .. port+N) plus one gateway
    # container on ``port`` that routes by least queue depth with
    # prefix-id affinity (kukeon_tpu/gateway). The client-facing endpoint
    # is ``port`` either way; replicas=1 keeps the single-engine shape.
    replicas: int = 1
    # SLO-driven autoscaling bounds (runtime/scaler.py): setting
    # ``maxReplicas`` arms the daemon's FleetScaler for this cell — the
    # runner materializes the full port range and chip partition up to the
    # bound, and the scaler moves the ACTIVE replica count between
    # ``minReplicas`` (default 1) and ``maxReplicas`` from windowed SLO
    # burn rate + aggregate queue depth, debounced through the alert
    # engine's pending->firing state machine. ``replicas`` is the initial
    # active count and must sit inside the bounds. Scale-up starts a
    # parked replica on its pre-partitioned chip grant; scale-down drains
    # through the gateway first, so no in-flight request is lost. Unset =
    # the static replica set, byte-identical to before autoscaling.
    min_replicas: int | None = None
    max_replicas: int | None = None
    # Disaggregated prefill/decode serving (FlexNPU-style): "mixed" (the
    # default — every replica serves both phases, byte-identical to the
    # pre-role behavior), or a comma-separated per-replica role list
    # ("prefill,decode,decode", one atom per replica in declaration order)
    # splitting the replica set into a prefill pool and a decode pool
    # behind the same gateway. The gateway then routes /v1/generate as a
    # two-stage KV handoff: prefill pool by queue depth, decode pool by
    # prefix affinity, with page-granular KV transfer between them and
    # graceful fallback to local decode on a prefill-capable replica when
    # the decode pool is unavailable. Roles are policy, not capability —
    # every replica keeps the full engine.
    role: str = "mixed"
    num_slots: int = 8
    max_seq_len: int | None = None
    checkpoint: str | None = None    # orbax checkpoint dir; random-init if None
    dtype: str | None = None
    # int8 KV cache: halves the decode-time cache HBM stream (dequant fused
    # into the attention dots). Weights are governed by ``dtype``; this
    # governs only the per-request KV cache.
    kv_cache_int8: bool = False
    # Paged KV cache (serving/kv_pages.py): > 0 serves from a block-table
    # page pool with pages of this many KV rows instead of reserving
    # numSlots * maxSeqLen contiguous rows per slot — mixed-length agent
    # traffic packs HBM page-granularly, with preemption + requeue under
    # pressure and refcounted prefix sharing. 0 forces the legacy
    # contiguous layout; None defers to the persisted autotune profile.
    kv_page_tokens: int | None = None
    # Admission control (serving resilience): bound on queued-not-yet-
    # slotted requests — past it the cell sheds with 429 + Retry-After
    # instead of growing an unbounded backlog. None = the serving cell's
    # own default; 0 = unbounded (explicit operator opt-out).
    max_pending: int | None = None
    # Default per-request deadline in seconds (a request's own deadlineS
    # wins). Expired requests get an in-band timeout terminal event and
    # free their slot. None/0 = no default deadline.
    deadline_s: float | None = None
    # Serving objectives (obs/slo.py): the cell evaluates availability and
    # TTFT burn rates against these at scrape time and exposes them as
    # kukeon_slo_* on /metrics. sloTtftP95Ms bounds the 95th-percentile
    # time-to-first-token (milliseconds); sloAvailability is the required
    # success fraction (e.g. 0.999). Unset = the cell's loose defaults.
    slo_ttft_p95_ms: float | None = None
    slo_availability: float | None = None
    # Model cells live INSIDE the space network by default: the server binds
    # the cell's bridge IP, in-space agent cells reach it there, and the
    # space's default-deny egress governs its traffic (BASELINE config 4).
    # hostNetwork: true is the spec-visible opt-out for hosts whose TPU
    # runtime plane needs host networking (multi-host pod slices, emulated
    # chips behind a loopback tunnel) — it exempts the cell from the space
    # egress policy, so it must be an explicit manifest decision.
    host_network: bool = False


# --- cell / hierarchy ----------------------------------------------------


@dataclass
class CellSpec:
    containers: list[ContainerSpec] = field(default_factory=list)
    model: ModelSpec | None = None
    auto_delete: bool = False        # reap when root task exits (kuke run --rm)
    ignore_disk_pressure: bool = False


@dataclass
class EgressRule:
    host: str | None = None          # hostname, resolved at apply/reconcile
    cidr: str | None = None
    ports: list[int] = field(default_factory=list)
    # tcp | udp; None = unset (all protocols for a port-less rule, tcp once
    # ports are given). DNS allowlists say `ports: [53], protocol: udp`.
    protocol: str | None = None


@dataclass
class NetworkSpec:
    egress_default: str = "allow"    # allow | deny
    egress_allow: list[EgressRule] = field(default_factory=list)


@dataclass
class SpaceSpec:
    network: NetworkSpec = field(default_factory=NetworkSpec)
    subnet: str | None = None        # auto-allocated from the pool if unset
    container_defaults: ContainerSpec | None = None


@dataclass
class RealmSpec:
    description: str | None = None


@dataclass
class StackSpec:
    description: str | None = None


# --- secrets / volumes ---------------------------------------------------


@dataclass
class SecretSpec:
    data: dict[str, str] = field(default_factory=dict)   # plain values
    # (the store chmods the staged file 0400 root-only, like the reference)


@dataclass
class VolumeSpec:
    reclaim_policy: str = "delete"   # retain | delete (volume.go:61-83)
    size: str | None = None


# --- blueprints / configs ------------------------------------------------


@dataclass
class BlueprintParam:
    name: str = ""
    default: str | None = None
    required: bool = False


@dataclass
class CellBlueprintSpec:
    """Parametrized cell template; ``${param}`` scalars substituted at
    materialization (reference: internal/cellblueprint/params.go:47-174)."""

    params: list[BlueprintParam] = field(default_factory=list)
    cell: CellSpec = field(default_factory=CellSpec)
    name_prefix: str | None = None


@dataclass
class ConfigSecretBinding:
    slot: str = ""                   # secret slot name in the blueprint
    secret: str = ""                 # concrete Secret name


@dataclass
class CellConfigSpec:
    """Binds a CellBlueprint to a concrete cell identity
    (reference: internal/cellconfig/materialize.go:63-317)."""

    blueprint: str = ""
    values: dict[str, str] = field(default_factory=dict)
    secrets: list[ConfigSecretBinding] = field(default_factory=list)
    env: list[EnvVar] = field(default_factory=list)
    cell_name: str | None = None     # deterministic name of the one live cell


# --- configurations ------------------------------------------------------


@dataclass
class ServerConfigurationSpec:
    run_path: str | None = None
    socket: str | None = None
    reconcile_interval_seconds: float | None = None
    subnet_pool: str | None = None
    disk_pressure_warn_pct: float | None = None
    disk_pressure_block_pct: float | None = None
    log_level: str | None = None


@dataclass
class ClientConfigurationSpec:
    socket: str | None = None
    default_realm: str | None = None
    default_space: str | None = None
    default_stack: str | None = None


# --- document envelope ---------------------------------------------------

SPEC_BY_KIND = {
    KIND_REALM: RealmSpec,
    KIND_SPACE: SpaceSpec,
    KIND_STACK: StackSpec,
    KIND_CELL: CellSpec,
    KIND_CONTAINER: ContainerSpec,
    KIND_SECRET: SecretSpec,
    KIND_CELL_BLUEPRINT: CellBlueprintSpec,
    KIND_CELL_CONFIG: CellConfigSpec,
    KIND_VOLUME: VolumeSpec,
    KIND_SERVER_CONFIGURATION: ServerConfigurationSpec,
    KIND_CLIENT_CONFIGURATION: ClientConfigurationSpec,
}


@dataclass
class Document:
    api_version: str = API_VERSION
    kind: str = ""
    metadata: Metadata = field(default_factory=Metadata)
    spec: object = None

    def clone(self) -> "Document":
        return dataclasses.replace(
            self,
            metadata=dataclasses.replace(self.metadata, labels=dict(self.metadata.labels)),
            spec=dataclasses.replace(self.spec) if dataclasses.is_dataclass(self.spec) else self.spec,
        )
