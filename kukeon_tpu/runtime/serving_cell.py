"""Model-serving cell entrypoint: HTTP front-end over the ServingEngine.

The in-tree serving workload the runner materializes for ``CellSpec.model``
(BASELINE north star: "an in-tree JetStream (JAX/XLA) inference cell"). The
runner grants chips via TPU_VISIBLE_DEVICES before exec; this process builds
the mesh over whatever devices JAX exposes and serves:

  GET  /v1/health    -> {"status": "ok", ...}  (the reconciler's health seam)
  GET  /v1/stats     -> slots/queue/throughput counters
  POST /v1/generate  -> {"promptTokens": [...] | "prompt": "text",
                         "maxNewTokens": N, "temperature": T, ...}
                        => {"tokens": [...], "text": "..."}

Tokenization: checkpoint-less engines (random init, dev/e2e) use a byte
tokenizer (id = byte + 1); real deployments pass a HF tokenizer name.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

MODELS = {}
EMBEDDING_MODELS = {}


def _trailing_fffd(s: str) -> int:
    """Length of the run of U+FFFD replacement chars at the end of ``s``
    (the provisional decode of an incomplete multi-byte codepoint)."""
    n = 0
    while n < len(s) and s[-1 - n] == "�":
        n += 1
    return n


_CACHE_DIR: str | None = None   # the versioned dir actually configured


def _enable_compilation_cache() -> None:
    """Persistent XLA compilation cache: the dominant cold-start cost after
    weight load is jit compilation; caching it on disk makes every boot
    after the first (same program shapes) start in seconds. Standard TPU
    serving practice (JetStream does the same).

    The cache dir is keyed by the runtime build (jax version + backend
    platform_version, which embeds the libtpu build stamp): AOT artifacts
    compiled under one libtpu are invalid under another — r4's cold-start
    died to exactly this ("FAILED_PRECONDITION: libtpu version mismatch"
    crash loop off stale cache entries after a libtpu roll). A rolled
    runtime must see an EMPTY cache, never a poisoned one."""
    global _CACHE_DIR
    import hashlib

    import jax

    base = os.environ.get(
        "KUKEON_JAX_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "kukeon-jax"),
    )
    try:
        try:
            import jax.extend

            ver = jax.extend.backend.get_backend().platform_version
        except Exception:  # noqa: BLE001 — version probe must not kill serving
            ver = "unknown"
        key = hashlib.sha256(f"{jax.__version__}|{ver}".encode()).hexdigest()[:12]
        cache_dir = os.path.join(base, key)
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        _CACHE_DIR = cache_dir
    except Exception:  # noqa: BLE001 — cache is an optimization, never fatal
        pass


def _bust_compilation_cache() -> bool:
    """Wipe the configured cache dir; True if there was anything to wipe.
    Last-resort self-heal for a corrupted cache entry that keys identically
    but fails to deserialize (crash-looping forever would be worse than one
    slow recompile)."""
    if not _CACHE_DIR or not os.path.isdir(_CACHE_DIR):
        return False
    import shutil

    had = any(os.scandir(_CACHE_DIR))
    shutil.rmtree(_CACHE_DIR, ignore_errors=True)
    os.makedirs(_CACHE_DIR, exist_ok=True)
    return had


MOE_MODELS = set()


def _register_models():
    from kukeon_tpu.models import bert, llama, moe

    MODELS.update({
        "tiny": llama.llama_tiny,
        "llama3-1b": llama.llama3_1b,
        "llama3-8b": llama.llama3_8b,
        "mixtral-tiny": moe.moe_tiny,
        "mixtral-8x7b": moe.mixtral_8x7b,
    })
    MOE_MODELS.update({"mixtral-tiny", "mixtral-8x7b"})
    EMBEDDING_MODELS.update({
        "bge-base": bert.bge_base,
        "bge-tiny": bert.bge_tiny,
    })


class ServingCell:
    def __init__(self, model: str, *, num_slots: int, max_seq_len: int | None,
                 checkpoint: str | None, dtype: str | None, seed: int = 0,
                 kv_cache_int8: bool | None = None,
                 decode_chunk: int | None = None):
        import jax

        _enable_compilation_cache()

        from kukeon_tpu.models import llama
        from kukeon_tpu.parallel import auto_mesh_shape, make_mesh
        from kukeon_tpu.serving import ServingEngine

        _register_models()
        if model not in MODELS:
            raise SystemExit(
                f"unknown model {model!r}; known: "
                f"{sorted(MODELS) + sorted(EMBEDDING_MODELS)}"
            )
        import dataclasses

        # "int8" quantizes the weights post-load (activations stay bf16);
        # other dtype strings set the activation/weight dtype directly.
        quantize = dtype == "int8"
        cfg = MODELS[model]()
        if dtype and not quantize:
            import jax.numpy as jnp

            cfg = dataclasses.replace(cfg, dtype=getattr(jnp, dtype))
        if max_seq_len:
            cfg = dataclasses.replace(cfg, max_seq_len=max_seq_len)

        n = len(jax.devices())
        shape = auto_mesh_shape(n)
        mesh = make_mesh(data=shape["data"], tensor=shape["tensor"])

        forward_fn = None
        param_specs = None
        if model in MOE_MODELS:
            # MoE family: same engine, moe forward + expert-aware specs.
            # int8-KV is a llama-decode-path feature the MoE forward doesn't
            # have yet — fail loudly rather than serving garbage; an
            # unspecified flag pins False so a tuning profile can never
            # switch it on behind the guard.
            if kv_cache_int8:
                raise SystemExit(
                    f"model {model!r} does not support --kv-cache-int8 yet"
                )
            kv_cache_int8 = False
            from kukeon_tpu.models import hf_convert, moe
            from kukeon_tpu.parallel import moe_specs_for_params

            if checkpoint:
                params, cfg = hf_convert.load_moe_params(
                    checkpoint, dtype=cfg.dtype
                )
                if max_seq_len:
                    cfg = dataclasses.replace(cfg, max_seq_len=max_seq_len)
                if quantize:
                    # Weights-only int8 (router/norms stay high precision);
                    # dequant fuses into attention _mm and expert einsums.
                    params = moe.quantize_params(params)
            elif quantize:
                # Random-init directly in int8 on the host: a mixtral-8x7b
                # bf16 tree (~93 GB) cannot be materialized on-device just
                # to be quantized (same rule as the Llama path).
                params = moe.init_quantized_params_host(cfg, seed)
            else:
                params = moe.init_params(jax.random.key(seed), cfg)
            forward_fn = moe.forward
            param_specs = moe_specs_for_params(params)
        elif checkpoint:
            params, cfg = self._load_checkpoint(checkpoint, cfg, quantize)
        elif quantize:
            # Random-init directly in int8 on the host: an 8B bf16 tree
            # (~16 GB) cannot be materialized on a 16 GB chip just to be
            # quantized (models/llama.py init_quantized_params_host).
            params = llama.init_quantized_params_host(cfg, seed)
        else:
            params = llama.init_params(jax.random.key(seed), cfg)

        self.model_name = model
        self.cfg = cfg
        # async_load: the multi-GB weight transfer streams in the background
        # while warmup()'s precompile pass AOT-compiles the programs — cold
        # start pays max(transfer, compile) instead of their sum.
        # model_name routes the engine to the persisted autotune profile
        # (bench.py --autotune): levers the operator left unset
        # (decode_chunk/kv_cache_int8 None) boot at the swept winner for
        # this model+backend+chip-count.
        self.engine = ServingEngine(
            cfg, params, mesh, num_slots=num_slots,
            max_seq_len=max_seq_len or min(cfg.max_seq_len, 4096),
            kv_cache_int8=kv_cache_int8, async_load=True,
            forward_fn=forward_fn, param_specs=param_specs,
            decode_chunk=decode_chunk, model_name=model,
        )
        from kukeon_tpu.serving.tokenizer import load_tokenizer

        self.tokenizer = load_tokenizer(checkpoint)
        self.started_at = time.time()
        self.total_tokens = 0
        self._stats_lock = threading.Lock()

    @staticmethod
    def _load_checkpoint(path: str, cfg, quantize: bool = False):
        """(params, cfg) from, in precedence order:

        - a kukeon int8 quantized checkpoint (kukeon_quant.json manifest) —
          the cold-start fast path: int8 streams straight to the device with
          zero quantization work;
        - an HF safetensors directory (config.json + *.safetensors, the hub
          layout) — streamed and host-quantized when ``quantize`` (an 8B
          bf16 tree cannot be materialized on a 16 GB chip);
        - an orbax checkpoint path.
        """
        import os

        import jax

        from kukeon_tpu.models import checkpoints, llama

        if checkpoints.is_quantized_checkpoint(path):
            return checkpoints.load_quantized(path, dtype=cfg.dtype)
        if os.path.isdir(path) and os.path.exists(os.path.join(path, "config.json")):
            from kukeon_tpu.models import hf_convert

            if quantize:
                return hf_convert.load_params_quantized(path, dtype=cfg.dtype)
            return hf_convert.load_params(path, dtype=cfg.dtype)
        import orbax.checkpoint as ocp

        abstract = jax.eval_shape(lambda k: llama.init_params(k, cfg), jax.random.key(0))
        ckptr = ocp.StandardCheckpointer()
        params = ckptr.restore(path, abstract)
        if quantize:
            params = llama.quantize_params(params)
        return params, cfg

    def warmup(self, prompt_len: int = 64):
        # Compile first (needs shapes only — overlaps the async weight
        # transfer), then run the real warmup pass (needs the weights).
        self.engine.precompile((prompt_len,))
        self.engine.warmup(prompt_len)

    def _parse_generate(self, req: dict):
        from kukeon_tpu.serving import SamplingParams

        if "promptTokens" in req:
            prompt = np.asarray(req["promptTokens"], np.int32)
        elif "prompt" in req:
            prompt = np.asarray(self.tokenizer.encode(req["prompt"]), np.int32)
        else:
            raise ValueError("need promptTokens or prompt")
        stops = req.get("stop", [])
        if isinstance(stops, str):
            stops = [stops]
        if not all(isinstance(s, str) and s for s in stops):
            raise ValueError("stop must be a non-empty string or list of them")
        sp = SamplingParams(
            temperature=float(req.get("temperature", 0.0)),
            top_k=int(req.get("topK", 0)),
            top_p=float(req.get("topP", 1.0)),
            max_new_tokens=int(req.get("maxNewTokens", 128)),
            stop_tokens=tuple(int(t) for t in req.get("stopTokens", [])),
        )
        prefix_id = req.get("prefixId")
        if prefix_id is not None and not isinstance(prefix_id, str):
            raise ValueError("prefixId must be a string")
        return prompt, sp, list(stops), prefix_id

    def generate(self, req: dict) -> dict:
        """Non-streaming generation: the terminal record of the streaming
        path (one machinery for both modes — stop handling included)."""
        out = None
        for out in self.generate_stream(req):
            pass
        if "error" in out:
            raise RuntimeError(out["error"])
        return {k: out[k] for k in ("tokens", "text", "numTokens", "seconds")}

    def generate_stream(self, req: dict):
        """Streaming generation: yields one JSON-line dict per token as the
        engine emits them (an agent session reads tokens as they decode
        instead of waiting for the full completion), then a terminal record
        with the aggregate fields.

        ``stop`` strings are matched against the accumulated decode; on a
        match the request is cancelled (the slot frees immediately) and the
        emitted text is cut at the match. ``stopTokens`` stop token-exactly
        inside the engine."""
        import queue as _q

        prompt, sp, stops, prefix_id = self._parse_generate(req)
        events: _q.Queue = _q.Queue()
        t0 = time.monotonic()
        r = self.engine.submit(prompt, sp,
                               emit=lambda tok, done: events.put((tok, done)),
                               prefix_id=prefix_id)
        driving = not self.engine._running   # direct use without the thread
        tokens: list[int] = []
        emitted = ""
        stopped = False
        while True:
            if driving:
                while events.empty() and not r.done.is_set():
                    self.engine.step()
            tok, done = events.get()
            if tok >= 0 and not stopped:
                tokens.append(tok)
                # Incremental decode by prefix diff: decoding ids in
                # isolation breaks BPE merging (word-boundary markers,
                # multi-token UTF-8), so concatenated per-token text would
                # not equal the final decode.
                full = self.tokenizer.decode(tokens)
                hit = min((full.find(s) for s in stops if s in full),
                          default=-1)
                if hit >= 0:
                    full = full[:hit]
                    stopped = True
                    r.cancel()
                out = full
                if not (done or stopped):
                    # decode() is NOT append-only: a codepoint split across
                    # tokens decodes to U+FFFD now and is rewritten when the
                    # next token completes it. Hold back trailing U+FFFDs
                    # until they stabilize (the final event flushes them, so
                    # genuine replacement chars still arrive) — emitted text
                    # then never needs retracting.
                    out = full[:len(full) - _trailing_fffd(full)]
                if out.startswith(emitted):
                    delta = out[len(emitted):]
                else:
                    # Belt: a tokenizer that rewrites non-tail text (never
                    # the byte/BPE ones) — re-sync at the common prefix
                    # rather than slicing at a wrong offset.
                    n = min(len(out), len(emitted))
                    i = next((j for j in range(n) if out[j] != emitted[j]), n)
                    delta = out[i:]
                emitted = out
                if delta or not stopped:
                    yield {"token": tok, "text": delta}
            if done:
                break
        if r.error is not None:
            yield {"error": f"{type(r.error).__name__}: {r.error}"}
            return
        dt = time.monotonic() - t0
        with self._stats_lock:
            self.total_tokens += len(tokens)
        yield {
            "done": True,
            "tokens": tokens,
            "text": emitted if stops else self.tokenizer.decode(tokens),
            "numTokens": len(tokens),
            "seconds": round(dt, 4),
            "cancelled": bool(r.cancelled) and not stopped,
            "stopped": stopped,
        }

    def stats(self) -> dict:
        import jax

        return {
            "model": self.model_name,
            "devices": [str(d) for d in jax.devices()],
            "numSlots": self.engine.num_slots,
            "freeSlots": len(self.engine._free_slots()),
            "uptimeSeconds": round(time.time() - self.started_at, 1),
            "totalTokens": self.total_tokens,
            "prefixCache": {"hits": self.engine.prefix_hits,
                            "misses": self.engine.prefix_misses,
                            "entries": len(self.engine._prefix_cache)},
            "tuning": {
                "decodeChunk": self.engine.decode_chunk,
                "kvCacheInt8": self.engine.kv_cache_int8,
                "fromProfile": self.engine.tune is not None,
            },
        }


class EmbeddingCell:
    """Embedding-model serving cell (bge-base): /v1/embed instead of
    /v1/generate; same health/stats seams as the decoder cell so the
    reconciler treats both cell flavors identically."""

    def __init__(self, model: str, *, batch_size: int = 16,
                 pooling: str = "cls", checkpoint: str | None = None,
                 dtype: str | None = None, seed: int = 0):
        import dataclasses

        import jax

        _enable_compilation_cache()

        from kukeon_tpu.models import bert
        from kukeon_tpu.parallel import auto_mesh_shape, make_mesh
        from kukeon_tpu.serving import EmbeddingEngine

        _register_models()
        cfg = EMBEDDING_MODELS[model]()
        if dtype:
            import jax.numpy as jnp

            cfg = dataclasses.replace(cfg, dtype=getattr(jnp, dtype))
        n = len(jax.devices())
        shape = auto_mesh_shape(n)
        mesh = make_mesh(data=shape["data"], tensor=shape["tensor"])
        if checkpoint:
            params = self._load_checkpoint(checkpoint, cfg)
        else:
            params = bert.init_params(jax.random.key(seed), cfg)

        self.model_name = model
        self.cfg = cfg
        self.engine = EmbeddingEngine(cfg, params, mesh,
                                      batch_size=batch_size, pooling=pooling)
        # The checkpoint's real tokenizer when it ships one (BASELINE config
        # 5 text inputs must not be byte-mangled for a real bge model);
        # byte fallback otherwise — same rule as the decoder cell.
        from kukeon_tpu.serving.tokenizer import load_tokenizer

        self.tokenizer = load_tokenizer(checkpoint)
        self.started_at = time.time()
        self.total_sequences = 0
        self._stats_lock = threading.Lock()

    @staticmethod
    def _load_checkpoint(path: str, cfg):
        import jax
        import orbax.checkpoint as ocp

        from kukeon_tpu.models import bert

        abstract = jax.eval_shape(
            lambda k: bert.init_params(k, cfg), jax.random.key(0)
        )
        return ocp.StandardCheckpointer().restore(path, abstract)

    def warmup(self, prompt_len: int = 64):
        self.engine.warmup((prompt_len,))

    def embed(self, req: dict) -> dict:
        if "inputTokens" in req:
            prompts = [np.asarray(p, np.int32) for p in req["inputTokens"]]
        elif "inputs" in req:
            texts = req["inputs"]
            if isinstance(texts, str):
                texts = [texts]
            prompts = [np.asarray(self.tokenizer.encode(x) or [1], np.int32)
                       for x in texts]
        else:
            raise ValueError("need inputs or inputTokens")
        t0 = time.monotonic()
        vecs = self.engine.embed_batch(prompts)
        dt = time.monotonic() - t0
        with self._stats_lock:
            self.total_sequences += len(prompts)
        return {
            "embeddings": [v.tolist() for v in vecs],
            "dim": int(vecs.shape[1]) if len(prompts) else self.cfg.hidden_size,
            "numSequences": len(prompts),
            "seconds": round(dt, 4),
        }

    def stats(self) -> dict:
        import jax

        return {
            "model": self.model_name,
            "kind": "embedding",
            "devices": [str(d) for d in jax.devices()],
            "batchSize": self.engine.batch_size,
            "uptimeSeconds": round(time.time() - self.started_at, 1),
            "totalSequences": self.total_sequences,
        }


def make_handler(cell: ServingCell):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *a):
            sys.stderr.write("serving-cell: " + fmt % a + "\n")

        def _send(self, code: int, obj: dict):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/v1/health":
                self._send(200, {"status": "ok", "model": cell.model_name})
            elif self.path == "/v1/stats":
                self._send(200, cell.stats())
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            routes = {}
            if hasattr(cell, "generate"):
                routes["/v1/generate"] = cell.generate
            if hasattr(cell, "embed"):
                routes["/v1/embed"] = cell.embed
            fn = routes.get(self.path)
            if fn is None:
                self._send(404, {"error": f"no route {self.path}; "
                                          f"this cell serves {sorted(routes)}"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                if (self.path == "/v1/generate" and req.get("stream")
                        and hasattr(cell, "generate_stream")):
                    self._stream(cell.generate_stream(req))
                    return
                self._send(200, fn(req))
            except ValueError as e:
                self._send(400, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — server must keep serving
                self._send(500, {"error": f"{type(e).__name__}: {e}"})

        def _stream(self, gen):
            """Newline-delimited JSON, framed by connection close (the
            handler speaks HTTP/1.0). The first record is pulled before
            headers go out so parse errors still surface as a clean 400."""
            import itertools

            try:
                first = next(gen)
            except ValueError as e:
                self._send(400, {"error": str(e)})
                return
            except StopIteration:
                self._send(500, {"error": "empty stream"})
                return
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.end_headers()
            try:
                for obj in itertools.chain([first], gen):
                    self.wfile.write((json.dumps(obj) + "\n").encode())
                    self.wfile.flush()
            except OSError:
                pass   # client went away mid-stream; nothing to tell it
            except Exception as e:  # noqa: BLE001 — headers are already out
                # A second status line (do_POST's 500 path) would land
                # inside the open ndjson body and corrupt the stream; the
                # in-band terminal error line is the protocol here.
                try:
                    self.wfile.write(
                        (json.dumps({"error": f"{type(e).__name__}: {e}"})
                         + "\n").encode())
                    self.wfile.flush()
                except OSError:
                    pass

    return Handler


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kukeon-serving-cell")
    ap.add_argument("--model", required=True)
    ap.add_argument("--port", type=int, default=9000)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--num-slots", type=int, default=8)
    ap.add_argument("--max-seq-len", type=int, default=None)
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--dtype", default=None)
    # None (flag absent) lets the persisted autotune profile decide; the
    # explicit flag always wins (serving/tuning.py).
    ap.add_argument("--kv-cache-int8", action="store_true", default=None)
    ap.add_argument("--decode-chunk", type=int, default=None)
    ap.add_argument("--no-warmup", action="store_true")
    args = ap.parse_args(argv)

    _register_models()

    def build():
        if args.model in EMBEDDING_MODELS:
            cell = EmbeddingCell(args.model, batch_size=args.num_slots,
                                 checkpoint=args.checkpoint, dtype=args.dtype)
            if not args.no_warmup:
                cell.warmup()
            return cell
        cell = ServingCell(
            args.model, num_slots=args.num_slots, max_seq_len=args.max_seq_len,
            checkpoint=args.checkpoint, dtype=args.dtype,
            kv_cache_int8=args.kv_cache_int8, decode_chunk=args.decode_chunk,
        )
        # Warmup before the engine thread starts: step() is single-driver.
        if not args.no_warmup:
            cell.warmup()
        cell.engine.start()
        return cell

    try:
        cell = build()
    except Exception as e:  # noqa: BLE001 — one self-heal attempt
        # A poisoned persistent-cache entry (stale AOT vs rolled libtpu,
        # truncated write) would otherwise crash-loop the cell forever under
        # restartPolicy: always. Bust the cache and recompile once; rethrow
        # if the failure had nothing to do with the cache.
        if not _bust_compilation_cache():
            raise
        print(f"serving-cell: init failed ({type(e).__name__}: {e}); "
              "busted persistent compilation cache, retrying once",
              file=sys.stderr, flush=True)
        cell = build()
    server = ThreadingHTTPServer((args.host, args.port), make_handler(cell))
    print(f"serving-cell: {args.model} ready on {args.host}:{args.port}", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
