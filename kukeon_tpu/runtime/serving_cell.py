"""Model-serving cell entrypoint: HTTP front-end over the ServingEngine.

The in-tree serving workload the runner materializes for ``CellSpec.model``
(BASELINE north star: "an in-tree JetStream (JAX/XLA) inference cell"). The
runner grants chips via TPU_VISIBLE_DEVICES before exec; this process builds
the mesh over whatever devices JAX exposes and serves:

  GET  /v1/health    -> {"status": "ok", ...}  (the reconciler's health seam)
  GET  /healthz      -> liveness (200 while the process can answer at all)
  GET  /readyz       -> readiness (503 until warmup completes, while
                        draining, and after the TPU watchdog trips)
  POST /drain        -> stop admitting, finish in-flight, then exit cleanly
  GET  /v1/stats     -> slots/queue/throughput counters (a JSON view over
                        the same obs registry /metrics scrapes)
  GET  /metrics      -> Prometheus text exposition: engine latency
                        histograms (TTFT/inter-token/e2e/queue-wait/
                        prefill-by-bucket), shed/timeout/watchdog/fault
                        counters, slot/queue gauges (kukeon_tpu/obs)
  GET  /v1/trace?n=K -> newest K per-request trace spans (lifecycle events
                        + per-phase durations summing to e2e);
                        ?request_id=N pulls one request's span exactly
  POST /v1/profile   -> {"durationMs": N} starts a single-flight
                        jax.profiler capture into KUKEON_PROFILE_DIR
                        (409 while one runs); GET /v1/profile lists captures
  POST /v1/generate  -> {"promptTokens": [...] | "prompt": "text",
                         "maxNewTokens": N, "temperature": T,
                         "deadlineS": D, ...}
                        => {"tokens": [...], "text": "..."}
  POST /v1/kv/export -> generate-shaped JSON body in, binary KV handoff
                        block out (prefill only — no decode slot consumed);
                        the disaggregated gateway's first hop
  POST /v1/kv/import -> binary KV handoff block in, the continuation out
                        (JSON, or ndjson when the header says stream); the
                        imported request seats straight into a decode slot
                        via the paged insert program, never re-prefilling

Resilience: admission is bounded (``--max-pending`` -> 429 + Retry-After),
requests carry deadlines (``--deadline-s`` default, per-request
``deadlineS``), and a TPU watchdog (KUKEON_WATCHDOG_S) detects a stuck
engine step, confirms against devices.probe_tpu_runtime, and exits nonzero
so the runner's restart policy recovers the cell on its own chip grant.

Tokenization: checkpoint-less engines (random init, dev/e2e) use a byte
tokenizer (id = byte + 1); real deployments pass a HF tokenizer name.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from kukeon_tpu import faults, sanitize
from kukeon_tpu.obs import (
    FlightRecorder,
    ProfileBusy,
    ProfileSpool,
    Registry,
    SloObjectives,
    SloTracker,
    device_memory_collector,
    expo,
)
from kukeon_tpu.obs import trace as obs_trace
from kukeon_tpu.serving.engine import DeadlineExceeded, RejectedError

MODELS = {}
EMBEDDING_MODELS = {}

# Process birth (well, module import — the runner execs `python -m
# kukeon_tpu.runtime.serving_cell`, so they coincide in production):
# the zero point for the cold-start phase breakdown finish_boot exports.
_PROC_T0 = time.monotonic()

# Exit code for a watchdog-confirmed wedged TPU runtime: nonzero so the
# runner's restart policy (always / on-failure) restarts the cell, distinct
# from generic crashes so the operator can grep for it in `kuke get` reasons.
WEDGED_EXIT_CODE = 86

DRAIN_TIMEOUT_ENV = "KUKEON_DRAIN_TIMEOUT_S"
WATCHDOG_ENV = "KUKEON_WATCHDOG_S"
WATCHDOG_PROBE_TIMEOUT_ENV = "KUKEON_WATCHDOG_PROBE_TIMEOUT_S"


@sanitize.guard_class
class LifecycleMixin:
    """Readiness/drain lifecycle shared by both cell flavors.

    States: warming up (unready) -> ready -> draining (unready, in-flight
    finishing) -> drained. The watchdog flips unready via mark_unready
    before exiting. Everything here is advisory for direct (non-HTTP) cell
    use; the HTTP handler is where admission is enforced.

    Lock hierarchy: ``_drain_lock`` serializes the drain state machine
    (``draining`` flips exactly once), ``_inflight_lock`` guards the HTTP
    in-flight count — they never nest. Under ``KUKEON_SANITIZE=1`` both
    are kukesan recording proxies and the guarded-by contract below is
    enforced on every write.
    """

    def _init_lifecycle(self):
        self._ready = sanitize.event("LifecycleMixin._ready")
        self.unready_reason: str | None = "warming up"
        # Guarded attrs are assigned BEFORE their locks exist: kukesan's
        # __setattr__ hook then skips them even when a subclass constructs
        # without a wrapped __init__ (no guard lock to interrogate yet).
        self.draining = False   # guarded-by: _drain_lock
        self._drain_lock = sanitize.lock("LifecycleMixin._drain_lock")
        self.drained = sanitize.event("LifecycleMixin.drained")
        self._inflight = 0      # guarded-by: _inflight_lock
        self._inflight_lock = sanitize.lock("LifecycleMixin._inflight_lock")
        # Drain wake signal (shares _inflight_lock): _inflight_dec notifies
        # when the HTTP in-flight count hits zero, so the drain loop wakes
        # the moment the last request finishes instead of sleep-polling
        # _idle() at 50ms (the same condition-over-poll fix the engine
        # loop got). The timed wait below doubles as the poll for the
        # engine-side half of _idle(), which this condition cannot see.
        self._inflight_zero = sanitize.condition(
            self._inflight_lock, name="LifecycleMixin._inflight_zero")
        # main() points this at server.shutdown so a finished drain unblocks
        # serve_forever and the process exits 0.
        self.on_drained = None

    def _init_cell_obs(self, registry: Registry, kind: str) -> None:
        """Cell-level observability shared by both cell flavors: lifecycle
        gauges (scrape-time callables — zero cost between scrapes) plus
        the fault-injection fire-count family, all on the one registry
        ``GET /metrics`` renders."""
        self.registry = registry
        registry.gauge("kukeon_cell_info",
                       "Static cell identity (value always 1).",
                       labels=("model", "kind")).set(
            1, model=self.model_name, kind=kind)
        registry.gauge("kukeon_cell_uptime_seconds",
                       "Seconds since cell construction.").set_function(
            lambda: time.time() - self.started_at)
        registry.gauge("kukeon_cell_ready",
                       "1 while admitting requests (readyz).").set_function(
            lambda: 1.0 if self.readiness()[0] else 0.0)
        registry.gauge("kukeon_cell_draining",
                       "1 while a drain is in progress.").set_function(
            lambda: 1.0 if self.draining else 0.0)
        registry.gauge("kukeon_cell_http_inflight",
                       "HTTP requests currently being served.").set_function(
            lambda: float(self._inflight))
        # Pre-declare the watchdog families so a scrape sees them at zero
        # even before (or without) an EngineWatchdog — the watchdog's own
        # get-or-create then lands on these same counters.
        registry.counter(
            "kukeon_watchdog_probes_total",
            "TPU runtime probes fired after an engine stall.",
            labels=("verdict",))
        registry.counter(
            "kukeon_watchdog_trips_total",
            "Wedged verdicts (the cell exits for restart right after).")
        registry.register_collector(expo.faults_collector)
        # Device telemetry on every cell flavor (register_collector dedupes,
        # so the decoder cell — whose engine already registered the same
        # collector on the shared registry — emits the families once).
        registry.register_collector(device_memory_collector)
        # On-demand profiler spool behind POST/GET /v1/profile: single-
        # flight jax.profiler captures into KUKEON_PROFILE_DIR, keep-last-K.
        self.profiler = ProfileSpool(registry=registry)
        # Step flight recorder behind GET /v1/timeline: the decoder cell
        # aliases its engine's ring (one ring, one dropped-counter family);
        # flavors without an engine-side recorder get a cell-local one
        # (the embedding cell records one entry per embed batch).
        # NB: an explicit None check — FlightRecorder defines __len__, so
        # an (empty) engine ring is falsy and `or` would shadow it with a
        # second ring nobody writes to.
        engine_rec = getattr(getattr(self, "engine", None), "recorder", None)
        self.recorder = (engine_rec if engine_rec is not None
                         else FlightRecorder(registry=registry))

    def mark_ready(self):
        self.unready_reason = None
        self._ready.set()

    def mark_unready(self, reason: str):
        self.unready_reason = reason
        self._ready.clear()

    def readiness(self) -> tuple[bool, str | None]:
        if self.draining:
            return False, "draining"
        if not self._ready.is_set():
            return False, self.unready_reason or "not ready"
        return True, None

    def check_admission(self):
        """Raise RejectedError while the cell must not take new requests.
        Queue-full shedding lives in the engine; this is the lifecycle
        layer (warming up / draining / watchdog-tripped)."""
        ok, why = self.readiness()
        if not ok:
            raise RejectedError(f"not admitting requests: {why}",
                                retry_after_s=5.0)

    def _inflight_inc(self):
        with self._inflight_lock:
            self._inflight += 1

    def _inflight_dec(self):
        with self._inflight_lock:
            self._inflight -= 1
            if self._inflight == 0:
                # Wake a drain loop parked on the condition NOW — the
                # last in-flight request completing is exactly the event
                # it is waiting for.
                self._inflight_zero.notify_all()

    def _idle(self) -> bool:
        """No in-flight HTTP requests (subclasses add engine occupancy)."""
        with self._inflight_lock:
            return self._inflight == 0

    def begin_drain(self) -> bool:
        """Stop admitting, finish in-flight work, then report drained (and
        fire on_drained, which in main() shuts the HTTP server down).
        Idempotent; returns False if a drain was already running."""
        with self._drain_lock:
            if self.draining:
                return False
            self.draining = True
        self.mark_unready("draining")
        threading.Thread(target=self._drain_loop, daemon=True,
                         name="cell-drain").start()
        return True

    def _drain_loop(self):
        timeout = float(os.environ.get(DRAIN_TIMEOUT_ENV, "30") or 30)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and not self._idle():
            # Park on the inflight-zero condition instead of sleep-polling
            # (KUKE009 discipline): the last HTTP request's _inflight_dec
            # wakes the drain immediately; the bounded wait is the safety
            # net AND the poll tick for engine-side work the condition is
            # not signalled for (ServingCell._idle also watches
            # engine._requests).
            with self._inflight_zero:
                self._inflight_zero.wait(timeout=0.05)
        self._shutdown_engine()
        self.drained.set()
        if self.on_drained is not None:
            self.on_drained()

    def _shutdown_engine(self):
        pass


def _trailing_fffd(s: str) -> int:
    """Length of the run of U+FFFD replacement chars at the end of ``s``
    (the provisional decode of an incomplete multi-byte codepoint)."""
    n = 0
    while n < len(s) and s[-1 - n] == "�":
        n += 1
    return n


# --- KV handoff wire format (disaggregated serving) --------------------------
#
# One prefill's output travels prefill cell -> gateway -> decode cell as a
# single binary body: a JSON header line (token, length, dtype, shape, byte
# counts, plus — on the import leg — the generation parameters), then the
# raw K rows, then the raw V rows. JSON-encoding multi-MB bf16 tensors
# would triple the bytes; this stays a flat memcpy on both ends.

KV_CONTENT_TYPE = "application/x-kukeon-kv"


def _kv_dtype(name: str):
    """numpy dtype from its string name, including the ml_dtypes families
    (bfloat16 & friends) jax checkpoints use."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def pack_kv(header: dict, k: np.ndarray, v: np.ndarray) -> bytes:
    """Serialize a KV block + header into the handoff wire format."""
    kb = np.ascontiguousarray(k).tobytes()
    vb = np.ascontiguousarray(v).tobytes()
    head = dict(header)
    head.update({
        "dtype": str(k.dtype), "shape": list(k.shape),
        "kBytes": len(kb), "vBytes": len(vb),
    })
    return json.dumps(head).encode() + b"\n" + kb + vb


def unpack_kv(body: bytes) -> tuple[dict, np.ndarray, np.ndarray]:
    """Parse the handoff wire format back into (header, k, v)."""
    nl = body.find(b"\n")
    if nl < 0:
        raise ValueError("KV body has no header line")
    header = json.loads(body[:nl])
    dtype = _kv_dtype(header["dtype"])
    shape = tuple(int(s) for s in header["shape"])
    kb, vb = int(header["kBytes"]), int(header["vBytes"])
    raw = body[nl + 1:]
    if len(raw) != kb + vb:
        raise ValueError(
            f"KV body truncated: header claims {kb + vb} tensor bytes, "
            f"got {len(raw)}")
    k = np.frombuffer(raw[:kb], dtype=dtype).reshape(shape)
    v = np.frombuffer(raw[kb:], dtype=dtype).reshape(shape)
    return header, k, v


_CACHE_DIR: str | None = None   # the versioned dir actually configured


def _enable_compilation_cache() -> None:
    """Persistent XLA compilation cache: the dominant cold-start cost after
    weight load is jit compilation; caching it on disk makes every boot
    after the first (same program shapes) start in seconds. Standard TPU
    serving practice (JetStream does the same).

    The cache dir is keyed by the runtime build (jax version + backend
    platform_version, which embeds the libtpu build stamp): AOT artifacts
    compiled under one libtpu are invalid under another — r4's cold-start
    died to exactly this ("FAILED_PRECONDITION: libtpu version mismatch"
    crash loop off stale cache entries after a libtpu roll). A rolled
    runtime must see an EMPTY cache, never a poisoned one."""
    global _CACHE_DIR
    import hashlib

    import jax

    base = os.environ.get(
        "KUKEON_JAX_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "kukeon-jax"),
    )
    try:
        try:
            import jax.extend

            ver = jax.extend.backend.get_backend().platform_version
        except Exception:  # noqa: BLE001 — version probe must not kill serving
            ver = "unknown"
        key = hashlib.sha256(f"{jax.__version__}|{ver}".encode()).hexdigest()[:12]
        cache_dir = os.path.join(base, key)
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        _CACHE_DIR = cache_dir
    except Exception:  # noqa: BLE001 — cache is an optimization, never fatal
        pass


def _bust_compilation_cache() -> bool:
    """Wipe the configured cache dir; True if there was anything to wipe.
    Last-resort self-heal for a corrupted cache entry that keys identically
    but fails to deserialize (crash-looping forever would be worse than one
    slow recompile)."""
    if not _CACHE_DIR or not os.path.isdir(_CACHE_DIR):
        return False
    import shutil

    had = any(os.scandir(_CACHE_DIR))
    shutil.rmtree(_CACHE_DIR, ignore_errors=True)
    os.makedirs(_CACHE_DIR, exist_ok=True)
    return had


MOE_MODELS = set()


def _register_models():
    from kukeon_tpu.models import bert, llama, moe

    MODELS.update({
        "tiny": llama.llama_tiny,
        "llama3-1b": llama.llama3_1b,
        "llama3-8b": llama.llama3_8b,
        "mixtral-tiny": moe.moe_tiny,
        "mixtral-8x7b": moe.mixtral_8x7b,
    })
    MOE_MODELS.update({"mixtral-tiny", "mixtral-8x7b"})
    EMBEDDING_MODELS.update({
        "bge-base": bert.bge_base,
        "bge-tiny": bert.bge_tiny,
    })


@sanitize.guard_class
class ServingCell(LifecycleMixin):
    def __init__(self, model: str, *, num_slots: int, max_seq_len: int | None,
                 checkpoint: str | None, dtype: str | None, seed: int = 0,
                 kv_cache_int8: bool | None = None,
                 decode_chunk: int | None = None,
                 kv_page_tokens: int | None = None,
                 max_pending: int | None = None,
                 deadline_s: float | None = None,
                 slo_ttft_p95_ms: float | None = None,
                 slo_availability: float | None = None,
                 role: str = "mixed",
                 chips: int | None = None):
        # Cold-start phase marks (monotonic). "boot_imports" is everything
        # between process start and constructor entry — interpreter boot,
        # module imports, argparse; the remaining phases are stamped as
        # the boot pipeline advances and exported by finish_boot().
        self._boot_marks: dict[str, float] = {"init_entry": time.monotonic()}
        import jax

        _enable_compilation_cache()

        from kukeon_tpu.models import llama
        from kukeon_tpu.parallel import auto_mesh_shape, make_mesh, serving_mesh
        from kukeon_tpu.serving import ServingEngine

        _register_models()
        if model not in MODELS:
            raise SystemExit(
                f"unknown model {model!r}; known: "
                f"{sorted(MODELS) + sorted(EMBEDDING_MODELS)}"
            )
        import dataclasses

        # "int8" quantizes the weights post-load (activations stay bf16);
        # other dtype strings set the activation/weight dtype directly.
        quantize = dtype == "int8"
        cfg = MODELS[model]()
        if dtype and not quantize:
            import jax.numpy as jnp

            cfg = dataclasses.replace(cfg, dtype=getattr(jnp, dtype))
        if max_seq_len:
            cfg = dataclasses.replace(cfg, max_seq_len=max_seq_len)

        # Mesh: an explicit --chips N is the ModelSpec's grant — exactly N
        # chips, all on the tensor axis, dying loudly (serving_mesh) when
        # the grant exceeds what this process can see rather than silently
        # serving on fewer chips. Without the flag (bare/dev boots) the
        # cell keeps the old behavior: every visible device, factorized by
        # the auto heuristic.
        if chips is not None:
            try:
                mesh = serving_mesh(chips)
            except ValueError as e:
                raise SystemExit(f"--chips {chips}: {e}") from e
        else:
            n = len(jax.devices())
            shape = auto_mesh_shape(n)
            mesh = make_mesh(data=shape["data"], tensor=shape["tensor"])

        forward_fn = None
        param_specs = None
        if model in MOE_MODELS:
            # MoE family: same engine, moe forward + expert-aware specs.
            # int8-KV is a llama-decode-path feature the MoE forward doesn't
            # have yet — fail loudly rather than serving garbage; an
            # unspecified flag pins False so a tuning profile can never
            # switch it on behind the guard.
            if kv_cache_int8:
                raise SystemExit(
                    f"model {model!r} does not support --kv-cache-int8 yet"
                )
            kv_cache_int8 = False
            from kukeon_tpu.models import hf_convert, moe
            from kukeon_tpu.parallel import moe_specs_for_params

            if checkpoint:
                params, cfg = hf_convert.load_moe_params(
                    checkpoint, dtype=cfg.dtype
                )
                if max_seq_len:
                    cfg = dataclasses.replace(cfg, max_seq_len=max_seq_len)
                if quantize:
                    # Weights-only int8 (router/norms stay high precision);
                    # dequant fuses into attention _mm and expert einsums.
                    params = moe.quantize_params(params)
            elif quantize:
                # Random-init directly in int8 on the host: a mixtral-8x7b
                # bf16 tree (~93 GB) cannot be materialized on-device just
                # to be quantized (same rule as the Llama path).
                params = moe.init_quantized_params_host(cfg, seed)
            else:
                params = moe.init_params(jax.random.key(seed), cfg)
            forward_fn = moe.forward
            param_specs = moe_specs_for_params(params)
        elif checkpoint:
            params, cfg = self._load_checkpoint(checkpoint, cfg, quantize)
        elif quantize:
            # Random-init directly in int8 on the host: an 8B bf16 tree
            # (~16 GB) cannot be materialized on a 16 GB chip just to be
            # quantized (models/llama.py init_quantized_params_host).
            params = llama.init_quantized_params_host(cfg, seed)
        else:
            params = llama.init_params(jax.random.key(seed), cfg)

        self.model_name = model
        self.cfg = cfg
        # Disaggregated-serving role (mixed | prefill | decode). Policy,
        # not capability: every cell keeps the full engine — a prefill
        # cell can still decode locally (the gateway's fallback when no
        # decode replica is ready), a decode cell can still re-prefill a
        # preempted import. The role is advertised on /v1/stats so the
        # gateway's two-stage router builds its pools from the census.
        if role not in ("mixed", "prefill", "decode"):
            raise SystemExit(
                f"unknown --role {role!r}; must be mixed|prefill|decode")
        self.role = role
        # async_load: the multi-GB weight transfer streams in the background
        # while warmup()'s precompile pass AOT-compiles the programs — cold
        # start pays max(transfer, compile) instead of their sum.
        # model_name routes the engine to the persisted autotune profile
        # (bench.py --autotune): levers the operator left unset
        # (decode_chunk/kv_cache_int8 None) boot at the swept winner for
        # this model+backend+chip-count.
        # One registry for the whole cell: engine metrics and cell
        # lifecycle gauges land in the same /metrics exposition.
        registry = Registry()
        self.engine = ServingEngine(
            cfg, params, mesh, num_slots=num_slots,
            max_seq_len=max_seq_len or min(cfg.max_seq_len, 4096),
            kv_cache_int8=kv_cache_int8, async_load=True,
            forward_fn=forward_fn, param_specs=param_specs,
            decode_chunk=decode_chunk, model_name=model,
            kv_page_tokens=kv_page_tokens,
            max_pending=max_pending, registry=registry,
        )
        from kukeon_tpu.serving.tokenizer import load_tokenizer

        self.tokenizer = load_tokenizer(checkpoint)
        self.started_at = time.time()
        self._stats_lock = sanitize.lock("ServingCell._stats_lock")
        self.total_tokens = 0   # guarded-by: _stats_lock
        # Default per-request deadline; a request's own deadlineS wins.
        self.default_deadline_s = deadline_s
        self._init_lifecycle()
        self._init_cell_obs(registry, kind="decoder")
        # SLO layer (obs/slo.py): burn rates + error-budget gauges computed
        # at scrape time from the engine's own requests/TTFT instruments.
        # Unset objectives fall back to the loose defaults so every cell
        # exposes the kukeon_slo_* families with a stable schema.
        d = SloObjectives()
        self.slo = SloTracker(registry, SloObjectives(
            availability=(slo_availability if slo_availability
                          else d.availability),
            ttft_p95_ms=(slo_ttft_p95_ms if slo_ttft_p95_ms
                         else d.ttft_p95_ms),
        ))
        self._boot_marks["init_exit"] = time.monotonic()

    @staticmethod
    def _load_checkpoint(path: str, cfg, quantize: bool = False):
        """(params-or-stream, cfg) from, in precedence order:

        - a kukeon int8 quantized checkpoint (kukeon_quant.json manifest) —
          the cold-start fast path: a tensor-granular CheckpointStream
          whose config and abstract shapes come from the manifest alone,
          so this returns before any tensor byte is read and the engine
          overlaps disk / cast / upload / compile;
        - an HF safetensors directory (config.json + *.safetensors, the hub
          layout) — the same streaming pipeline, host-quantizing per leaf
          when ``quantize`` (an 8B bf16 tree cannot be materialized on a
          16 GB chip);
        - an orbax checkpoint path (materialized — orbax has no
          tensor-granular reader here).
        """
        import os

        import jax

        from kukeon_tpu.models import checkpoints, llama

        if checkpoints.is_quantized_checkpoint(path):
            stream = checkpoints.stream_quantized(path, dtype=cfg.dtype)
            return stream, stream.cfg
        if os.path.isdir(path) and os.path.exists(os.path.join(path, "config.json")):
            from kukeon_tpu.models import hf_convert

            if quantize:
                stream = hf_convert.stream_params_quantized(
                    path, dtype=cfg.dtype)
            else:
                stream = hf_convert.stream_params(path, dtype=cfg.dtype)
            return stream, stream.cfg
        import orbax.checkpoint as ocp

        abstract = jax.eval_shape(lambda k: llama.init_params(k, cfg), jax.random.key(0))
        ckptr = ocp.StandardCheckpointer()
        params = ckptr.restore(path, abstract)
        if quantize:
            params = llama.quantize_params(params)
        return params, cfg

    def warmup(self, prompt_len: int = 64):
        # Compile first (needs shapes only — overlaps the async weight
        # transfer), then run the real warmup pass (needs the weights; the
        # "warmup" phase therefore also absorbs whatever remains of the
        # async checkpoint transfer).
        self.engine.precompile((prompt_len,))
        self._boot_marks.setdefault("compile_done", time.monotonic())
        try:
            self.engine.warmup(prompt_len)
        except RuntimeError as e:
            from kukeon_tpu.models.checkpoints import CheckpointStreamError

            if isinstance(e.__cause__, CheckpointStreamError):
                # A mid-stream read/decode failure (or the armed
                # checkpoint.stream fault point) must never leave a
                # half-loaded engine a step from /readyz. SystemExit is
                # NOT an Exception, so main()'s cache-bust retry does not
                # swallow it: the cell exits with a clear message and the
                # runner's restart policy recovers it on the same grant.
                raise SystemExit(
                    f"serving-cell: checkpoint stream failed during boot "
                    f"({e.__cause__}); exiting for the restart policy to "
                    f"recover") from e
            raise
        self._boot_marks.setdefault("warmup_done", time.monotonic())

    def finish_boot(self) -> dict[str, float]:
        """Close out the cold-start trace: compute the boot phase
        breakdown, export ``kukeon_cold_start_seconds`` (total) +
        ``kukeon_cold_start_phase_seconds{phase=}``, and drop a
        ``component="boot"`` span into the trace ring so ``kuke trace``
        can render the boot timeline like any request. Called once from
        main() right before the cell goes ready; bench.py's cold-start
        phase reads these gauges off the first /metrics scrape."""
        now = time.monotonic()
        m = self._boot_marks
        phases: dict[str, float] = {
            "imports": m["init_entry"] - _PROC_T0,
            "init": m.get("init_exit", m["init_entry"]) - m["init_entry"],
        }
        if "compile_done" in m:
            phases["compile"] = m["compile_done"] - m.get("init_exit",
                                                          m["init_entry"])
            phases["warmup"] = m.get("warmup_done",
                                     m["compile_done"]) - m["compile_done"]
        total = now - _PROC_T0
        phases["serve"] = max(0.0, total - sum(phases.values()))
        # Streamed-checkpoint sub-phases (disk / cast / upload): measured
        # AFTER the serial partition above is closed, because they overlap
        # it — the reader threads' file reads and host casts and the load
        # thread's sharded uploads all run inside the init/compile/warmup
        # wall time. Their presence makes sum(phases) exceed the total;
        # that excess IS the overlap the streamed boot buys.
        eng = self.engine
        cs = (eng._ckpt_stream.stat_snapshot()
              if getattr(eng, "_ckpt_stream", None) is not None else {})
        load = {"disk": cs.get("disk_s", 0.0), "cast": cs.get("cast_s", 0.0),
                "upload": eng.load_stats.get("upload_s", 0.0)}
        if any(load.values()):
            phases.update(load)
        reg = self.registry
        reg.gauge(
            "kukeon_cold_start_seconds",
            "Process start -> ready wall time (the rolling-restart and "
            "autoscaling latency floor).").set(total)
        g = reg.gauge("kukeon_cold_start_phase_seconds",
                      "Cold-start breakdown by boot phase.",
                      labels=("phase",))
        for phase, dt in phases.items():
            g.set(dt, phase=phase)
        # Each event marks where its phase BEGINS, so the span's phase
        # durations (gap to the next event) mirror the gauge breakdown;
        # the tail gap (warmup start -> finished) covers warmup + serve.
        span = self.engine.tracer.begin(-2, 0, component="boot",
                                        start_mono=_PROC_T0)
        span.event("boot_imports", at=_PROC_T0)
        span.event("boot_init", at=m["init_entry"])
        if "compile_done" in m:
            span.event("boot_compile", at=m.get("init_exit",
                                                m["init_entry"]))
            span.event("boot_warmup", at=m["compile_done"])
        self.engine.tracer.finish(span, "ok")
        return phases

    def _parse_generate(self, req: dict):
        from kukeon_tpu.serving import SamplingParams

        if "promptTokens" in req:
            prompt = np.asarray(req["promptTokens"], np.int32)
        elif "prompt" in req:
            prompt = np.asarray(self.tokenizer.encode(req["prompt"]), np.int32)
        else:
            raise ValueError("need promptTokens or prompt")
        stops = req.get("stop", [])
        if isinstance(stops, str):
            stops = [stops]
        if not all(isinstance(s, str) and s for s in stops):
            raise ValueError("stop must be a non-empty string or list of them")
        sp = SamplingParams(
            temperature=float(req.get("temperature", 0.0)),
            top_k=int(req.get("topK", 0)),
            top_p=float(req.get("topP", 1.0)),
            max_new_tokens=int(req.get("maxNewTokens", 128)),
            stop_tokens=tuple(int(t) for t in req.get("stopTokens", [])),
        )
        prefix_id = req.get("prefixId")
        if prefix_id is not None and not isinstance(prefix_id, str):
            raise ValueError("prefixId must be a string")
        deadline_s = req.get("deadlineS", self.default_deadline_s)
        if deadline_s is not None:
            deadline_s = float(deadline_s)
            if deadline_s <= 0:
                raise ValueError("deadlineS must be positive")
        return prompt, sp, list(stops), prefix_id, deadline_s

    def generate(self, req: dict,
                 trace_ctx: "obs_trace.TraceContext | None" = None) -> dict:
        """Non-streaming generation: the terminal record of the streaming
        path (one machinery for both modes — stop handling included)."""
        out = None
        for out in self.generate_stream(req, trace_ctx=trace_ctx):
            pass
        if out.get("timedOut"):
            raise DeadlineExceeded(out["error"])
        if "error" in out:
            raise RuntimeError(out["error"])
        return {k: out[k] for k in ("tokens", "text", "numTokens", "seconds")}

    def generate_stream(self, req: dict,
                        trace_ctx: "obs_trace.TraceContext | None" = None):
        """Streaming generation: yields one JSON-line dict per token as the
        engine emits them (an agent session reads tokens as they decode
        instead of waiting for the full completion), then a terminal record
        with the aggregate fields.

        ``stop`` strings are matched against the accumulated decode; on a
        match the request is cancelled (the slot frees immediately) and the
        emitted text is cut at the match. ``stopTokens`` stop token-exactly
        inside the engine."""
        import queue as _q

        prompt, sp, stops, prefix_id, deadline_s = self._parse_generate(req)
        events: _q.Queue = _q.Queue()
        t0 = time.monotonic()
        r = self.engine.submit(prompt, sp,
                               emit=lambda tok, done: events.put((tok, done)),
                               prefix_id=prefix_id, deadline_s=deadline_s,
                               trace_ctx=trace_ctx)
        yield from self._stream_events(r, events, stops, tokens=[],
                                       emitted="", t0=t0)

    def _stream_events(self, r, events, stops, *, tokens, emitted, t0,
                       skip_first=False):
        """The shared token-event loop behind generate_stream AND the KV
        handoff import: drain the engine's emit events, decode by prefix
        diff, match stop strings, then yield the terminal record.
        ``tokens``/``emitted`` may arrive pre-seeded (the import path
        already emitted the handed-off first token before seating);
        ``skip_first`` swallows the engine's re-emit of that token."""
        driving = not self.engine._running   # direct use without the thread
        stopped = False
        while True:
            if driving:
                while events.empty() and not r.done.is_set():
                    self.engine.step()
            tok, done = events.get()
            if skip_first:
                # The engine re-emits the imported first token at seat
                # time; its line already went out pre-seat (the handoff's
                # TTFT point), so only honor its terminal flag here.
                skip_first = False
                if not done:
                    continue
                tok = -1
            if tok >= 0 and not stopped:
                tokens.append(tok)
                # Incremental decode by prefix diff: decoding ids in
                # isolation breaks BPE merging (word-boundary markers,
                # multi-token UTF-8), so concatenated per-token text would
                # not equal the final decode.
                full = self.tokenizer.decode(tokens)
                hit = min((full.find(s) for s in stops if s in full),
                          default=-1)
                if hit >= 0:
                    full = full[:hit]
                    stopped = True
                    r.cancel()
                out = full
                if not (done or stopped):
                    # decode() is NOT append-only: a codepoint split across
                    # tokens decodes to U+FFFD now and is rewritten when the
                    # next token completes it. Hold back trailing U+FFFDs
                    # until they stabilize (the final event flushes them, so
                    # genuine replacement chars still arrive) — emitted text
                    # then never needs retracting.
                    out = full[:len(full) - _trailing_fffd(full)]
                if out.startswith(emitted):
                    delta = out[len(emitted):]
                else:
                    # Belt: a tokenizer that rewrites non-tail text (never
                    # the byte/BPE ones) — re-sync at the common prefix
                    # rather than slicing at a wrong offset.
                    n = min(len(out), len(emitted))
                    i = next((j for j in range(n) if out[j] != emitted[j]), n)
                    delta = out[i:]
                emitted = out
                if delta or not stopped:
                    yield {"token": tok, "text": delta}
            if done:
                break
        if r.timed_out:
            # In-band timeout terminal event: the deadline expiring mid-
            # stream must not masquerade as a transport error — partial
            # tokens are already on the wire, the terminal line names why
            # they stopped.
            yield {"error": f"deadline exceeded: {r.error}",
                   "timedOut": True, "numTokens": len(tokens)}
            return
        if r.error is not None:
            yield {"error": f"{type(r.error).__name__}: {r.error}"}
            return
        dt = time.monotonic() - t0
        with self._stats_lock:
            self.total_tokens += len(tokens)
        yield {
            "done": True,
            "tokens": tokens,
            "text": emitted if stops else self.tokenizer.decode(tokens),
            "numTokens": len(tokens),
            "seconds": round(dt, 4),
            "cancelled": bool(r.cancelled) and not stopped,
            "stopped": stopped,
        }

    # --- disaggregated serving: KV handoff -------------------------------

    def kv_export(self, req: dict,
                  trace_ctx: "obs_trace.TraceContext | None" = None) -> bytes:
        """Prefill-only handler behind ``POST /v1/kv/export``: run the
        prompt's prefill, fetch the KV block, and serialize it (plus the
        first sampled token and everything a decode cell needs to seat the
        request) in the handoff wire format. No decode slot is consumed on
        this cell — that is what makes a prefill pool's TTFT immune to
        decode occupancy."""
        import queue as _q

        prompt, sp, stops, prefix_id, deadline_s = self._parse_generate(req)
        events: _q.Queue = _q.Queue()
        r = self.engine.submit(prompt, sp,
                               emit=lambda tok, done: events.put((tok, done)),
                               prefix_id=prefix_id, deadline_s=deadline_s,
                               trace_ctx=trace_ctx, export=True)
        if not self.engine._running:    # direct use without the thread
            while not r.done.is_set():
                self.engine.step()
        r.done.wait()
        if r.timed_out:
            raise DeadlineExceeded(str(r.error))
        if r.error is not None:
            if isinstance(r.error, RejectedError):
                raise r.error
            raise RuntimeError(f"{type(r.error).__name__}: {r.error}")
        p = r.export_payload
        first = int(p["token"])
        first_text = self.tokenizer.decode([first])
        # A first token that is already terminal (eos, stop token, a
        # one-token budget, or a stop string it completes by itself) needs
        # no decode hop at all — the gateway answers from this header.
        hit = min((first_text.find(s) for s in stops if s in first_text),
                  default=-1)
        done = (hit >= 0
                or first in self.engine.eos_ids
                or first in sp.stop_tokens
                or sp.max_new_tokens <= 1)
        header = {
            "token": first,
            "text": first_text[:hit] if hit >= 0 else first_text,
            "length": int(p["length"]),
            "pageTokens": int(p["pageTokens"]),
            "model": self.model_name,
            "done": done,
            # Everything the decode cell needs to seat and continue the
            # request (tokenized HERE — the gateway has no tokenizer).
            "promptTokens": [int(t) for t in prompt],
            "maxNewTokens": sp.max_new_tokens,
            "temperature": sp.temperature,
            "topK": sp.top_k,
            "topP": sp.top_p,
            "stopTokens": list(sp.stop_tokens),
            "stop": stops,
            **({"prefixId": prefix_id} if prefix_id else {}),
            **({"deadlineS": deadline_s} if deadline_s else {}),
        }
        return pack_kv(header, p["k"], p["v"])

    def kv_import_stream(self, header: dict, k: np.ndarray, v: np.ndarray,
                         trace_ctx: "obs_trace.TraceContext | None" = None):
        """Seat a prefill cell's exported KV block into this cell's decode
        batch and stream the continuation (``POST /v1/kv/import``).

        The handed-off first token is emitted BEFORE the request waits for
        a decode slot — it already exists, so the client's TTFT is the
        prefill+transfer cost, not prefill plus decode-batch queueing;
        that ordering is the latency architecture of the handoff. The
        engine re-emits the token at seat time and the shared event loop
        swallows it (``skip_first``)."""
        import queue as _q

        faults.maybe_fail("kv.handoff")
        first = int(header["token"])
        n = int(header["length"])
        prompt = np.asarray(header.get("promptTokens", []), np.int32)
        stops = list(header.get("stop") or [])
        from kukeon_tpu.serving import SamplingParams

        sp = SamplingParams(
            temperature=float(header.get("temperature", 0.0)),
            top_k=int(header.get("topK", 0)),
            top_p=float(header.get("topP", 1.0)),
            max_new_tokens=int(header.get("maxNewTokens", 128)),
            stop_tokens=tuple(int(t) for t in header.get("stopTokens", [])),
        )
        deadline_s = header.get("deadlineS", self.default_deadline_s)
        t0 = time.monotonic()
        tokens = [first]
        full = self.tokenizer.decode(tokens)
        hit = min((full.find(s) for s in stops if s in full), default=-1)
        stopped = hit >= 0
        if stopped:
            full = full[:hit]
        done_now = (stopped or first in self.engine.eos_ids
                    or first in sp.stop_tokens or sp.max_new_tokens <= 1)
        emitted = (full if done_now
                   else full[:len(full) - _trailing_fffd(full)])
        if done_now:
            with self._stats_lock:
                self.total_tokens += 1
            yield {"token": first, "text": emitted}
            yield {"done": True, "tokens": tokens,
                   "text": emitted if stops else full,
                   "numTokens": 1, "seconds": round(
                       time.monotonic() - t0, 4),
                   "cancelled": False, "stopped": stopped}
            return
        # Submit BEFORE the first yield: a queue-full RejectedError must
        # surface before any body byte goes out, so the handler can still
        # answer a clean 429 the gateway's retry accounting understands.
        events: _q.Queue = _q.Queue()
        r = self.engine.submit(
            prompt, sp,
            emit=lambda tok, done: events.put((tok, done)),
            prefix_id=header.get("prefixId"), deadline_s=deadline_s,
            trace_ctx=trace_ctx,
            kv_import={"token": first, "length": n, "k": k, "v": v})
        # The handed-off first token goes out NOW, before the request has
        # a decode slot — TTFT is prefill+transfer, not seat-queue wait.
        yield {"token": first, "text": emitted}
        yield from self._stream_events(r, events, stops, tokens=tokens,
                                       emitted=emitted, t0=t0,
                                       skip_first=True)

    def kv_import(self, header: dict, k: np.ndarray, v: np.ndarray,
                  trace_ctx: "obs_trace.TraceContext | None" = None) -> dict:
        """Non-streaming import: drive the streaming path to its terminal
        record (one machinery for both modes, like generate)."""
        out = None
        for out in self.kv_import_stream(header, k, v, trace_ctx=trace_ctx):
            pass
        if out.get("timedOut"):
            raise DeadlineExceeded(out["error"])
        if "error" in out:
            raise RuntimeError(out["error"])
        return {key: out[key]
                for key in ("tokens", "text", "numTokens", "seconds")}

    def _idle(self) -> bool:
        # _requests is the engine's authoritative unfinished-request map —
        # it covers queued, slotted, AND mid-dispatch requests (queue depth
        # + free-slot counts have a window during prefill dispatch where
        # both read idle while a request is in flight).
        return super()._idle() and not self.engine._requests

    def _shutdown_engine(self):
        self.engine.stop()

    def stats(self) -> dict:
        """JSON stats view over the obs registry: every counter/gauge here
        reads the same instruments /metrics renders (shed_stats is a
        registry-counter view, the gauges are the registry's scrape-time
        callables) — one source of truth, two presentations."""
        import jax

        reg = self.registry
        ready, unready_why = self.readiness()
        return {
            "model": self.model_name,
            # Disaggregation role census: the gateway's two-stage router
            # reads this off every poll to build its prefill/decode pools.
            "role": self.role,
            "devices": [str(d) for d in jax.devices()],
            "numSlots": int(reg.get("kukeon_engine_slots_total").value()),
            "freeSlots": int(reg.get("kukeon_engine_slots_free").value()),
            "uptimeSeconds": round(
                reg.get("kukeon_cell_uptime_seconds").value(), 1),
            "totalTokens": self.total_tokens,
            "generatedTokens": int(
                reg.get("kukeon_engine_tokens_total").value()),
            "prefixCache": {"hits": self.engine.prefix_hits,
                            "misses": self.engine.prefix_misses,
                            "entries": len(self.engine._prefix_cache)},
            "tuning": {
                "decodeChunk": self.engine.decode_chunk,
                "kvCacheInt8": self.engine.kv_cache_int8,
                "kvPageTokens": self.engine.page_tokens,
                "fromProfile": self.engine.tune is not None,
            },
            # Serving mesh: chip count and axis layout this engine's jitted
            # programs are sharded over (meshChips == 1 means single-chip).
            # getattr: harness fakes duck-type the engine without a mesh.
            "mesh": ({
                "chips": int(self.engine.mesh.size),
                "shape": {ax: int(sz) for ax, sz
                          in self.engine.mesh.shape.items() if sz > 1},
                "kvSharded": bool(
                    any(self.engine._cache_shardings()[0].spec)),
            } if getattr(self.engine, "mesh", None) is not None else None),
            # Paged KV pool occupancy (0/0 on the legacy layout): what the
            # operator watches to size kvPageTokens / the pool.
            "kvPages": {
                "total": self.engine.kv_pool_pages,
                "inUse": (self.engine._pool.in_use
                          if self.engine._pool is not None else 0),
                "preemptions": int(reg.get(
                    "kukeon_preemptions_total").value(reason="kv_pressure")),
                "shedKvExhausted": self.engine.shed_stats["kv_exhausted"],
            },
            # Overload/lifecycle counters (the shed accounting the stress
            # tier asserts on): queueDepth is live, rejected/timedOut are
            # monotonic totals since boot.
            "queueDepth": int(reg.get("kukeon_engine_queue_depth").value()),
            # Unfinished engine requests (queued + slotted + mid-dispatch):
            # the gateway's rollout polls this to see a drain go idle, and
            # it is the truthful "busy" signal (queueDepth alone reads 0
            # while slots are full).
            "inflight": len(self.engine._requests),
            "maxPending": self.engine.max_pending,
            "rejected": self.engine.shed_stats["rejected"],
            "timedOut": self.engine.shed_stats["timed_out"],
            "ready": ready,
            "draining": self.draining,
            **({"unreadyReason": unready_why} if unready_why else {}),
        }

    def profile_layers(self, prefill_len: int | None = None,
                       decode_batch: int | None = None) -> dict:
        """Per-layer roofline profile of the live model
        (obs/profile.profile_layers), persisted next to the serving tune
        under the same ``model|backend|n_chips`` key. Degradation
        contract: an armed ``profile.layers`` fault or a backend without
        cost analysis yields recorded ``error`` entries in the returned
        profile (and skips persistence) — it never crashes the cell."""
        import jax

        from kukeon_tpu.obs import profile as obs_profile
        from kukeon_tpu.serving import tuning

        eng = self.engine
        eng._ensure_loaded()
        prof = obs_profile.profile_layers(
            eng.params, eng.cfg, eng.mesh,
            prefill_len=prefill_len or min(64, eng.max_seq_len - 1),
            decode_batch=decode_batch or eng.num_slots)
        key_args = (self.model_name, jax.default_backend(),
                    int(eng.mesh.size))
        prof["key"] = tuning.profile_key(*key_args)
        if not prof.get("errors"):
            prof["path"] = tuning.save_layer_profile(*key_args, prof)
        return prof


@sanitize.guard_class
class EmbeddingCell(LifecycleMixin):
    """Embedding-model serving cell (bge-base): /v1/embed instead of
    /v1/generate; same health/stats seams as the decoder cell so the
    reconciler treats both cell flavors identically."""

    def __init__(self, model: str, *, batch_size: int = 16,
                 pooling: str = "cls", checkpoint: str | None = None,
                 dtype: str | None = None, seed: int = 0,
                 chips: int | None = None):
        import dataclasses

        import jax

        _enable_compilation_cache()

        from kukeon_tpu.models import bert
        from kukeon_tpu.parallel import auto_mesh_shape, make_mesh, serving_mesh
        from kukeon_tpu.serving import EmbeddingEngine

        _register_models()
        cfg = EMBEDDING_MODELS[model]()
        if dtype:
            import jax.numpy as jnp

            cfg = dataclasses.replace(cfg, dtype=getattr(jnp, dtype))
        if chips is not None:
            try:
                mesh = serving_mesh(chips)
            except ValueError as e:
                raise SystemExit(f"--chips {chips}: {e}") from e
        else:
            n = len(jax.devices())
            shape = auto_mesh_shape(n)
            mesh = make_mesh(data=shape["data"], tensor=shape["tensor"])
        if checkpoint:
            params = self._load_checkpoint(checkpoint, cfg)
        else:
            params = bert.init_params(jax.random.key(seed), cfg)

        self.model_name = model
        self.cfg = cfg
        self.engine = EmbeddingEngine(cfg, params, mesh,
                                      batch_size=batch_size, pooling=pooling)
        # The checkpoint's real tokenizer when it ships one (BASELINE config
        # 5 text inputs must not be byte-mangled for a real bge model);
        # byte fallback otherwise — same rule as the decoder cell.
        from kukeon_tpu.serving.tokenizer import load_tokenizer

        self.tokenizer = load_tokenizer(checkpoint)
        self.started_at = time.time()
        self._stats_lock = sanitize.lock("EmbeddingCell._stats_lock")
        self.total_sequences = 0   # guarded-by: _stats_lock
        self._init_lifecycle()
        self._init_cell_obs(Registry(), kind="embedding")
        self.registry.gauge(
            "kukeon_embed_batch_size",
            "Embedding micro-batch grid size.").set(batch_size)
        self.registry.register_collector(self._obs_collect)

    def _obs_collect(self):
        yield ("kukeon_embed_sequences_total", "counter",
               "Sequences embedded since boot.",
               [({}, float(self.total_sequences))])

    @staticmethod
    def _load_checkpoint(path: str, cfg):
        import jax
        import orbax.checkpoint as ocp

        from kukeon_tpu.models import bert

        abstract = jax.eval_shape(
            lambda k: bert.init_params(k, cfg), jax.random.key(0)
        )
        return ocp.StandardCheckpointer().restore(path, abstract)

    def warmup(self, prompt_len: int = 64):
        self.engine.warmup((prompt_len,))

    def embed(self, req: dict) -> dict:
        if "inputTokens" in req:
            prompts = [np.asarray(p, np.int32) for p in req["inputTokens"]]
        elif "inputs" in req:
            texts = req["inputs"]
            if isinstance(texts, str):
                texts = [texts]
            prompts = [np.asarray(self.tokenizer.encode(x) or [1], np.int32)
                       for x in texts]
        else:
            raise ValueError("need inputs or inputTokens")
        t0 = time.monotonic()
        vecs = self.engine.embed_batch(prompts)
        dt = time.monotonic() - t0
        with self._stats_lock:
            self.total_sequences += len(prompts)
        # One timeline record per embed batch: the embedding flavor's
        # "step" — same /v1/timeline schema spine as the decoder cell.
        self.recorder.record({
            "wall_s": round(dt, 6),
            "occupancy": len(prompts),
            "tokens": int(sum(p.size for p in prompts)),
            "programs": {"embed": round(dt, 6)},
            "traces": [],
        })
        return {
            "embeddings": [v.tolist() for v in vecs],
            "dim": int(vecs.shape[1]) if len(prompts) else self.cfg.hidden_size,
            "numSequences": len(prompts),
            "seconds": round(dt, 4),
        }

    def stats(self) -> dict:
        import jax

        # ready/draining/uptime parity with the decoder cell's stats: a
        # scraper (or the reconciler) treats both cell flavors uniformly.
        ready, unready_why = self.readiness()
        return {
            "model": self.model_name,
            "kind": "embedding",
            "devices": [str(d) for d in jax.devices()],
            "batchSize": self.engine.batch_size,
            "uptimeSeconds": round(
                self.registry.get("kukeon_cell_uptime_seconds").value(), 1),
            "totalSequences": self.total_sequences,
            "ready": ready,
            "draining": self.draining,
            **({"unreadyReason": unready_why} if unready_why else {}),
        }


@sanitize.guard_class
class EngineWatchdog(threading.Thread):
    """Detects a wedged TPU runtime behind a stuck engine and gets the cell
    restarted instead of hanging forever.

    Failure mode (STATUS.md r4/r5): a wedged libtpu/tunnel accepts work and
    then blocks a device call indefinitely — the engine driver thread is
    stuck inside jit dispatch, no Python-level timeout fires, and the cell
    sits Ready while serving nobody. The watchdog watches the engine's
    progress heartbeat; once work has been outstanding with no progress past
    ``stall_budget_s`` it consults ``devices.probe_tpu_runtime`` (a killable
    subprocess probe, so it works even while this process's own runtime is
    stuck). A "wedged" verdict trips the watchdog: ``on_wedged`` runs (the
    cell flips unready and exits WEDGED_EXIT_CODE) and the runner's restart
    policy + stable chip grant bring the cell back on its own chips. Any
    other verdict re-arms the budget — a long compile or a giant prefill is
    slow, not wedged, and must not get the cell killed.
    """

    def __init__(self, engine, *, stall_budget_s: float,
                 probe=None, on_wedged=None, interval_s: float | None = None,
                 probe_timeout_s: float = 20.0,
                 registry: Registry | None = None):
        super().__init__(daemon=True, name="tpu-watchdog")
        self.engine = engine
        self.stall_budget_s = stall_budget_s
        self.probe = probe
        self.on_wedged = on_wedged
        self.interval_s = interval_s if interval_s is not None else max(
            0.5, stall_budget_s / 4)
        self.probe_timeout_s = probe_timeout_s
        self.tripped = False
        self.last_verdict: tuple[str, str] | None = None
        self.probes = 0
        self._halt = sanitize.event("EngineWatchdog._halt")
        # Watchdog activity on the cell's scrape: every probe is a sign
        # the engine stalled past budget; a trip precedes the exit-86.
        reg = registry if registry is not None else Registry()
        self._m_probes = reg.counter(
            "kukeon_watchdog_probes_total",
            "TPU runtime probes fired after an engine stall.",
            labels=("verdict",))
        self._m_trips = reg.counter(
            "kukeon_watchdog_trips_total",
            "Wedged verdicts (the cell exits for restart right after).")

    def stop(self):
        self._halt.set()

    def run(self):
        probe = self.probe
        if probe is None:
            from kukeon_tpu.runtime.devices import probe_tpu_runtime
            probe = probe_tpu_runtime
        while not self._halt.wait(self.interval_s):
            if self.engine.stalled_s() < self.stall_budget_s:
                continue
            self.probes += 1
            status, detail = probe(timeout_s=self.probe_timeout_s)
            self.last_verdict = (status, detail)
            self._m_probes.inc(verdict=status)
            if status == "wedged":
                self.tripped = True
                self._m_trips.inc()
                if self.on_wedged is not None:
                    self.on_wedged(detail)
                return
            # Runtime answers: the stall is compute- or host-side. Treat the
            # probe completion as progress so the next probe waits a full
            # budget (no probe hammering during a legitimately long step).
            # Under the engine's admission lock: last_progress is
            # _lock-guarded state (kukesan surfaced this write as the
            # tree's one cross-thread unlocked heartbeat write).
            with self.engine._lock:
                self.engine.last_progress = time.monotonic()


def make_handler(cell: ServingCell):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *a):
            sys.stderr.write("serving-cell: " + fmt % a + "\n")

        def _send(self, code: int, obj: dict,
                  headers: dict[str, str] | None = None):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _send_text(self, code: int, text: str, content_type: str):
            self._send_bytes(code, text.encode(), content_type)

        def _send_bytes(self, code: int, body: bytes, content_type: str):
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _reject(self, e: RejectedError):
            """429 (engine queue full — retry against THIS cell) or 503
            (lifecycle: warming up/draining/wedged — retry elsewhere), both
            with Retry-After so clients back off instead of hammering."""
            import math

            ok, _why = (cell.readiness() if hasattr(cell, "readiness")
                        else (True, None))
            code = 429 if ok else 503
            self._send(code, {"error": str(e), "retryAfterSeconds":
                              e.retry_after_s},
                       headers={"Retry-After":
                                str(max(1, math.ceil(e.retry_after_s)))})

        def do_GET(self):
            from urllib.parse import parse_qs, urlsplit

            parts = urlsplit(self.path)
            path = parts.path
            if path == "/v1/health" or path == "/healthz":
                # Liveness: answering at all is the signal.
                self._send(200, {"status": "ok", "model": cell.model_name})
            elif path == "/readyz":
                ok, why = (cell.readiness() if hasattr(cell, "readiness")
                           else (True, None))
                if ok:
                    self._send(200, {"ready": True})
                else:
                    self._send(503, {"ready": False, "reason": why})
            elif path == "/v1/stats":
                self._send(200, cell.stats())
            elif path == "/metrics":
                # Prometheus text exposition over the cell's registry
                # (engine histograms + lifecycle gauges + fault counters).
                self._send_text(200, expo.render(cell.registry),
                                expo.CONTENT_TYPE)
            elif path == "/v1/trace":
                tracer = getattr(getattr(cell, "engine", None),
                                 "tracer", None)
                if tracer is None:
                    self._send(404, {"error": "this cell records no "
                                              "request traces"})
                    return
                q = parse_qs(parts.query)
                if "trace_id" in q:
                    # Distributed-trace pull: the daemon's Traces RPC (and
                    # `kuke trace <id>`) fan this out to every cell and
                    # union the spans into one timeline.
                    self._send(200, {"spans":
                                     tracer.for_trace(q["trace_id"][0])})
                    return
                if "request_id" in q:
                    # Exact-match pull: a slow request found in the logs is
                    # fetched directly instead of paging the ?n=K tail.
                    try:
                        rid = int(q["request_id"][0])
                    except ValueError:
                        self._send(400,
                                   {"error": "request_id must be an integer"})
                        return
                    self._send(200, {"spans": tracer.for_request(rid)})
                    return
                try:
                    n = int(q.get("n", ["50"])[0])
                except ValueError:
                    self._send(400, {"error": "n must be an integer"})
                    return
                self._send(200, {"spans": tracer.recent(n)})
            elif path == "/v1/profile":
                profiler = getattr(cell, "profiler", None)
                if profiler is None:
                    self._send(404, {"error": "this cell has no profiler"})
                    return
                self._send(200, {"captures": profiler.list(),
                                 "dir": profiler.base_dir,
                                 "keep": profiler.keep})
            elif path == "/v1/timeline":
                # The step flight recorder: last-N engine-loop step
                # records, oldest first. The daemon's Timeline RPC (and
                # `kuke timeline <cell>`) federate this across the fleet.
                recorder = getattr(cell, "recorder", None)
                if recorder is None:
                    self._send(404, {"error": "this cell records no "
                                              "step timeline"})
                    return
                q = parse_qs(parts.query)
                try:
                    n = int(q.get("n", ["50"])[0])
                except ValueError:
                    self._send(400, {"error": "n must be an integer"})
                    return
                self._send(200, {"steps": recorder.snapshot(n),
                                 "dropped": recorder.dropped,
                                 "capacity": recorder.capacity})
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path == "/drain":
                started = (cell.begin_drain()
                           if hasattr(cell, "begin_drain") else False)
                self._send(200, {"draining": True, "started": started})
                return
            if self.path == "/v1/profile":
                # Start an on-demand device-profile capture. Deliberately
                # exempt from admission: profiling a draining or overloaded
                # cell is exactly when an operator wants a trace.
                profiler = getattr(cell, "profiler", None)
                if profiler is None:
                    self._send(404, {"error": "this cell has no profiler"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    if req.get("layers"):
                        # Per-layer roofline profile (synchronous — the
                        # lowering loop runs in-request). Errors inside
                        # the loop (including the armed profile.layers
                        # fault) come back RECORDED in the profile body;
                        # the cell keeps serving either way.
                        if not hasattr(cell, "profile_layers"):
                            self._send(404, {"error": "this cell has no "
                                                      "layer profiler"})
                            return
                        prof = cell.profile_layers(
                            prefill_len=req.get("prefillLen"),
                            decode_batch=req.get("decodeBatch"))
                        self._send(200, prof)
                        return
                    rec = profiler.start(float(req.get("durationMs", 1000)))
                    self._send(200, {"started": True, "capture": rec})
                except ProfileBusy as e:
                    # Single-flight: one capture at a time (409 Conflict).
                    self._send(409, {"error": str(e)})
                except (ValueError, TypeError) as e:
                    self._send(400, {"error": str(e)})
                except Exception as e:  # noqa: BLE001 — server must keep serving
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})
                return
            if self.path in ("/v1/kv/export", "/v1/kv/import"):
                self._kv_handoff()
                return
            routes = {}
            if hasattr(cell, "generate"):
                routes["/v1/generate"] = cell.generate
            if hasattr(cell, "embed"):
                routes["/v1/embed"] = cell.embed
            fn = routes.get(self.path)
            if fn is None:
                self._send(404, {"error": f"no route {self.path}; "
                                          f"this cell serves {sorted(routes)}"})
                return
            tracked = False
            try:
                faults.maybe_fail("cell.http")
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                # Distributed trace context: the gateway (or any client)
                # propagates a traceparent header; the engine's span joins
                # that trace instead of rooting a fresh one. Malformed
                # headers degrade to a fresh root trace.
                ctx = obs_trace.parse_traceparent(
                    self.headers.get(obs_trace.TRACEPARENT_HEADER))
                # Lifecycle admission first (503), then the engine's own
                # queue-full shedding fires inside submit (429).
                if hasattr(cell, "check_admission"):
                    cell.check_admission()
                if hasattr(cell, "_inflight_inc"):
                    cell._inflight_inc()
                    tracked = True
                if (self.path == "/v1/generate" and req.get("stream")
                        and hasattr(cell, "generate_stream")):
                    self._stream(cell.generate_stream(req, trace_ctx=ctx))
                    return
                if self.path == "/v1/generate" and hasattr(cell, "generate"):
                    self._send(200, cell.generate(req, trace_ctx=ctx))
                    return
                self._send(200, fn(req))
            except RejectedError as e:
                self._reject(e)
            except DeadlineExceeded as e:
                self._send(504, {"error": str(e), "timedOut": True})
            except ValueError as e:
                self._send(400, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — server must keep serving
                self._send(500, {"error": f"{type(e).__name__}: {e}"})
            finally:
                if tracked:
                    cell._inflight_dec()

        def _kv_handoff(self):
            """The disaggregated-serving KV handoff surface:

            ``POST /v1/kv/export`` — JSON generate-shaped body in, binary
            KV block (header line + raw K/V rows) out; prefill only, no
            decode slot consumed.
            ``POST /v1/kv/import`` — binary KV block in, the continuation
            out (JSON, or ndjson when the header says ``stream``). Same
            admission/shed semantics as /v1/generate: lifecycle refusals
            are 503, engine queue pressure is 429 + Retry-After — the
            gateway's fallback logic keys off exactly those."""
            if not hasattr(cell, "kv_export"):
                self._send(404, {"error": "this cell serves no KV handoff"})
                return
            tracked = False
            try:
                faults.maybe_fail("cell.http")
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                ctx = obs_trace.parse_traceparent(
                    self.headers.get(obs_trace.TRACEPARENT_HEADER))
                cell.check_admission()
                cell._inflight_inc()
                tracked = True
                if self.path == "/v1/kv/export":
                    req = json.loads(body or b"{}")
                    self._send_bytes(200, cell.kv_export(req, trace_ctx=ctx),
                                     KV_CONTENT_TYPE)
                    return
                header, k, v = unpack_kv(body)
                if header.get("stream"):
                    self._stream(
                        cell.kv_import_stream(header, k, v, trace_ctx=ctx))
                    return
                self._send(200, cell.kv_import(header, k, v, trace_ctx=ctx))
            except RejectedError as e:
                self._reject(e)
            except DeadlineExceeded as e:
                self._send(504, {"error": str(e), "timedOut": True})
            except ValueError as e:
                self._send(400, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — server must keep serving
                self._send(500, {"error": f"{type(e).__name__}: {e}"})
            finally:
                if tracked:
                    cell._inflight_dec()

        def _stream(self, gen):
            """Newline-delimited JSON, framed by connection close (the
            handler speaks HTTP/1.0). The first record is pulled before
            headers go out so parse errors still surface as a clean 400."""
            import itertools

            try:
                first = next(gen)
            except RejectedError as e:
                # The engine sheds inside submit(), which runs lazily at the
                # first pull — headers are not out yet, so the rejection can
                # still travel as a clean 429/503.
                self._reject(e)
                return
            except ValueError as e:
                self._send(400, {"error": str(e)})
                return
            except StopIteration:
                self._send(500, {"error": "empty stream"})
                return
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.end_headers()
            try:
                for obj in itertools.chain([first], gen):
                    self.wfile.write((json.dumps(obj) + "\n").encode())
                    self.wfile.flush()
            except OSError:
                pass   # client went away mid-stream; nothing to tell it
            except Exception as e:  # noqa: BLE001 — headers are already out
                # A second status line (do_POST's 500 path) would land
                # inside the open ndjson body and corrupt the stream; the
                # in-band terminal error line is the protocol here.
                try:
                    self.wfile.write(
                        (json.dumps({"error": f"{type(e).__name__}: {e}"})
                         + "\n").encode())
                    self.wfile.flush()
                except OSError:
                    pass

    return Handler


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kukeon-serving-cell")
    ap.add_argument("--model", required=True)
    ap.add_argument("--port", type=int, default=9000)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--num-slots", type=int, default=8)
    ap.add_argument("--max-seq-len", type=int, default=None)
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--dtype", default=None)
    # None (flag absent) lets the persisted autotune profile decide; the
    # explicit flag always wins (serving/tuning.py).
    ap.add_argument("--kv-cache-int8", action="store_true", default=None)
    ap.add_argument("--decode-chunk", type=int, default=None)
    # Paged KV cache (ModelSpec kvPageTokens): > 0 = page size in KV rows,
    # 0 = pin the legacy contiguous layout, absent = profile decides.
    ap.add_argument("--kv-page-tokens", type=int, default=None)
    # Disaggregated serving role (ModelSpec role): what the gateway's
    # two-stage router reads off /v1/stats. Policy, not capability — every
    # role keeps the full engine.
    ap.add_argument("--role", choices=("mixed", "prefill", "decode"),
                    default="mixed")
    # Serving mesh size (ModelSpec chips): exactly N visible chips, all on
    # the tensor axis. Absent = every visible device, auto-factorized —
    # the pre-multi-chip behavior.
    ap.add_argument("--chips", type=int, default=None)
    ap.add_argument("--no-warmup", action="store_true")
    # Admission control: bound the pending queue (shed with 429 past it)
    # and default every request to a deadline (expired requests free their
    # slot and answer in-band). 0 disables either.
    ap.add_argument("--max-pending", type=int, default=64)
    ap.add_argument("--deadline-s", type=float, default=0.0)
    # SLO objectives (ModelSpec sloTtftP95Ms / sloAvailability): drive the
    # kukeon_slo_* burn-rate gauges on /metrics. 0 = use the loose default.
    ap.add_argument("--slo-ttft-p95-ms", type=float, default=0.0)
    ap.add_argument("--slo-availability", type=float, default=0.0)
    args = ap.parse_args(argv)

    _register_models()

    def build():
        if args.model in EMBEDDING_MODELS:
            cell = EmbeddingCell(args.model, batch_size=args.num_slots,
                                 checkpoint=args.checkpoint, dtype=args.dtype,
                                 chips=args.chips)
            if not args.no_warmup:
                cell.warmup()
            return cell
        cell = ServingCell(
            args.model, num_slots=args.num_slots, max_seq_len=args.max_seq_len,
            checkpoint=args.checkpoint, dtype=args.dtype,
            kv_cache_int8=args.kv_cache_int8, decode_chunk=args.decode_chunk,
            kv_page_tokens=args.kv_page_tokens,
            max_pending=args.max_pending or None,
            deadline_s=args.deadline_s or None,
            slo_ttft_p95_ms=args.slo_ttft_p95_ms or None,
            slo_availability=args.slo_availability or None,
            role=args.role, chips=args.chips,
        )
        # Warmup before the engine thread starts: step() is single-driver.
        if not args.no_warmup:
            cell.warmup()
        cell.engine.start()
        return cell

    try:
        cell = build()
    except Exception as e:  # noqa: BLE001 — one self-heal attempt
        # A poisoned persistent-cache entry (stale AOT vs rolled libtpu,
        # truncated write) would otherwise crash-loop the cell forever under
        # restartPolicy: always. Bust the cache and recompile once; rethrow
        # if the failure had nothing to do with the cache.
        if not _bust_compilation_cache():
            raise
        print(f"serving-cell: init failed ({type(e).__name__}: {e}); "
              "busted persistent compilation cache, retrying once",
              file=sys.stderr, flush=True)
        cell = build()
    server = ThreadingHTTPServer((args.host, args.port), make_handler(cell))
    # /readyz goes true only now: weights loaded, warmup done, server bound.
    cell.on_drained = server.shutdown
    if isinstance(cell, ServingCell):
        # Close out the cold-start trace: kukeon_cold_start_seconds (+ the
        # per-phase breakdown) lands on /metrics and the boot span joins
        # the trace ring — bench.py's cold-start phase reads both.
        cell.finish_boot()
    cell.mark_ready()

    # SIGTERM = drain (the runner's stop path sends it with a grace window):
    # stop admitting, finish in-flight, exit 0. A second SIGTERM (or the
    # runner's SIGKILL after the grace) still kills immediately.
    import signal as _signal

    _signal.signal(_signal.SIGTERM, lambda *_a: cell.begin_drain())

    # TPU watchdog: a stuck engine step past the stall budget, confirmed
    # wedged by the runtime probe, exits WEDGED_EXIT_CODE so the restart
    # policy recovers the cell (same chip grant, runner._chip_slices).
    watchdog = None
    budget = float(os.environ.get(WATCHDOG_ENV, "120") or 0)
    if budget > 0 and isinstance(cell, ServingCell):

        def _wedged(detail: str):
            cell.mark_unready(f"TPU runtime wedged: {detail}")
            print(f"serving-cell: watchdog tripped — {detail}; exiting "
                  f"{WEDGED_EXIT_CODE} for restart", file=sys.stderr,
                  flush=True)
            os._exit(WEDGED_EXIT_CODE)

        watchdog = EngineWatchdog(
            cell.engine, stall_budget_s=budget, on_wedged=_wedged,
            probe_timeout_s=float(
                os.environ.get(WATCHDOG_PROBE_TIMEOUT_ENV, "20") or 20),
            registry=cell.registry,
        )
        watchdog.start()

    print(f"serving-cell: {args.model} ready on {args.host}:{args.port}", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if watchdog is not None:
            watchdog.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
