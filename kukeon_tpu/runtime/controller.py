"""Controller: the desired-state -> actual-state brain.

Reference: internal/controller (controller.go:37-133, bootstrap.go, apply.go,
reconcile.go). Shared by the daemon and in-process CLI clients ("promotion"
path). Verbs: bootstrap, create/get/list/delete/purge per kind, start/stop/
kill cell, apply/delete documents (declarative), reconcile.
"""

from __future__ import annotations

import dataclasses
import os
import re
import time
from typing import Any

from kukeon_tpu.runtime import consts, model, naming
from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.api.wire import from_wire, to_wire
from kukeon_tpu.runtime.apply import parser, scheme
from kukeon_tpu.runtime.errors import (
    FailedPrecondition,
    InvalidArgument,
    KukeonError,
    NotFound,
)
from kukeon_tpu.runtime.runner import (
    OUTCOME_AUTO_DELETED,
    OUTCOME_STEADY,
    OUTCOME_VANISHED,
    Runner,
)
from kukeon_tpu.runtime.store import ResourceStore

BREAKING = "breaking"
COMPATIBLE = "compatible"
UNCHANGED = "unchanged"


@dataclasses.dataclass
class ApplyResult:
    kind: str = ""
    name: str = ""
    scope: str = ""
    action: str = ""     # created | updated | recreated | unchanged | pruned


class Controller:
    def __init__(self, store: ResourceStore, runner: Runner):
        self.store = store
        self.runner = runner

    # --- bootstrap (reference: bootstrap.go) -------------------------------

    def bootstrap(self) -> None:
        """Provision the default + system hierarchy. The daemon itself runs
        as a host process here (the reference containerizes kukeond as a
        system cell; with the process backend the daemon IS a host process
        already, so the system realm just reserves the namespace)."""
        for realm in (consts.DEFAULT_REALM, consts.SYSTEM_REALM):
            self.runner.ensure_realm(realm)
            self.runner.ensure_space(realm, consts.DEFAULT_SPACE)
            self.runner.ensure_stack(realm, consts.DEFAULT_SPACE, consts.DEFAULT_STACK)

    # --- scope verbs -------------------------------------------------------

    def create_realm(self, name: str, spec: t.RealmSpec | None = None) -> None:
        naming.validate_name(name, "realm")
        self.runner.ensure_realm(name, spec)

    def create_space(self, realm: str, name: str, spec: t.SpaceSpec | None = None) -> None:
        naming.validate_name(name, "space")
        self.runner.ensure_space(realm or consts.DEFAULT_REALM, name, spec)

    def create_stack(self, realm: str, space: str, name: str,
                     spec: t.StackSpec | None = None) -> None:
        naming.validate_name(name, "stack")
        self.runner.ensure_stack(
            realm or consts.DEFAULT_REALM, space or consts.DEFAULT_SPACE, name, spec
        )

    def get_realm(self, name: str) -> dict:
        return self.store.read_realm(name).to_json()

    def get_space(self, realm: str, name: str) -> dict:
        return self.store.read_space(realm, name).to_json()

    def get_stack(self, realm: str, space: str, name: str) -> dict:
        return self.store.read_stack(realm, space, name).to_json()

    def list_realms(self) -> list[str]:
        return self.store.list_realms()

    def list_spaces(self, realm: str) -> list[str]:
        return self.store.list_spaces(realm)

    def list_stacks(self, realm: str, space: str) -> list[str]:
        return self.store.list_stacks(realm, space)

    def delete_realm(self, name: str, purge: bool = False) -> None:
        if name == consts.SYSTEM_REALM:
            raise FailedPrecondition("refusing to delete the system realm")
        spaces = self.store.list_spaces(name)
        if spaces and not purge:
            raise FailedPrecondition(
                f"realm {name!r} has spaces {spaces}; purge to cascade"
            )
        for s in spaces:
            self.delete_space(name, s, purge=True)
        self._reclaim_volumes(name, None, None)
        self.store.ms.delete_tree(*self.store.realm_parts(name))

    def delete_space(self, realm: str, name: str, purge: bool = False) -> None:
        stacks = self.store.list_stacks(realm, name)
        if stacks and not purge:
            raise FailedPrecondition(
                f"space {name!r} has stacks {stacks}; purge to cascade"
            )
        for st in stacks:
            self.delete_stack(realm, name, st, purge=True)
        self._reclaim_volumes(realm, name, None)
        self.runner.teardown_space_network(realm, name)
        self.store.ms.delete_tree(*self.store.space_parts(realm, name))

    def delete_stack(self, realm: str, space: str, name: str, purge: bool = False) -> None:
        cells = self.store.list_cells(realm, space, name)
        if cells and not purge:
            raise FailedPrecondition(
                f"stack {name!r} has cells {cells}; purge to cascade"
            )
        for c in cells:
            self.runner.delete_cell(realm, space, name, c, force=True)
        # Volumes with reclaimPolicy=retain survive scope deletion.
        self._reclaim_volumes(realm, space, name)
        self.store.ms.delete_tree(*self.store.stack_parts(realm, space, name))

    def _reclaim_volumes(self, realm: str, space: str | None, stack: str | None) -> None:
        """reclaimPolicy=retain volumes survive the owning scope's cascade
        purge (reference: volume.go:61-83): their record + data are re-homed
        to the store's `retained/` area before the scope tree is removed."""
        import shutil

        for vol in self.store.list_scoped(consts.VOLUMES_DIR, realm, space, stack):
            doc = self.store.read_scoped(consts.VOLUMES_DIR, realm, space, stack, vol)
            if doc and doc.get("reclaimPolicy") == "retain":
                scope = "-".join(x for x in (realm, space, stack) if x)
                dest_dir = self.store.ms.ensure_dir("retained", f"{scope}-{vol}")
                data_dir = doc.get("dataDir")
                if data_dir and os.path.isdir(data_dir):
                    dest_data = os.path.join(dest_dir, "data")
                    if not os.path.exists(dest_data):
                        shutil.move(data_dir, dest_data)
                    doc["dataDir"] = dest_data
                doc["retainedFrom"] = scope
                self.store.ms.write_json(doc, "retained", f"{scope}-{vol}", "volume.json")
            self.store.delete_scoped(consts.VOLUMES_DIR, realm, space, stack, vol)

    # --- cell verbs --------------------------------------------------------

    def create_cell(self, doc: t.Document, start: bool = True) -> dict:
        doc = scheme.normalize(doc)
        parser.validate_document(doc)
        md = doc.metadata
        # Auto-provision intermediate scopes for imperative creates
        # (the reference's imperative create does the same defaulting).
        self.runner.ensure_realm(md.realm)
        self.runner.ensure_space(md.realm, md.space)
        self.runner.ensure_stack(md.realm, md.space, md.stack)
        rec = model.cell_record_from_doc(doc)
        rec = self.runner.create_cell(rec)
        if start:
            rec = self.runner.start_cell(md.realm, md.space, md.stack, md.name)
        return rec.to_json()

    def get_cell(self, realm: str, space: str, stack: str, name: str) -> dict:
        rec, _ = self.runner.refresh_cell(realm, space, stack, name)
        if rec is None:
            raise NotFound(f"cell {realm}/{space}/{stack}/{name} not found")
        d = rec.to_json()
        # Live resource usage per container (reference: cgroup/task metrics
        # surfaced through `kuke status`/`get`, internal/ctr/cgroups.go:484).
        metrics = self.runner.cell_metrics(rec)
        if metrics:
            d["metrics"] = metrics
        return d

    def list_cells(self, realm: str, space: str | None = None,
                   stack: str | None = None) -> list[dict]:
        out = []
        spaces = [space] if space else self.store.list_spaces(realm)
        for s in spaces:
            stacks = [stack] if stack else self.store.list_stacks(realm, s)
            for st in stacks:
                for c in self.store.list_cells(realm, s, st):
                    try:
                        out.append(self.store.read_cell(realm, s, st, c).to_json())
                    except NotFound:
                        continue
        return out

    def start_cell(self, realm: str, space: str, stack: str, name: str) -> dict:
        return self.runner.start_cell(realm, space, stack, name).to_json()

    def stop_cell(self, realm: str, space: str, stack: str, name: str) -> dict:
        return self.runner.stop_cell(realm, space, stack, name).to_json()

    def kill_cell(self, realm: str, space: str, stack: str, name: str) -> dict:
        return self.runner.kill_cell(realm, space, stack, name).to_json()

    def delete_cell(self, realm: str, space: str, stack: str, name: str,
                    force: bool = False) -> None:
        self.runner.delete_cell(realm, space, stack, name, force=force)

    # --- scoped resource verbs ---------------------------------------------

    def put_secret(self, doc: t.Document) -> None:
        doc = scheme.normalize(doc)
        md = doc.metadata
        self._ensure_scope(md)
        payload = {"data": dict(doc.spec.data), "labels": dict(md.labels),
                   "createdAt": time.time()}
        self.store.write_scoped(consts.SECRETS_DIR, md.realm, md.space, md.stack,
                                md.name, payload)

    def get_secret_names(self, realm: str, space: str | None, stack: str | None) -> list[str]:
        return self.store.list_scoped(consts.SECRETS_DIR, realm, space, stack)

    def delete_secret(self, realm: str, space: str | None, stack: str | None, name: str) -> None:
        if not self.store.delete_scoped(consts.SECRETS_DIR, realm, space, stack, name):
            raise NotFound(f"secret {name!r} not found")

    def put_blueprint(self, doc: t.Document) -> None:
        doc = scheme.normalize(doc)
        md = doc.metadata
        self._ensure_scope(md)
        payload = {"spec": to_wire(doc.spec), "labels": dict(md.labels),
                   "createdAt": time.time()}
        self.store.write_scoped(consts.BLUEPRINTS_DIR, md.realm, md.space, md.stack,
                                md.name, payload)

    def get_blueprint(self, realm: str, space: str | None, stack: str | None,
                      name: str) -> t.CellBlueprintSpec:
        doc = self.store.resolve_scoped(consts.BLUEPRINTS_DIR, realm, space, stack, name)
        if doc is None:
            raise NotFound(f"blueprint {name!r} not found")
        return from_wire(t.CellBlueprintSpec, doc["spec"])

    def list_blueprints(self, realm: str, space: str | None, stack: str | None) -> list[str]:
        return self.store.list_scoped(consts.BLUEPRINTS_DIR, realm, space, stack)

    def delete_blueprint(self, realm: str, space: str | None, stack: str | None, name: str) -> None:
        if not self.store.delete_scoped(consts.BLUEPRINTS_DIR, realm, space, stack, name):
            raise NotFound(f"blueprint {name!r} not found")

    def put_config(self, doc: t.Document) -> None:
        doc = scheme.normalize(doc)
        md = doc.metadata
        self._ensure_scope(md)
        payload = {"spec": to_wire(doc.spec), "labels": dict(md.labels),
                   "createdAt": time.time()}
        self.store.write_scoped(consts.CONFIGS_DIR, md.realm, md.space, md.stack,
                                md.name, payload)

    def get_config(self, realm: str, space: str | None, stack: str | None,
                   name: str) -> t.CellConfigSpec:
        doc = self.store.resolve_scoped(consts.CONFIGS_DIR, realm, space, stack, name)
        if doc is None:
            raise NotFound(f"cellconfig {name!r} not found")
        return from_wire(t.CellConfigSpec, doc["spec"])

    def list_configs(self, realm: str, space: str | None, stack: str | None) -> list[str]:
        return self.store.list_scoped(consts.CONFIGS_DIR, realm, space, stack)

    def delete_config(self, realm: str, space: str | None, stack: str | None, name: str) -> None:
        if not self.store.delete_scoped(consts.CONFIGS_DIR, realm, space, stack, name):
            raise NotFound(f"cellconfig {name!r} not found")

    def put_volume(self, doc: t.Document) -> None:
        doc = scheme.normalize(doc)
        md = doc.metadata
        self._ensure_scope(md)
        data_dir = self.store.ms.ensure_dir(
            *self.store.scope_parts(md.realm, md.space, md.stack),
            consts.VOLUMES_DIR + "-data", md.name,
        )
        payload = {"reclaimPolicy": doc.spec.reclaim_policy, "dataDir": data_dir,
                   "labels": dict(md.labels), "createdAt": time.time()}
        self.store.write_scoped(consts.VOLUMES_DIR, md.realm, md.space, md.stack,
                                md.name, payload)

    def list_volumes(self, realm: str, space: str | None, stack: str | None) -> list[str]:
        return self.store.list_scoped(consts.VOLUMES_DIR, realm, space, stack)

    def delete_volume(self, realm: str, space: str | None, stack: str | None,
                      name: str) -> None:
        if not self.store.delete_scoped(consts.VOLUMES_DIR, realm, space, stack, name):
            raise NotFound(f"volume {name!r} not found")
        self.store.ms.delete_tree(
            *self.store.scope_parts(realm, space, stack), consts.VOLUMES_DIR + "-data", name
        )

    def _ensure_scope(self, md: t.Metadata) -> None:
        self.runner.ensure_realm(md.realm)
        if md.space:
            self.runner.ensure_space(md.realm, md.space)
        if md.stack:
            self.runner.ensure_stack(md.realm, md.space, md.stack)

    # --- declarative apply (reference: apply.go:96-445) --------------------

    def apply_documents(self, blob: str, team: str | None = None,
                        prune: bool = False) -> list[ApplyResult]:
        docs = parser.parse_documents(blob)
        for d in docs:
            if d.kind in (t.KIND_SERVER_CONFIGURATION, t.KIND_CLIENT_CONFIGURATION):
                raise InvalidArgument(f"{d.kind} is a local configuration file, not appliable")
        docs = parser.sort_documents(docs)
        results = []
        if team:
            for d in docs:
                d.metadata.labels[consts.LABEL_TEAM] = team
        for d in docs:
            results.append(self._apply_one(d))
        if team and prune:
            results.extend(self._prune_team(team, docs))
        return results

    def delete_documents(self, blob: str) -> list[ApplyResult]:
        docs = parser.sort_documents(parser.parse_documents(blob), reverse=True)
        results = []
        for d in docs:
            d = scheme.normalize(d)
            md = d.metadata
            try:
                if d.kind == t.KIND_CELL:
                    self.runner.delete_cell(md.realm, md.space, md.stack, md.name, force=True)
                elif d.kind == t.KIND_SECRET:
                    self.delete_secret(md.realm, md.space, md.stack, md.name)
                elif d.kind == t.KIND_CELL_BLUEPRINT:
                    self.delete_blueprint(md.realm, md.space, md.stack, md.name)
                elif d.kind == t.KIND_CELL_CONFIG:
                    self.delete_config(md.realm, md.space, md.stack, md.name)
                elif d.kind == t.KIND_VOLUME:
                    self.delete_volume(md.realm, md.space, md.stack, md.name)
                elif d.kind == t.KIND_STACK:
                    self.delete_stack(md.realm, md.space, md.name, purge=True)
                elif d.kind == t.KIND_SPACE:
                    self.delete_space(md.realm, md.name, purge=True)
                elif d.kind == t.KIND_REALM:
                    self.delete_realm(md.name, purge=True)
                action = "deleted"
            except NotFound:
                action = "absent"
            results.append(ApplyResult(kind=d.kind, name=md.name,
                                       scope=self._scope_str(md), action=action))
        return results

    def _apply_one(self, d: t.Document) -> ApplyResult:
        d = scheme.normalize(d)
        md = d.metadata
        res = ApplyResult(kind=d.kind, name=md.name, scope=self._scope_str(md))
        if d.kind == t.KIND_REALM:
            existed = self.store.ms.exists(*self.store.realm_parts(md.name), "realm.json")
            self.runner.ensure_realm(md.name, d.spec, md.labels)
            res.action = "unchanged" if existed else "created"
        elif d.kind == t.KIND_SPACE:
            existed = self.store.ms.exists(*self.store.space_parts(md.realm, md.name), "space.json")
            self.runner.ensure_space(md.realm, md.name, d.spec, md.labels)
            res.action = "updated" if existed else "created"
        elif d.kind == t.KIND_STACK:
            existed = self.store.ms.exists(*self.store.stack_parts(md.realm, md.space, md.name), "stack.json")
            self.runner.ensure_stack(md.realm, md.space, md.name, d.spec, md.labels)
            res.action = "unchanged" if existed else "created"
        elif d.kind == t.KIND_CELL:
            res.action = self._apply_cell(d)
        elif d.kind == t.KIND_SECRET:
            self.put_secret(d)
            res.action = "applied"
        elif d.kind == t.KIND_CELL_BLUEPRINT:
            self.put_blueprint(d)
            res.action = "applied"
        elif d.kind == t.KIND_CELL_CONFIG:
            self.put_config(d)
            res.action = "applied"
            self.materialize_config(md.realm, md.space, md.stack, md.name)
        elif d.kind == t.KIND_VOLUME:
            self.put_volume(d)
            res.action = "applied"
        else:
            raise InvalidArgument(f"cannot apply kind {d.kind}")
        return res

    def _apply_cell(self, d: t.Document) -> str:
        md = d.metadata
        self.runner.ensure_realm(md.realm)
        self.runner.ensure_space(md.realm, md.space)
        self.runner.ensure_stack(md.realm, md.space, md.stack)
        new_rec = model.cell_record_from_doc(d)
        try:
            old = self.store.read_cell(md.realm, md.space, md.stack, md.name)
        except NotFound:
            self.runner.create_cell(new_rec)
            self.runner.start_cell(md.realm, md.space, md.stack, md.name)
            return "created"
        verdict = diff_cell_spec(old.spec, d.spec)
        if verdict == UNCHANGED and old.labels == new_rec.labels:
            return "unchanged"
        if verdict == BREAKING:
            # Recreate: stop + delete + create + start (reference: breaking
            # fields are baked into cell setup; apply/diff.go:594-600).
            self.runner.delete_cell(md.realm, md.space, md.stack, md.name, force=True)
            new_rec.generation = old.generation + 1
            self.runner.create_cell(new_rec)
            self.runner.start_cell(md.realm, md.space, md.stack, md.name)
            return "recreated"
        # Compatible: update spec/labels in place, keep workloads running.
        old.spec = d.spec
        old.labels = new_rec.labels
        old.provenance = new_rec.provenance
        old.generation += 1
        # Ports are a compatible field, so the host-port claims must follow
        # the update: re-claim (rejecting on conflict) and drop stale claims.
        self.runner.claim_host_ports(old)
        self.store.write_cell(old)
        return "updated"

    def _prune_team(self, team: str, applied: list[t.Document]) -> list[ApplyResult]:
        """Delete team-labeled objects not present in this apply
        (reference: apply.go:363-445, Config before Blueprint)."""
        keep = {(d.kind, d.metadata.realm or consts.DEFAULT_REALM,
                 d.metadata.space, d.metadata.stack, d.metadata.name)
                for d in (scheme.normalize(x) for x in applied)}
        results = []
        # Exact identity of each kept config's ONE materialized cell
        # (cell name defaults to the config name; scope to the config's).
        kept_config_cells = set()
        for d in (scheme.normalize(x) for x in applied):
            if d.kind != t.KIND_CELL_CONFIG:
                continue
            md = d.metadata
            kept_config_cells.add((
                md.realm or consts.DEFAULT_REALM,
                md.space or consts.DEFAULT_SPACE,
                md.stack or consts.DEFAULT_STACK,
                d.spec.cell_name or md.name,
            ))
        for realm in self.store.list_realms():
            for rec in self.list_cells(realm):
                labels = rec.get("labels", {})
                if labels.get(consts.LABEL_TEAM) != team:
                    continue
                key = (t.KIND_CELL, rec["realm"], rec["space"], rec["stack"], rec["name"])
                if key in keep:
                    continue
                # A Config-lineage cell lives as long as its config — but
                # only the config's CURRENT materialization; stale or
                # renamed materializations fall through and get pruned.
                ident = (rec["realm"], rec["space"], rec["stack"], rec["name"])
                if labels.get(consts.LABEL_PROVENANCE_CONFIG) and \
                        ident in kept_config_cells:
                    continue
                self.runner.delete_cell(rec["realm"], rec["space"], rec["stack"],
                                        rec["name"], force=True)
                results.append(ApplyResult(kind=t.KIND_CELL, name=rec["name"],
                                           scope=f"{rec['realm']}/{rec['space']}/{rec['stack']}",
                                           action="pruned"))
            # Prune scoped kinds at every scope level (Config before
            # Blueprint, then Secret — reference: apply.go:363-445).
            scopes: list[tuple[str | None, str | None]] = [(None, None)]
            for space in self.store.list_spaces(realm):
                scopes.append((space, None))
                for stack in self.store.list_stacks(realm, space):
                    scopes.append((space, stack))
            for kind_dir, kind in ((consts.CONFIGS_DIR, t.KIND_CELL_CONFIG),
                                   (consts.BLUEPRINTS_DIR, t.KIND_CELL_BLUEPRINT),
                                   (consts.SECRETS_DIR, t.KIND_SECRET)):
                for space, stack in scopes:
                    for name in self.store.list_scoped(kind_dir, realm, space, stack):
                        doc = self.store.read_scoped(kind_dir, realm, space, stack, name)
                        if not doc or doc.get("labels", {}).get(consts.LABEL_TEAM) != team:
                            continue
                        if (kind, realm, space, stack, name) in keep:
                            continue
                        self.store.delete_scoped(kind_dir, realm, space, stack, name)
                        scope_str = "/".join(x for x in (realm, space, stack) if x)
                        results.append(ApplyResult(kind=kind, name=name,
                                                   scope=scope_str, action="pruned"))
        return results

    # --- blueprint/config materialization ----------------------------------

    def _materialize_spec(self, realm: str, space: str | None, stack: str | None,
                          cfg: t.CellConfigSpec) -> t.CellSpec:
        """Config + referenced blueprint -> would-be cell spec. Shared by
        materialize_config and the OutOfSync re-derivation so both always
        agree (reference: cellconfig/materialize.go:63-317)."""
        bp = self.get_blueprint(realm, space, stack, cfg.blueprint)
        cell_spec = substitute_blueprint(bp, cfg.values)
        # Bind config env overlay + secret slots.
        for c in cell_spec.containers:
            for e in cfg.env:
                c.env = [x for x in c.env if x.name != e.name] + [e]
            for binding in cfg.secrets:
                c.secrets = [
                    dataclasses.replace(s, name=binding.secret)
                    if s.name == binding.slot else s
                    for s in c.secrets
                ]
        return cell_spec

    def materialize_config(self, realm: str, space: str | None, stack: str | None,
                           config_name: str) -> dict:
        """CellConfig -> live cell (reference: cellconfig/materialize.go)."""
        cfg_doc = self.store.resolve_scoped(
            consts.CONFIGS_DIR, realm, space, stack, config_name
        )
        if cfg_doc is None:
            raise NotFound(f"cellconfig {config_name!r} not found")
        cfg = from_wire(t.CellConfigSpec, cfg_doc["spec"])
        cell_spec = self._materialize_spec(realm, space, stack, cfg)
        # A config represents exactly ONE live cell, so the default name is
        # the config's own name — deterministic across applies (a random
        # name here would mint a fresh cell every apply; fresh-cell-per-run
        # is run_blueprint's job).
        name = cfg.cell_name or config_name
        doc = t.Document(
            kind=t.KIND_CELL,
            metadata=t.Metadata(
                name=name, realm=realm, space=space, stack=stack,
                # The cell inherits the config's team label so team prune
                # converges materialized cells too.
                labels={
                    **{k: v for k, v in (cfg_doc.get("labels") or {}).items()
                       if k == consts.LABEL_TEAM},
                    consts.LABEL_PROVENANCE_CONFIG: config_name,
                    consts.LABEL_PROVENANCE_BLUEPRINT: cfg.blueprint,
                },
            ),
            spec=cell_spec,
        )
        d = scheme.normalize(doc)
        md = d.metadata
        if self.store.cell_exists(md.realm, md.space, md.stack, name):
            self._apply_one(d)
            return self.store.read_cell(md.realm, md.space, md.stack, name).to_json()
        return self.create_cell(d)

    def run_blueprint(self, realm: str, space: str | None, stack: str | None,
                      blueprint: str, values: dict[str, str]) -> dict:
        """kuke run -b: materialize a fresh <prefix>-<6hex> cell."""
        bp = self.get_blueprint(realm, space, stack, blueprint)
        cell_spec = substitute_blueprint(bp, values)
        name = naming.random_cell_name(bp.name_prefix or blueprint)
        doc = t.Document(
            kind=t.KIND_CELL,
            metadata=t.Metadata(
                name=name, realm=realm, space=space, stack=stack,
                labels={consts.LABEL_PROVENANCE_BLUEPRINT: blueprint},
            ),
            spec=cell_spec,
        )
        return self.create_cell(doc)

    # --- reconcile (reference: reconcile.go:52-206) ------------------------

    def images_in_use(self) -> set[str]:
        """Image refs referenced by any cell container spec OR any stored
        CellBlueprint's container template (prune keep-set). Blueprints count
        because a config may materialize a cell from them at any time; prune
        must not strand that future cell without its image."""
        out: set[str] = set()
        for realm in self.store.list_realms():
            for rec in self.list_cells(realm):
                for c in rec.get("spec", {}).get("containers", []):
                    if c.get("image"):
                        out.add(c["image"])
            scopes: list[tuple[str | None, str | None]] = [(None, None)]
            for space in self.store.list_spaces(realm):
                scopes.append((space, None))
                for stack in self.store.list_stacks(realm, space):
                    scopes.append((space, stack))
            for space, stack in scopes:
                for name in self.store.list_scoped(
                        consts.BLUEPRINTS_DIR, realm, space, stack):
                    doc = self.store.read_scoped(
                        consts.BLUEPRINTS_DIR, realm, space, stack, name)
                    if doc:
                        out |= self._blueprint_image_refs(doc, {})
                # Stored configs may override params (values: {img: ...});
                # the images THEY would materialize must survive prune too.
                for name in self.store.list_scoped(
                        consts.CONFIGS_DIR, realm, space, stack):
                    cfg_doc = self.store.read_scoped(
                        consts.CONFIGS_DIR, realm, space, stack, name)
                    if not cfg_doc:
                        continue
                    spec = cfg_doc.get("spec", {}) or {}
                    bp_doc = self.store.resolve_scoped(
                        consts.BLUEPRINTS_DIR, realm, space, stack,
                        spec.get("blueprint") or "")
                    if bp_doc:
                        out |= self._blueprint_image_refs(
                            bp_doc, dict(spec.get("values") or {}))
        return out

    @staticmethod
    def _blueprint_image_refs(doc: dict, values: dict[str, str]) -> set[str]:
        """Image refs a stored blueprint doc would materialize under the
        given param values — computed with the SAME substitution path
        materialization uses (substitute_scalar over blueprint_params). A
        ref whose params stay unresolved can't name a concrete image and is
        skipped."""
        try:
            bp = from_wire(t.CellBlueprintSpec, doc.get("spec") or {})
            params = {p.name: p.default for p in bp.params}
            params.update(values)
        except (TypeError, KeyError, AttributeError):
            return set()
        refs: set[str] = set()
        for c in bp.cell.containers:
            if not c.image:
                continue
            try:
                refs.add(substitute_scalar(c.image, params))
            except InvalidArgument:
                continue
        return refs

    def reconcile_space_networks(self) -> dict[str, dict]:
        """Re-assert every space's bridge/conflist/egress chain (reference:
        ReconcileSpaceNetworks, reconcile.go:52-66 — heals reboot flushes)."""
        if self.runner.netman is None:
            return {}
        return self.runner.netman.reconcile_all()

    def reconcile_cells(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        for realm in self.store.list_realms():
            for space in self.store.list_spaces(realm):
                for stack in self.store.list_stacks(realm, space):
                    for cell in self.store.list_cells(realm, space, stack):
                        # One broken cell (stale image ref, corrupt metadata)
                        # must not stall reconciliation for every cell after
                        # it in iteration order.
                        try:
                            rec, outcome = self.runner.refresh_cell(realm, space, stack, cell)
                            counts[outcome] = counts.get(outcome, 0) + 1
                            # A cell refresh just deleted must not get its
                            # record resurrected by an out-of-sync write.
                            if (rec is not None
                                    and outcome not in (OUTCOME_AUTO_DELETED,
                                                        OUTCOME_VANISHED)
                                    and self._reconcile_out_of_sync(rec)):
                                counts["out_of_sync"] = counts.get("out_of_sync", 0) + 1
                        except (KukeonError, OSError):
                            counts["error"] = counts.get("error", 0) + 1
        return counts

    def _reconcile_out_of_sync(self, rec: model.CellRecord) -> bool:
        """Per-cell OutOfSync detection for Config-lineage cells (reference:
        reconcile_outofsync.go:38-160). Re-derives the would-be spec from the
        stored Config + Blueprint and diffs it against the live spec. Three
        outcomes land on status: out_of_sync+reason (drift, or Config
        deleted), clean (synced), or out_of_sync_error (undecidable:
        blueprint missing / materialize failure). Persists only on change;
        returns True when the cell is currently out of sync."""
        config_name = (rec.provenance.config or "").strip()
        if not config_name:
            return False

        out_of_sync, reason, error = False, None, None
        cfg_doc = self.store.resolve_scoped(
            consts.CONFIGS_DIR, rec.realm, rec.space, rec.stack, config_name
        )
        if cfg_doc is None:
            out_of_sync, reason = True, "lineage Config deleted"
        else:
            try:
                cfg = from_wire(t.CellConfigSpec, cfg_doc["spec"])
                spec = self._materialize_spec(rec.realm, rec.space, rec.stack, cfg)
                # Normalize through the same path materialize_config's cell
                # took at create time, so defaulting never reads as drift.
                desired = scheme.normalize(t.Document(
                    kind=t.KIND_CELL,
                    metadata=t.Metadata(name=rec.name, realm=rec.realm,
                                        space=rec.space, stack=rec.stack),
                    spec=spec,
                )).spec
                verdict = diff_cell_spec(desired, rec.spec)
                if verdict != UNCHANGED:
                    out_of_sync, reason = True, f"spec differs ({verdict})"
            except KukeonError as e:
                error = str(e)

        st = rec.status
        if (st.out_of_sync, st.out_of_sync_reason, st.out_of_sync_error) == \
                (out_of_sync, reason, error):
            return out_of_sync
        # Persist under the cell lock against a FRESH read: a concurrent RPC
        # (stop/apply) may have written the record since our refresh snapshot,
        # and writing the stale rec back would undo it (e.g. flip a stopped
        # cell back to desired_state=running).
        with self.runner.cell_lock(rec.realm, rec.space, rec.stack, rec.name):
            try:
                fresh = self.store.read_cell(rec.realm, rec.space, rec.stack, rec.name)
            except NotFound:
                return out_of_sync
            fresh.status.out_of_sync = out_of_sync
            fresh.status.out_of_sync_reason = reason
            fresh.status.out_of_sync_error = error
            self.store.write_cell(fresh)
        return out_of_sync

    # --- helpers -----------------------------------------------------------

    @staticmethod
    def _scope_str(md: t.Metadata) -> str:
        return "/".join(x for x in (md.realm, md.space, md.stack) if x)


# --- diff engine (reference: controller/apply/diff.go) ----------------------

# Fields whose change requires recreating the cell (baked into process/
# namespace setup at start).
_BREAKING_CONTAINER_FIELDS = (
    "image", "command", "args", "user", "privileged", "host_network",
    "host_pid", "read_only_root_filesystem", "capabilities", "security_opts",
    "devices", "workdir", "attachable", "tty", "secrets", "volumes", "repos",
)
_COMPATIBLE_CONTAINER_FIELDS = ("env", "resources", "restart_policy", "ports", "networks")


def diff_cell_spec(old: t.CellSpec, new: t.CellSpec) -> str:
    if to_wire(old) == to_wire(new):
        return UNCHANGED
    old_names = {c.name for c in old.containers}
    new_names = {c.name for c in new.containers}
    if old_names != new_names:
        return BREAKING
    if to_wire(old.model) != to_wire(new.model):
        return BREAKING
    for name in old_names:
        oc = next(c for c in old.containers if c.name == name)
        nc = next(c for c in new.containers if c.name == name)
        for f in _BREAKING_CONTAINER_FIELDS:
            if to_wire(getattr(oc, f)) != to_wire(getattr(nc, f)):
                return BREAKING
    return COMPATIBLE


_PARAM_RE = re.compile(r"\$\{([A-Za-z0-9_.-]+)\}")


def substitute_scalar(s: str, params: dict[str, Any]) -> str:
    """``${param}`` substitution over one scalar — the ONE implementation
    shared by blueprint materialization and the prune keep-set, so the two
    can never diverge on substitution semantics."""

    def repl(m):
        key = m.group(1)
        if key not in params or params[key] is None:
            raise InvalidArgument(f"blueprint param {key!r} has no value")
        return str(params[key])

    return _PARAM_RE.sub(repl, s)


def blueprint_params(bp: t.CellBlueprintSpec, values: dict[str, str]) -> dict[str, Any]:
    """Effective param map (defaults overlaid with caller values), with
    required-param validation."""
    params: dict[str, Any] = {p.name: p.default for p in bp.params}
    params.update(values)
    missing = [
        p.name for p in bp.params
        if p.required and params.get(p.name) is None
    ]
    if missing:
        raise InvalidArgument(f"blueprint requires params: {missing}")
    return params


def substitute_blueprint(bp: t.CellBlueprintSpec, values: dict[str, str]) -> t.CellSpec:
    """``${param}`` scalar substitution over the blueprint's cell template
    (reference: cellblueprint/params.go:47-174)."""
    import copy

    params = blueprint_params(bp, values)

    def sub_str(s: str) -> str:
        return substitute_scalar(s, params)

    def walk(obj: Any) -> Any:
        if isinstance(obj, str):
            return sub_str(obj)
        if isinstance(obj, list):
            return [walk(x) for x in obj]
        if isinstance(obj, dict):
            return {k: walk(v) for k, v in obj.items()}
        if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
            return type(obj)(**{
                f.name: walk(getattr(obj, f.name)) for f in dataclasses.fields(obj)
            })
        return obj

    return walk(copy.deepcopy(bp.cell))
