"""`kuke team init` orchestration: roster -> running fleet.

Reference call stack (SURVEY.md §3.6): teamhost -> teamsource -> [teambuild]
-> teamsecrets -> teamrender -> ApplyDocumentsForTeam with prune.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from kukeon_tpu.runtime.errors import InvalidArgument
from kukeon_tpu.runtime.teams import types as tt
from kukeon_tpu.runtime.teams.host import TeamHost
from kukeon_tpu.runtime.teams.render import RenderResult, render_team
from kukeon_tpu.runtime.teams.secrets import load_team_secrets, secret_documents
from kukeon_tpu.runtime.teams.source import GitRunner, TeamSourceResolver
from kukeon_tpu.runtime import consts


@dataclass
class TeamInitResult:
    project: str = ""
    checkout: str = ""
    applied: list = field(default_factory=list)   # ApplyResult-like dicts
    rendered: RenderResult | None = None
    secret_names: list[str] = field(default_factory=list)
    built_images: list[str] = field(default_factory=list)
    pushed_images: list[str] = field(default_factory=list)


def load_project_team(path: str) -> tt.ProjectTeam:
    with open(path) as f:
        docs = tt.parse_team_documents(f.read(), origin=path)
    teams = [d for d in docs if isinstance(d, tt.ProjectTeam)]
    if len(teams) != 1:
        raise InvalidArgument(
            f"{path} must contain exactly one ProjectTeam (got {len(teams)})"
        )
    return teams[0]


def team_init(apply_fn, project_file: str, host: TeamHost | None = None,
              git: GitRunner | None = None, dry_run: bool = False,
              build: bool = False, builder=None,
              pusher=None) -> TeamInitResult:
    """The full pipeline.

    ``apply_fn(yaml_blob, team, prune) -> list[dict]`` is the apply
    transport — an RPC client call or an in-process controller; None is
    allowed for dry runs.

    ``pusher(tag, registry) -> pushed_ref`` pushes each built image to the
    TeamsConfig's registry after the build (reference: teambuild threads the
    REGISTRY build-arg AND kukebuild pushes with docker-config auth,
    internal/teambuild/teambuild.go:17-42, cmd/kukebuild/auth.go:125-154).
    Requires ``build`` and a non-empty ``registry:`` in the teams config.
    """
    host = host or TeamHost()
    team = load_project_team(project_file)
    cfg = host.load_config()
    host.ensure_team_dirs(team.name)

    # Drop-in: the host's per-project entry pins the on-host project path
    # and may override the source.
    entry = host.load_dropin(team.name)
    project_path = entry.path if entry else os.path.dirname(
        os.path.abspath(project_file)
    )
    source = entry.source if entry and entry.source else team.source

    resolver = TeamSourceResolver(host, cfg, git=git)
    checkout = resolver.resolve(source)
    bundle = resolver.load_bundle(team, checkout)

    result = TeamInitResult(project=team.name, checkout=checkout)

    if build:
        if builder is None:
            raise InvalidArgument("--build requires an image builder")
        if pusher is not None and not cfg.registry:
            raise InvalidArgument(
                "--push requires a registry in the teams config "
                "(~/.kuke/kuketeams.yaml: registry: host[:port])"
            )
        result.built_images = build_team_images(
            builder, bundle, cfg, checkout
        )
        if pusher is not None:
            result.pushed_images = [
                pusher(tag, cfg.registry) for tag in result.built_images
            ]
    elif pusher is not None:
        raise InvalidArgument("--push requires --build")

    secret_values = load_team_secrets(host, cfg, team.name)
    realm = team.realm or consts.DEFAULT_REALM
    rendered = render_team(
        team, bundle, cfg,
        project_path=project_path,
        project_repo_url=resolver.clone_url(source),
    )
    result.rendered = rendered

    # Only ship secrets the rendered fleet actually binds.
    needed = {n: secret_values[n] for n in rendered.secrets_needed}
    result.secret_names = sorted(needed)
    if dry_run or apply_fn is None:
        return result
    missing = sorted(n for n, v in needed.items() if not v)
    if missing:
        raise InvalidArgument(
            f"secrets {missing} have no value; fill "
            f"{host.team_secrets_path(team.name)}"
        )
    secret_docs = secret_documents(needed, team.name, realm)
    docs = secret_docs + rendered.blueprints + rendered.configs

    from kukeon_tpu.runtime.apply.parser import dump_documents

    result.applied = apply_fn(dump_documents(docs), team.name, True)
    return result


def build_team_images(builder, bundle, cfg: tt.TeamsConfig,
                      checkout: str) -> list[str]:
    """FROM-order walk over the catalog's build contexts (reference:
    internal/teambuild — bases before leaves), building each image via the
    image builder. Returns the tags built."""
    entries = [e for e in bundle.catalog.images if e.build.context]
    by_image = {e.image: e for e in entries}
    built: list[str] = []
    seen: set[str] = set()

    def visit(entry, chain):
        if entry.image in seen:
            return
        if entry.image in chain:
            raise InvalidArgument(
                f"image FROM cycle: {' -> '.join([*chain, entry.image])}"
            )
        kukefile = os.path.join(checkout, entry.build.context,
                                entry.build.dockerfile or "Kukefile")
        build_args = {"REGISTRY": cfg.registry} if cfg.registry else {}
        base = builder.base_of(kukefile, build_args)
        if base in by_image:
            visit(by_image[base], [*chain, entry.image])
        builder.build(
            kukefile,
            context_dir=os.path.join(checkout, entry.build.context),
            tag=entry.image,
            build_args=build_args,
        )
        seen.add(entry.image)
        built.append(entry.image)

    for e in entries:
        visit(e, [])
    return built
