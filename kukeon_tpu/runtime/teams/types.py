"""kuketeams.io/v1 document model + parser.

Reference: pkg/api/model/kuketeams (projectteam.go, teamsconfig.go, role.go,
harness.go, imagecatalog.go, source.go) and internal/kuketeams/parser.go.
Six kinds: ProjectTeam (the per-project roster), TeamsConfig (operator
facts), TeamEntry (host drop-in), Role, Harness, ImageCatalog (the latter
three live in the agents source repo).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import yaml

from kukeon_tpu.runtime.errors import InvalidArgument

API_VERSION = "kuketeams.io/v1"

KIND_PROJECT_TEAM = "ProjectTeam"
KIND_TEAMS_CONFIG = "TeamsConfig"
KIND_TEAM_ENTRY = "TeamEntry"
KIND_ROLE = "Role"
KIND_HARNESS = "Harness"
KIND_IMAGE_CATALOG = "ImageCatalog"

DEFAULT_SOURCE_HOST = "github.com"


@dataclass
class TeamSource:
    """Agents-repo reference: host-qualified repo + exactly one of
    tag (pinned, clone-once) / branch (floating, refetch+reset) /
    commit (pinned)."""

    repo: str = ""
    tag: str = ""
    branch: str = ""
    commit: str = ""

    def ref(self) -> tuple[str, str]:
        """(value, kind) — exactly one ref must be set."""
        set_refs = [(v.strip(), k) for v, k in
                    ((self.tag, "tag"), (self.branch, "branch"),
                     (self.commit, "commit")) if v.strip()]
        if len(set_refs) != 1:
            raise InvalidArgument(
                f"source {self.repo!r} must set exactly one of "
                f"tag/branch/commit (got {len(set_refs)})"
            )
        return set_refs[0]

    @property
    def floating(self) -> bool:
        return self.ref()[1] == "branch"

    def qualified_repo(self) -> str:
        """host/owner/repo — a bare owner/repo defaults to github.com."""
        repo = self.repo.strip().strip("/")
        if not repo:
            raise InvalidArgument("source.repo is required")
        parts = repo.split("/")
        if len(parts) == 2:
            return f"{DEFAULT_SOURCE_HOST}/{repo}"
        if len(parts) == 3:
            return repo
        raise InvalidArgument(
            f"source.repo {self.repo!r} must be <owner>/<repo> or "
            f"<host>/<owner>/<repo>"
        )

    @property
    def owner(self) -> str:
        return self.qualified_repo().split("/")[1]

    def cache_key(self) -> str:
        value, _ = self.ref()
        return f"{self.qualified_repo()}@{value}".replace("/", "_")

    def default_clone_url(self) -> str:
        host, owner, repo = self.qualified_repo().split("/")
        return f"git@{host}:{owner}/{repo}.git"


@dataclass
class ProjectRoleNeeds:
    image: list[str] = field(default_factory=list)   # capability names


@dataclass
class ProjectTeamRole:
    ref: str = ""
    needs: ProjectRoleNeeds = field(default_factory=ProjectRoleNeeds)


@dataclass
class ProjectTeamDefaults:
    harnesses: list[str] = field(default_factory=list)


@dataclass
class ProjectTeam:
    name: str = ""
    source: TeamSource = field(default_factory=TeamSource)
    project_dir: str = ""            # in-cell clone dir override
    realm: str = ""
    space: str = ""
    stack: str = ""
    defaults: ProjectTeamDefaults = field(default_factory=ProjectTeamDefaults)
    roles: list[ProjectTeamRole] = field(default_factory=list)


@dataclass
class TeamsConfigGit:
    name: str = ""
    email: str = ""
    signing_key: str = ""
    ssh_key: str = ""


@dataclass
class TeamsConfigSecret:
    source: str = ""                 # "from": env-file basename or "env"
    key: str = ""


@dataclass
class TeamsConfig:
    git: TeamsConfigGit = field(default_factory=TeamsConfigGit)
    registry: str = ""
    home_dir: str = ""
    repo_owner: str = ""
    sources: dict[str, str] = field(default_factory=dict)   # repo -> clone URL
    secrets: dict[str, TeamsConfigSecret] = field(default_factory=dict)


@dataclass
class TeamEntry:
    name: str = ""
    path: str = ""                   # on-host project source tree
    team_dir: str = ""
    source: TeamSource | None = None


@dataclass
class RoleHarness:
    settings: str = ""
    sandbox: str = ""
    approval: str = ""
    permissions: str = ""
    secrets: list[str] = field(default_factory=list)


@dataclass
class RoleNeeds:
    image: list[str] = field(default_factory=list)
    repos: list[str] = field(default_factory=list)
    mounts: list[str] = field(default_factory=list)
    params: list[str] = field(default_factory=list)
    secrets: list[str] = field(default_factory=list)


@dataclass
class Role:
    name: str = ""
    skills: list[str] = field(default_factory=list)
    harnesses: dict[str, RoleHarness] = field(default_factory=dict)
    needs: RoleNeeds = field(default_factory=RoleNeeds)


@dataclass
class HarnessSeed:
    path: str = ""
    mode: int = 0
    content: str = ""


@dataclass
class Harness:
    name: str = ""
    base_image: str = ""
    skill_path: str = ""
    template: str = ""               # blueprint template file, harness-dir relative
    seeds: list[HarnessSeed] = field(default_factory=list)


@dataclass
class ImageCatalogBuild:
    context: str = ""
    dockerfile: str = ""


@dataclass
class ImageCatalogEntry:
    ref: str = ""
    harness: str = ""
    image: str = ""
    build: ImageCatalogBuild = field(default_factory=ImageCatalogBuild)
    capabilities: list[str] = field(default_factory=list)


@dataclass
class ImageCatalog:
    images: list[ImageCatalogEntry] = field(default_factory=list)


# --- parsing -----------------------------------------------------------------


def parse_team_documents(blob: str, origin: str = "<inline>") -> list:
    """Parse a multi-doc YAML blob into typed kuketeams objects."""
    out = []
    for i, raw in enumerate(yaml.safe_load_all(blob)):
        if raw is None:
            continue
        if not isinstance(raw, dict):
            raise InvalidArgument(f"{origin}[{i}]: document must be a mapping")
        out.append(parse_team_document(raw, f"{origin}[{i}]"))
    return out


def parse_team_document(raw: dict, origin: str = "<inline>"):
    api = raw.get("apiVersion", "")
    if api != API_VERSION:
        raise InvalidArgument(
            f"{origin}: apiVersion {api!r} is not {API_VERSION}"
        )
    kind = raw.get("kind", "")
    md = raw.get("metadata") or {}
    spec = raw.get("spec") or {}
    name = md.get("name", "")
    if kind == KIND_PROJECT_TEAM:
        return _parse_project_team(name, spec, origin)
    if kind == KIND_TEAMS_CONFIG:
        return _parse_teams_config(spec, origin)
    if kind == KIND_TEAM_ENTRY:
        return TeamEntry(
            name=name, path=spec.get("path", ""),
            team_dir=spec.get("teamDir", ""),
            source=_parse_source(spec["source"]) if spec.get("source") else None,
        )
    if kind == KIND_ROLE:
        return _parse_role(name, spec)
    if kind == KIND_HARNESS:
        return Harness(
            name=name,
            base_image=spec.get("baseImage", ""),
            skill_path=spec.get("skillPath", ""),
            template=spec.get("template", ""),
            seeds=[HarnessSeed(path=s.get("path", ""), mode=s.get("mode", 0),
                               content=s.get("content", ""))
                   for s in spec.get("seeds") or []],
        )
    if kind == KIND_IMAGE_CATALOG:
        return ImageCatalog(images=[
            ImageCatalogEntry(
                ref=e.get("ref", ""), harness=e.get("harness", ""),
                image=e.get("image", ""),
                build=ImageCatalogBuild(
                    context=(e.get("build") or {}).get("context", ""),
                    dockerfile=(e.get("build") or {}).get("dockerfile", ""),
                ),
                capabilities=list(e.get("capabilities") or []),
            )
            for e in spec.get("images") or []
        ])
    raise InvalidArgument(f"{origin}: unknown kuketeams kind {kind!r}")


def _parse_source(raw) -> TeamSource:
    if isinstance(raw, str):
        raise InvalidArgument(
            f"source {raw!r}: the string form is not supported; use the "
            f"structured form {{repo, tag|branch|commit}}"
        )
    src = TeamSource(repo=raw.get("repo", ""), tag=raw.get("tag", ""),
                     branch=raw.get("branch", ""), commit=raw.get("commit", ""))
    src.ref()            # validates exactly-one
    src.qualified_repo()  # validates shape
    return src


def _parse_project_team(name: str, spec: dict, origin: str) -> ProjectTeam:
    if not name:
        raise InvalidArgument(f"{origin}: ProjectTeam needs metadata.name")
    if not spec.get("source"):
        raise InvalidArgument(f"{origin}: ProjectTeam needs spec.source")
    roles = []
    for r in spec.get("roles") or []:
        if not r.get("ref"):
            raise InvalidArgument(f"{origin}: every role needs a ref")
        needs = r.get("needs") or {}
        roles.append(ProjectTeamRole(
            ref=r["ref"],
            needs=ProjectRoleNeeds(image=list(needs.get("image") or [])),
        ))
    if not roles:
        raise InvalidArgument(f"{origin}: ProjectTeam needs at least one role")
    defaults = spec.get("defaults") or {}
    return ProjectTeam(
        name=name,
        source=_parse_source(spec["source"]),
        project_dir=spec.get("projectDir", ""),
        realm=spec.get("realm", ""),
        space=spec.get("space", ""),
        stack=spec.get("stack", ""),
        defaults=ProjectTeamDefaults(
            harnesses=list(defaults.get("harnesses") or [])
        ),
        roles=roles,
    )


def _parse_teams_config(spec: dict, origin: str) -> TeamsConfig:
    git = spec.get("git") or {}
    secrets = {}
    for sname, s in (spec.get("secrets") or {}).items():
        if not isinstance(s, dict) or not s.get("from"):
            raise InvalidArgument(
                f"{origin}: secret {sname!r} needs a 'from' declaration "
                f"(secrets never carry inline values)"
            )
        secrets[sname] = TeamsConfigSecret(source=s["from"], key=s.get("key", sname))
    return TeamsConfig(
        git=TeamsConfigGit(
            name=git.get("name", ""), email=git.get("email", ""),
            signing_key=git.get("signingKey", ""),
            ssh_key=git.get("sshKey", ""),
        ),
        registry=spec.get("registry", ""),
        home_dir=spec.get("homeDir", ""),
        repo_owner=spec.get("repoOwner", ""),
        sources=dict(spec.get("sources") or {}),
        secrets=secrets,
    )


def _parse_role(name: str, spec: dict) -> Role:
    needs = spec.get("needs") or {}
    harnesses = {}
    for hname, h in (spec.get("harnesses") or {}).items():
        h = h or {}
        harnesses[hname] = RoleHarness(
            settings=h.get("settings", ""), sandbox=h.get("sandbox", ""),
            approval=h.get("approval", ""), permissions=h.get("permissions", ""),
            secrets=list(h.get("secrets") or []),
        )
    return Role(
        name=name,
        skills=list(spec.get("skills") or []),
        harnesses=harnesses,
        needs=RoleNeeds(
            image=list(needs.get("image") or []),
            repos=list(needs.get("repos") or []),
            mounts=list(needs.get("mounts") or []),
            params=list(needs.get("params") or []),
            secrets=list(needs.get("secrets") or []),
        ),
    )
