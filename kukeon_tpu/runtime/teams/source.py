"""Agents-repo source resolution: clone/refresh + document loading.

Reference: internal/teamsource/teamsource.go. Semantics kept:

- pinned tag/commit: the cache clone is made once and reused as-is
  (reproducible);
- floating branch: refetched and hard-reset to the branch tip on every
  init, so a stale roster is never silently reused;
- default transport is SSH (``git@<host>:<owner>/<repo>.git``);
  TeamsConfig.spec.sources overrides per-repo (HTTPS, mirrors, or a local
  path — which is also how tests provide a fixture remote).

Agents-repo layout (same convention as the reference so existing agents
repos work unchanged):

  <repo>/<role>/role.yaml
  <repo>/harnesses/<name>/harness.yaml   (+ template files alongside)
  <repo>/harnesses/images.yaml
"""

from __future__ import annotations

import os
import subprocess

from kukeon_tpu.runtime.errors import InvalidArgument, NotFound
from kukeon_tpu.runtime.teams import types as tt
from kukeon_tpu.runtime.teams.host import TeamHost


class GitRunner:
    """Shell-out seam so source resolution is unit-testable without git."""

    def run(self, argv: list[str], cwd: str | None = None,
            env: dict | None = None) -> tuple[int, str]:
        full_env = dict(os.environ)
        if env:
            full_env.update(env)
        try:
            p = subprocess.run(["git", *argv], cwd=cwd, env=full_env,
                               capture_output=True, text=True, timeout=300,
                               check=False)
        except (OSError, subprocess.TimeoutExpired) as e:
            return 127, str(e)
        return p.returncode, (p.stdout or "") + (p.stderr or "")


class FakeGitRunner(GitRunner):
    """Records calls; 'clone' materializes a scripted directory tree."""

    def __init__(self, tree: dict[str, str] | None = None):
        self.calls: list[list[str]] = []
        self.tree = tree or {}

    def run(self, argv, cwd=None, env=None):
        self.calls.append(list(argv))
        if argv and argv[0] == "clone":
            dest = argv[-1]
            for rel, content in self.tree.items():
                path = os.path.join(dest, rel)
                os.makedirs(os.path.dirname(path), exist_ok=True)
                with open(path, "w") as f:
                    f.write(content)
        return 0, ""


class TeamSourceResolver:
    def __init__(self, host: TeamHost, cfg: tt.TeamsConfig,
                 git: GitRunner | None = None):
        self.host = host
        self.cfg = cfg
        self.git = git or GitRunner()

    # --- clone/refresh ------------------------------------------------------

    def clone_url(self, source: tt.TeamSource) -> str:
        qualified = source.qualified_repo()
        bare = "/".join(qualified.split("/")[1:])   # owner/repo
        for key in (qualified, bare):
            if key in self.cfg.sources:
                return self.cfg.sources[key]
        return source.default_clone_url()

    def _git_env(self) -> dict:
        env = {}
        if self.cfg.git.ssh_key:
            env["GIT_SSH_COMMAND"] = (
                f"ssh -i {self.cfg.git.ssh_key} -o IdentitiesOnly=yes"
            )
        return env

    def resolve(self, source: tt.TeamSource) -> str:
        """Return a local checkout dir for the source, cloning/refreshing
        per the pinned-vs-floating contract."""
        value, kind = source.ref()
        cache = self.host.cache_dir(source)
        env = self._git_env()
        url = self.clone_url(source)

        if os.path.isdir(os.path.join(cache, ".git")) or (
            os.path.isdir(cache) and os.listdir(cache)
        ):
            if kind == "branch":
                code, out = self.git.run(["fetch", "origin", value], cwd=cache, env=env)
                if code != 0:
                    raise InvalidArgument(
                        f"refetch of {url} branch {value} failed: {out.strip()}"
                    )
                self.git.run(["checkout", value], cwd=cache, env=env)
                self.git.run(["reset", "--hard", f"origin/{value}"], cwd=cache, env=env)
            return cache

        os.makedirs(os.path.dirname(cache), exist_ok=True)
        argv = ["clone"]
        if kind in ("tag", "branch"):
            argv += ["--depth", "1", "--branch", value]
        argv += [url, cache]
        code, out = self.git.run(argv, env=env)
        if code != 0:
            raise InvalidArgument(f"clone of {url} failed: {out.strip()}")
        if kind == "commit":
            code, out = self.git.run(["checkout", value], cwd=cache, env=env)
            if code != 0:
                raise InvalidArgument(
                    f"checkout of commit {value} failed: {out.strip()}"
                )
        return cache

    # --- document loading ---------------------------------------------------

    def load_bundle(self, team: tt.ProjectTeam, checkout: str) -> "SourceBundle":
        roles: dict[str, tt.Role] = {}
        for r in team.roles:
            roles[r.ref] = load_role(checkout, r.ref)
        harness_names = set(team.defaults.harnesses)
        for role in roles.values():
            harness_names.update(role.harnesses)
        if not harness_names:
            raise InvalidArgument(
                f"team {team.name!r}: no harnesses (set defaults.harnesses "
                f"or per-role harnesses)"
            )
        harnesses = {h: load_harness(checkout, h) for h in sorted(harness_names)}
        return SourceBundle(
            checkout=checkout, roles=roles, harnesses=harnesses,
            catalog=load_image_catalog(checkout),
        )


class SourceBundle:
    def __init__(self, checkout: str, roles: dict, harnesses: dict, catalog):
        self.checkout = checkout
        self.roles = roles
        self.harnesses = harnesses
        self.catalog = catalog

    def harness_dir(self, name: str) -> str:
        return harness_dir(self.checkout, name)


# --- layout helpers ----------------------------------------------------------


def role_path(checkout: str, ref: str) -> str:
    return os.path.join(checkout, ref, "role.yaml")


def harness_dir(checkout: str, name: str) -> str:
    return os.path.join(checkout, "harnesses", name)


def harness_path(checkout: str, name: str) -> str:
    return os.path.join(harness_dir(checkout, name), "harness.yaml")


def catalog_path(checkout: str) -> str:
    return os.path.join(checkout, "harnesses", "images.yaml")


def _load_one(path: str, want_type, what: str):
    if not os.path.exists(path):
        raise NotFound(f"{what}: {path} not found in agents source")
    with open(path) as f:
        docs = tt.parse_team_documents(f.read(), origin=path)
    for d in docs:
        if isinstance(d, want_type):
            return d
    raise InvalidArgument(f"{path} contains no {what} document")


def load_role(checkout: str, ref: str) -> tt.Role:
    return _load_one(role_path(checkout, ref), tt.Role, f"role {ref!r}")


def load_harness(checkout: str, name: str) -> tt.Harness:
    return _load_one(harness_path(checkout, name), tt.Harness,
                     f"harness {name!r}")


def load_image_catalog(checkout: str) -> tt.ImageCatalog:
    return _load_one(catalog_path(checkout), tt.ImageCatalog, "image catalog")
