"""Two-layer secrets.env -> Secret documents.

Reference: internal/teamsecrets/teamsecrets.go. Host-wide
``~/.kuke/teams/secrets.env`` is merged under the per-team
``~/.kuke/teams/<project>/secrets.env`` (per-team wins). Missing keys that
the TeamsConfig declares are scaffolded as empty ``KEY=`` lines in a 0600
file so the operator has an obvious place to fill them. Secret VALUES are
never logged and never leave this module except inside the produced
Secret documents.
"""

from __future__ import annotations

import os

from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.teams import types as tt
from kukeon_tpu.runtime.teams.host import TeamHost


def parse_env_file(path: str) -> dict[str, str]:
    out: dict[str, str] = {}
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#") or "=" not in line:
                continue
            k, _, v = line.partition("=")
            out[k.strip()] = v.strip()
    return out


def _scaffold_missing(path: str, wanted: list[str]) -> None:
    """Append empty KEY= lines for declared-but-absent keys; create the
    file 0600 if missing. Never touches existing lines."""
    existing = parse_env_file(path)
    missing = [k for k in wanted if k not in existing]
    if not missing and os.path.exists(path):
        return
    os.makedirs(os.path.dirname(path), mode=0o700, exist_ok=True)
    flags = os.O_WRONLY | os.O_CREAT | os.O_APPEND
    fd = os.open(path, flags, 0o600)
    try:
        for k in missing:
            os.write(fd, f"{k}=\n".encode())
    finally:
        os.close(fd)


def load_team_secrets(host: TeamHost, cfg: tt.TeamsConfig,
                      project: str) -> dict[str, str]:
    """Merged name->value map for every secret the config declares.

    Declared keys without a value anywhere merge as "" — the caller decides
    whether an empty secret is an error for the roles that need it.
    """
    shared = parse_env_file(host.shared_secrets_path())
    per_team = parse_env_file(host.team_secrets_path(project))
    wanted = sorted(cfg.secrets)
    _scaffold_missing(host.team_secrets_path(project),
                      [cfg.secrets[n].key or n for n in wanted])
    out: dict[str, str] = {}
    for name in wanted:
        key = cfg.secrets[name].key or name
        # An empty per-team value (incl. the scaffolded `KEY=` line) falls
        # through to the shared layer — scaffolding must never mask a
        # filled host-wide secret.
        out[name] = per_team.get(key) or shared.get(key, "")
    return out


def secret_documents(values: dict[str, str], project: str,
                     realm: str) -> list[t.Document]:
    """One kind:Secret per named secret, realm-scoped, team-labeled."""
    docs = []
    for name in sorted(values):
        docs.append(t.Document(
            kind=t.KIND_SECRET,
            metadata=t.Metadata(
                name=name, realm=realm,
                labels={"kukeon.io/team": project},
            ),
            spec=t.SecretSpec(data={"value": values[name]}),
        ))
    return docs
