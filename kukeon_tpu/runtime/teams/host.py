"""Host-side teams config lifecycle.

Reference: internal/teamhost (teamhost.go, provision.go). Owns the operator's
``~/.kuke`` tree:

  ~/.kuke/kuketeams.yaml           global TeamsConfig (operator facts)
  ~/.kuke/kuketeam.d/<name>.yaml   per-project TeamEntry drop-ins
  ~/.kuke/teams/secrets.env        host-wide shared secrets
  ~/.kuke/teams/<project>/secrets.env   per-team overrides
  ~/.kuke/teams/cache/<repo@ref>/  agents-repo clone cache

``KUKE_HOME`` overrides the base for tests and multi-profile hosts.
"""

from __future__ import annotations

import os

import yaml

from kukeon_tpu.runtime.errors import InvalidArgument, NotFound
from kukeon_tpu.runtime.teams import types as tt

GLOBAL_CONFIG = "kuketeams.yaml"
DROPIN_DIR = "kuketeam.d"
TEAMS_DIR = "teams"
CACHE_DIR = "cache"
SECRETS_ENV = "secrets.env"

_SCAFFOLD = """\
apiVersion: kuketeams.io/v1
kind: TeamsConfig
spec:
  git:
    name: ""
    email: ""
  registry: ""
  sources: {}
  secrets: {}
"""


def kuke_home() -> str:
    return os.environ.get("KUKE_HOME") or os.path.join(
        os.path.expanduser("~"), ".kuke"
    )


class TeamHost:
    def __init__(self, base: str | None = None):
        self.base = base or kuke_home()

    # --- paths --------------------------------------------------------------

    def config_path(self) -> str:
        return os.path.join(self.base, GLOBAL_CONFIG)

    def dropin_path(self, project: str) -> str:
        return os.path.join(self.base, DROPIN_DIR, f"{project}.yaml")

    def shared_secrets_path(self) -> str:
        return os.path.join(self.base, TEAMS_DIR, SECRETS_ENV)

    def team_secrets_path(self, project: str) -> str:
        return os.path.join(self.base, TEAMS_DIR, project, SECRETS_ENV)

    def cache_dir(self, source: tt.TeamSource) -> str:
        return os.path.join(self.base, TEAMS_DIR, CACHE_DIR, source.cache_key())

    # --- config -------------------------------------------------------------

    def load_config(self, scaffold: bool = True) -> tt.TeamsConfig:
        """Load the global TeamsConfig, scaffolding a minimal one on first
        use (the reference writes the default O_EXCL so hand edits win)."""
        path = self.config_path()
        if not os.path.exists(path):
            if not scaffold:
                raise NotFound(f"no teams config at {path}")
            os.makedirs(self.base, mode=0o700, exist_ok=True)
            with open(path, "w") as f:
                f.write(_SCAFFOLD)
        with open(path) as f:
            docs = tt.parse_team_documents(f.read(), origin=path)
        for d in docs:
            if isinstance(d, tt.TeamsConfig):
                return d
        raise InvalidArgument(f"{path} contains no TeamsConfig document")

    def load_dropin(self, project: str) -> tt.TeamEntry | None:
        path = self.dropin_path(project)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            docs = tt.parse_team_documents(f.read(), origin=path)
        for d in docs:
            if isinstance(d, tt.TeamEntry):
                return d
        return None

    def write_dropin(self, entry: tt.TeamEntry) -> str:
        d = os.path.join(self.base, DROPIN_DIR)
        os.makedirs(d, mode=0o700, exist_ok=True)
        path = self.dropin_path(entry.name)
        doc = {
            "apiVersion": tt.API_VERSION,
            "kind": tt.KIND_TEAM_ENTRY,
            "metadata": {"name": entry.name},
            "spec": {"path": entry.path},
        }
        if entry.team_dir:
            doc["spec"]["teamDir"] = entry.team_dir
        if entry.source is not None:
            src: dict = {"repo": entry.source.repo}
            value, kind = entry.source.ref()
            src[kind] = value
            doc["spec"]["source"] = src
        with open(path, "w") as f:
            yaml.safe_dump(doc, f, sort_keys=False)
        return path

    def ensure_team_dirs(self, project: str) -> None:
        os.makedirs(os.path.join(self.base, TEAMS_DIR, project),
                    mode=0o700, exist_ok=True)
        os.makedirs(os.path.join(self.base, TEAMS_DIR, CACHE_DIR),
                    mode=0o700, exist_ok=True)
