"""Teams subsystem: per-project agent-team distribution (`kuke team init`).

Capability parity with the reference's §2.7 packages (SURVEY.md):
kuketeams.io/v1 doc model + parser (internal/kuketeams), host config
lifecycle (internal/teamhost), agents-repo source resolution
(internal/teamsource), two-layer secrets.env (internal/teamsecrets),
roster rendering to CellBlueprint+CellConfig pairs (internal/teamrender),
and catalog image builds (internal/teambuild, wired to the image builder).

The pipeline (`kuke team init`):
  host config -> source clone -> [build images] -> secrets -> render ->
  apply-with-prune under the `kukeon.io/team` label.
"""

from kukeon_tpu.runtime.teams.types import (
    Harness,
    ImageCatalog,
    ImageCatalogEntry,
    ProjectTeam,
    Role,
    TeamSource,
    TeamsConfig,
    parse_team_documents,
)
from kukeon_tpu.runtime.teams.host import TeamHost
from kukeon_tpu.runtime.teams.source import GitRunner, FakeGitRunner, TeamSourceResolver
from kukeon_tpu.runtime.teams.secrets import load_team_secrets, secret_documents
from kukeon_tpu.runtime.teams.render import RenderResult, render_team
from kukeon_tpu.runtime.teams.init import team_init

__all__ = [
    "FakeGitRunner",
    "GitRunner",
    "Harness",
    "ImageCatalog",
    "ImageCatalogEntry",
    "ProjectTeam",
    "RenderResult",
    "Role",
    "TeamHost",
    "TeamSource",
    "TeamSourceResolver",
    "TeamsConfig",
    "load_team_secrets",
    "parse_team_documents",
    "render_team",
    "secret_documents",
    "team_init",
]
