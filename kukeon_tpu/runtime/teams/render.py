"""Roster rendering: ProjectTeam -> (CellBlueprint, CellConfig) pairs.

Reference: internal/teamrender/teamrender.go. One pair per (role x
harness), via the same five-step pipeline:

1. **needs-merge** — union of role.needs.image and the project's per-role
   needs.image, deduped + sorted so renders are byte-identical.
2. **image-select** — first catalog entry whose harness matches and whose
   capabilities superset the merged needs; a miss names the first unmet
   capability and hints at building/labeling an image.
3. **render** — the harness's blueprint template (jinja2; the harness dir
   is the loader root so sibling partials {% include %} cleanly), executed
   against a typed dot-context (role/harness/needs/harnesses/operator/
   project/image/realm/space/stack), yaml-parsed into a CellBlueprint doc.
4. **bind** — a CellConfig referencing the blueprint, carrying operator
   facts as values, the project repo fill, and a secret binding for every
   secret the role declares that the blueprint has a slot for.
5. **label** — every doc gets labels[kukeon.io/team] = <project> so
   prune-apply converges this team without touching others.

Pure: reads template files from the materialized source checkout, writes
nothing, runs nothing.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from kukeon_tpu.runtime import consts
from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.apply import parser
from kukeon_tpu.runtime.errors import InvalidArgument
from kukeon_tpu.runtime.teams import types as tt
from kukeon_tpu.runtime.teams.source import SourceBundle


@dataclass
class RenderResult:
    blueprints: list[t.Document] = field(default_factory=list)
    configs: list[t.Document] = field(default_factory=list)
    secrets_needed: list[str] = field(default_factory=list)
    images_used: list[tt.ImageCatalogEntry] = field(default_factory=list)


def merge_needs(role: tt.Role, project_role: tt.ProjectTeamRole) -> list[str]:
    return sorted(set(role.needs.image) | set(project_role.needs.image))


def select_image(catalog: tt.ImageCatalog, harness: str,
                 needs: list[str]) -> tt.ImageCatalogEntry:
    best_missing: list[str] | None = None
    for entry in catalog.images:
        if entry.harness != harness:
            continue
        missing = [n for n in needs if n not in entry.capabilities]
        if not missing:
            return entry
        # Report against the closest candidate so the error names the
        # capability the operator actually has to add.
        if best_missing is None or len(missing) < len(best_missing):
            best_missing = missing
    if best_missing is not None:
        raise InvalidArgument(
            f"no {harness!r} image provides capability {best_missing[0]!r}; "
            f"build one and add it to harnesses/images.yaml with that "
            f"capability label"
        )
    raise InvalidArgument(
        f"image catalog has no entries for harness {harness!r}"
    )


def _template_env(harness_dir: str):
    import jinja2

    return jinja2.Environment(
        loader=jinja2.FileSystemLoader(harness_dir),
        undefined=jinja2.StrictUndefined,
        keep_trailing_newline=True,
    )


def _operator_facts(cfg: tt.TeamsConfig, team: tt.ProjectTeam) -> dict:
    return {
        "GIT_NAME": cfg.git.name,
        "GIT_EMAIL": cfg.git.email,
        "GIT_SIGNING_KEY": cfg.git.signing_key,
        "REGISTRY": cfg.registry,
        "HOME_DIR": cfg.home_dir or os.path.expanduser("~"),
        "REPO_OWNER": cfg.repo_owner or team.source.owner,
    }


def render_team(team: tt.ProjectTeam, bundle: SourceBundle,
                cfg: tt.TeamsConfig, project_path: str = "",
                project_repo_url: str = "") -> RenderResult:
    realm = team.realm or consts.DEFAULT_REALM
    space = team.space or consts.DEFAULT_SPACE
    stack = team.stack or consts.DEFAULT_STACK
    operator = _operator_facts(cfg, team)
    result = RenderResult()
    secrets_needed: set[str] = set()

    for project_role in team.roles:
        role = bundle.roles[project_role.ref]
        harness_names = sorted(
            set(role.harnesses) | set(team.defaults.harnesses)
        )
        if not harness_names:
            raise InvalidArgument(
                f"role {role.name!r} has no harnesses and the project sets "
                f"no defaults.harnesses"
            )
        for hname in harness_names:
            if hname not in bundle.harnesses:
                raise InvalidArgument(
                    f"role {role.name!r} references unknown harness {hname!r}"
                )
            harness = bundle.harnesses[hname]
            needs = merge_needs(role, project_role)
            image = select_image(bundle.catalog, hname, needs)
            bp_doc = _render_blueprint(
                team, role, harness, project_role, image, bundle, operator,
                realm, space, stack,
                project_path=project_path, project_repo_url=project_repo_url,
            )
            cfg_doc, bound = _bind_config(
                team, role, harness, bp_doc, cfg, operator,
                realm, space, stack,
            )
            secrets_needed.update(bound)
            result.blueprints.append(bp_doc)
            result.configs.append(cfg_doc)
            result.images_used.append(image)

    result.secrets_needed = sorted(secrets_needed)
    return result


def _render_blueprint(team, role, harness, project_role, image, bundle,
                      operator, realm, space, stack, project_path,
                      project_repo_url) -> t.Document:
    hdir = bundle.harness_dir(harness.name)
    if not harness.template:
        raise InvalidArgument(
            f"harness {harness.name!r} declares no template"
        )
    env = _template_env(hdir)
    try:
        tmpl = env.get_template(harness.template)
    except Exception as e:  # jinja2.TemplateNotFound etc.
        raise InvalidArgument(
            f"harness {harness.name!r} template {harness.template!r}: {e}"
        ) from e

    role_harness = role.harnesses.get(harness.name, tt.RoleHarness())
    ctx = {
        "role": {"NAME": role.name, "SKILLS": list(role.skills)},
        "harness": {
            "NAME": harness.name,
            "SKILL_PATH": harness.skill_path,
            "BASE_IMAGE": harness.base_image,
        },
        "needs": {
            "IMAGE": merge_needs(role, project_role),
            "REPOS": list(role.needs.repos),
            "MOUNTS": list(role.needs.mounts),
            "PARAMS": list(role.needs.params),
            "SECRETS": _role_secret_names(role, harness.name),
        },
        "harnesses": {
            "SETTINGS": role_harness.settings,
            "SANDBOX": role_harness.sandbox,
            "APPROVAL": role_harness.approval,
            "PERMISSIONS": role_harness.permissions,
            "SECRETS": list(role_harness.secrets),
        },
        "operator": operator,
        "project": {
            "NAME": team.project_dir or team.name,
            "TEAM": team.name,
            "PROJECT_DIR": project_path,
            "REPO_URL": project_repo_url,
        },
        "image": {
            "REF": image.ref,
            "IMAGE": image.image,
            "CAPABILITIES": list(image.capabilities),
        },
        "realm": realm,
        "space": space,
        "stack": stack,
    }
    try:
        rendered = tmpl.render(**ctx)
    except Exception as e:
        raise InvalidArgument(
            f"rendering {harness.name!r} template for role {role.name!r}: {e}"
        ) from e

    docs = parser.parse_documents(
        rendered, source=f"{harness.name}/{harness.template}[{role.name}]"
    )
    bps = [d for d in docs if d.kind == t.KIND_CELL_BLUEPRINT]
    if len(bps) != 1:
        raise InvalidArgument(
            f"harness {harness.name!r} template must render exactly one "
            f"CellBlueprint (got {len(bps)})"
        )
    bp = bps[0]
    bp.metadata.name = f"{team.name}-{role.name}-{harness.name}"
    bp.metadata.realm = realm
    bp.metadata.space = None
    bp.metadata.stack = None
    bp.metadata.labels[consts.LABEL_TEAM] = team.name
    return bp


def _role_secret_names(role: tt.Role, harness_name: str) -> list[str]:
    """Per-harness secrets are primary; role-level needs.secrets is the
    fallback (reference: role.go RoleHarness.Secrets vs RoleNeeds.Secrets)."""
    rh = role.harnesses.get(harness_name)
    if rh and rh.secrets:
        return sorted(set(rh.secrets))
    return sorted(set(role.needs.secrets))


def _bind_config(team, role, harness, bp_doc: t.Document, cfg, operator,
                 realm, space, stack) -> tuple[t.Document, list[str]]:
    declared_slots = {
        ref.name
        for c in bp_doc.spec.cell.containers
        for ref in c.secrets
    }
    bindings = []
    bound_names = []
    for sname in _role_secret_names(role, harness.name):
        if sname not in cfg.secrets:
            raise InvalidArgument(
                f"role {role.name!r} needs secret {sname!r} but the teams "
                f"config declares no source for it"
            )
        if sname in declared_slots:
            bindings.append(t.ConfigSecretBinding(slot=sname, secret=sname))
            bound_names.append(sname)

    values = {f"OPERATOR_{k}": v for k, v in operator.items() if v}
    values["TEAM"] = team.name
    cfg_doc = t.Document(
        kind=t.KIND_CELL_CONFIG,
        metadata=t.Metadata(
            name=f"{team.name}-{role.name}-{harness.name}",
            realm=realm, space=space, stack=stack,
            labels={consts.LABEL_TEAM: team.name},
        ),
        spec=t.CellConfigSpec(
            blueprint=bp_doc.metadata.name,
            values=values,
            secrets=bindings,
            cell_name=f"{team.name}-{role.name}-{harness.name}",
        ),
    )
    return cfg_doc, bound_names
