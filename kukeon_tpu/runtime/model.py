"""Internal (version-agnostic) resource records — the modelhub analog.

Reference: internal/modelhub (cell.go:21-100): the controller/runner operate
on these, not on wire docs. Records carry Generation/ObservedGeneration,
provenance (config/blueprint lineage) and runtime status, and round-trip
through the metadata store as JSON.
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass, field

from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.api.wire import from_wire, to_wire

# Cell phases.
PENDING = "pending"
READY = "ready"          # all containers running
DEGRADED = "degraded"    # some containers running
STOPPED = "stopped"
FAILED = "failed"

# Container states.
C_CREATED = "created"
C_RUNNING = "running"
C_EXITED = "exited"


@dataclass
class ContainerStatus:
    name: str = ""
    state: str = C_CREATED
    pid: int | None = None
    exit_code: int | None = None
    restarts: int = 0
    started_at: float | None = None
    finished_at: float | None = None
    last_restart_at: float | None = None
    # Tail of the container's log at its last non-clean exit — the operator's
    # answer to "why is this cell cycling" straight from `kuke get` (reference:
    # markCellFailed with reason, runner/start.go:186,414).
    last_error: str | None = None


@dataclass
class Provenance:
    config: str | None = None
    blueprint: str | None = None
    team: str | None = None


@dataclass
class SetupStatus:
    """Per-repo pre-start staging state (reference: the typed payloads
    kuketty reports to attach clients, internal/kuketty/setupstatus)."""

    container: str = ""
    kind: str = "repo"
    url: str = ""
    path: str = ""
    state: str = "pending"           # pending | cloning | ready | failed
    error: str | None = None


@dataclass
class CellStatus:
    phase: str = PENDING
    reason: str | None = None
    setup: list[SetupStatus] = field(default_factory=list)
    containers: list[ContainerStatus] = field(default_factory=list)
    observed_generation: int = 0
    tpu_chips: list[int] = field(default_factory=list)   # chips granted
    ip: str | None = None                # cell IP on the space bridge
    # OutOfSync detection for Config-lineage cells (reference:
    # internal/controller/reconcile_outofsync.go:38-160). out_of_sync_error
    # marks an UNDECIDABLE verdict (blueprint missing, materialize failure)
    # and is distinct from out_of_sync so `get cell` can route it separately.
    out_of_sync: bool = False
    out_of_sync_reason: str | None = None
    out_of_sync_error: str | None = None
    # Autoscaling (runtime/scaler.py): the ACTIVE replica count of a model
    # cell with minReplicas/maxReplicas bounds. None = the spec's static
    # ``replicas``. Replicas with index >= target are "parked": their
    # container specs, ports, and chip slices stay materialized (so a
    # scale-up re-starts them on exactly their grant) but the runner
    # neither starts nor heals them.
    target_replicas: int | None = None

    def container(self, name: str) -> ContainerStatus | None:
        for c in self.containers:
            if c.name == name:
                return c
        return None


@dataclass
class CellRecord:
    realm: str = ""
    space: str = ""
    stack: str = ""
    name: str = ""
    spec: t.CellSpec = field(default_factory=t.CellSpec)
    labels: dict[str, str] = field(default_factory=dict)
    provenance: Provenance = field(default_factory=Provenance)
    generation: int = 1
    created_at: float = field(default_factory=time.time)
    desired_state: str = "running"   # running | stopped
    status: CellStatus = field(default_factory=CellStatus)

    def to_json(self) -> dict:
        return to_wire(self)

    @staticmethod
    def from_json(d: dict) -> "CellRecord":
        return from_wire(CellRecord, d)


@dataclass
class ScopeRecord:
    """Realm / Space / Stack metadata record."""

    kind: str = ""
    name: str = ""
    realm: str | None = None
    space: str | None = None
    labels: dict[str, str] = field(default_factory=dict)
    spec_json: dict = field(default_factory=dict)
    created_at: float = field(default_factory=time.time)

    def to_json(self) -> dict:
        return to_wire(self)

    @staticmethod
    def from_json(d: dict) -> "ScopeRecord":
        return from_wire(ScopeRecord, d)


@dataclass
class VolumeRecord:
    realm: str = ""
    space: str | None = None
    stack: str | None = None
    name: str = ""
    reclaim_policy: str = "delete"
    labels: dict[str, str] = field(default_factory=dict)
    created_at: float = field(default_factory=time.time)

    def to_json(self) -> dict:
        return to_wire(self)

    @staticmethod
    def from_json(d: dict) -> "VolumeRecord":
        return from_wire(VolumeRecord, d)


def cell_record_from_doc(doc: t.Document) -> CellRecord:
    md = doc.metadata
    return CellRecord(
        realm=md.realm, space=md.space, stack=md.stack, name=md.name,
        spec=doc.spec, labels=dict(md.labels),
        provenance=Provenance(
            config=md.labels.get("kukeon.io/config"),
            blueprint=md.labels.get("kukeon.io/blueprint"),
            team=md.labels.get("kukeon.io/team"),
        ),
    )


def spec_to_json(spec) -> dict:
    return to_wire(spec)


def cell_spec_from_json(d: dict) -> t.CellSpec:
    return from_wire(t.CellSpec, d)
