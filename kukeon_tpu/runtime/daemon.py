"""kukeond: the unix-socket JSON-RPC daemon + reconcile loops.

Reference: internal/daemon (server.go:42-260, rpcservice.go:30-470). The
server owns the listener (socket mode 0660), a PID file, the RPC verb
facade, an eager startup reconcile pass, and the periodic reconcile ticker
(default 30s — KUKEOND_RECONCILE_INTERVAL).

Protocol: newline-delimited JSON frames on a persistent connection:
  -> {"id": 1, "method": "CreateCell", "params": {...}}
  <- {"id": 1, "result": {...}} | {"id": 1, "error": {"code": "...", "message": "..."}}
"""

from __future__ import annotations

import json
import os
import signal
import socket
import socketserver
import threading
import time
import traceback

from kukeon_tpu import sanitize
from kukeon_tpu.obs import federate as fed
from kukeon_tpu.obs import percentile_from_counts
from kukeon_tpu.obs.tsdb import parse_window as tsdb_parse_window
from kukeon_tpu.runtime import consts
from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.apply import parser
from kukeon_tpu.runtime.cells import ProcessBackend
from kukeon_tpu.runtime.cgroups import CgroupManager
from kukeon_tpu.runtime.controller import Controller
from kukeon_tpu.runtime.devices import TPUDeviceManager
from kukeon_tpu.runtime.errors import (
    FailedPrecondition,
    InvalidArgument,
    KukeonError,
    NotFound,
)
from kukeon_tpu.runtime.metadata import MetadataStore
from kukeon_tpu.runtime.runner import Runner
from kukeon_tpu.runtime.store import ResourceStore

PROTOCOL_VERSION = "v1"

# Per-cell /metrics scrape budget for fleet federation (seconds). One hung
# cell must cost the federated scrape at most this long, never block it.
SCRAPE_TIMEOUT_ENV = "KUKEON_SCRAPE_TIMEOUT_S"
DEFAULT_SCRAPE_TIMEOUT_S = 2.0

# Background telemetry-loop cadence: every tick scrapes the fleet into the
# in-daemon TSDB (obs/tsdb.py) and evaluates the alert rules.
SCRAPE_INTERVAL_ENV = "KUKEON_SCRAPE_INTERVAL_S"
DEFAULT_SCRAPE_INTERVAL_S = 10.0


def model_cell_endpoints(ctl) -> list[tuple[str, str, dict]]:
    """(cell key, base url, record) for every running model cell.

    The endpoint is the cell's bridge IP when the space network attached
    one, else the host loopback (hostNetwork cells and the process backend
    both bind there). A replicated cell contributes its gateway (under the
    cell's own key, on the base port) AND every replica (``key/rI`` on
    ``port+1+i``) so a federated scrape sees the whole replica set."""
    out: list[tuple[str, str, dict]] = []
    for realm in ctl.list_realms():
        for rec in ctl.list_cells(realm):
            m = (rec.get("spec") or {}).get("model")
            if not m:
                continue
            st = rec.get("status") or {}
            if st.get("phase") not in ("ready", "degraded"):
                continue
            host = st.get("ip") or "127.0.0.1"
            key = "/".join((rec["realm"], rec["space"], rec["stack"],
                            rec["name"]))
            port = m.get("port", 9000)
            out.append((key, f"http://{host}:{port}", rec))
            replicas = m.get("replicas") or 1
            bound = max(replicas, m.get("maxReplicas") or 0)
            if bound > 1:
                # Only ACTIVE replicas federate: a parked (scaled-down)
                # replica is intentionally dark, and scraping it would
                # page CellScrapeDown for a replica the scaler chose to
                # turn off.
                active = st.get("targetReplicas") or replicas
                for i in range(max(1, min(active, bound))):
                    out.append((f"{key}/r{i}",
                                f"http://{host}:{port + 1 + i}", rec))
    return out


def scrape_fleet(ctl, timeout_s: float | None = None) -> list[dict]:
    """Pull every running model cell's /metrics concurrently, each under
    its own timeout. Never raises: an unreachable or garbage-emitting cell
    yields ``ok: False`` with the error, and the pass carries on — one dead
    cell must not blind the operator to the rest of the fleet."""
    import urllib.request

    if timeout_s is None:
        timeout_s = float(os.environ.get(SCRAPE_TIMEOUT_ENV, "") or
                          DEFAULT_SCRAPE_TIMEOUT_S)
    cells = model_cell_endpoints(ctl)
    results: list[dict | None] = [None] * len(cells)

    def work(i: int, key: str, url: str, rec: dict) -> None:
        t0 = time.monotonic()
        try:
            with urllib.request.urlopen(url + "/metrics",
                                        timeout=timeout_s) as r:
                text = r.read().decode()
            results[i] = {"cell": key, "url": url, "record": rec,
                          "ok": True, "families": fed.parse(text),
                          "elapsedS": round(time.monotonic() - t0, 4)}
        except Exception as e:  # noqa: BLE001 — a dead cell is a data point, not a failure
            results[i] = {"cell": key, "url": url, "record": rec,
                          "ok": False, "error": f"{type(e).__name__}: {e}",
                          "elapsedS": round(time.monotonic() - t0, 4)}

    threads = [threading.Thread(target=work, args=(i, key, url, rec),
                                daemon=True, name=f"scrape-{key}")
               for i, (key, url, rec) in enumerate(cells)]
    for t in threads:
        t.start()
    # urllib's timeout bounds connect and each read separately; the join
    # backstop keeps a pathological socket from wedging the whole pass.
    deadline = time.monotonic() + timeout_s * 2 + 1.0
    for t in threads:
        t.join(timeout=max(0.1, deadline - time.monotonic()))
    return [
        r if r is not None else
        {"cell": key, "url": url, "record": rec, "ok": False,
         "error": f"scrape did not finish within {timeout_s * 2 + 1.0:.1f}s",
         "elapsedS": timeout_s}
        for r, (key, url, rec) in zip(results, cells)
    ]


def fetch_traces(endpoints: list[tuple[str, str, dict]],
                 trace_id: str | None = None, n: int = 50,
                 timeout_s: float | None = None) -> list[dict]:
    """Union every cell's ``/v1/trace`` ring (gateway included — it is
    the base endpoint of a replicated cell) into one span list, each span
    tagged with its cell key. Concurrent, per-cell timeout, never raises:
    a cell without a tracer (embedding flavor answers 404) or an
    unreachable one simply contributes nothing — federated trace
    reconstruction must degrade span-by-span, not fail wholesale.

    Spans come back sorted by wall-clock start so a renderer can lay the
    cross-component timeline without re-sorting."""
    import urllib.request
    from urllib.parse import quote

    if timeout_s is None:
        timeout_s = float(os.environ.get(SCRAPE_TIMEOUT_ENV, "") or
                          DEFAULT_SCRAPE_TIMEOUT_S)
    query = (f"?trace_id={quote(trace_id)}" if trace_id
             else f"?n={int(n)}")
    results: list[list[dict]] = [[] for _ in endpoints]

    def work(i: int, key: str, url: str) -> None:
        try:
            with urllib.request.urlopen(url + "/v1/trace" + query,
                                        timeout=timeout_s) as r:
                spans = json.loads(r.read()).get("spans", [])
        except Exception:  # noqa: BLE001 — a dead/traceless cell contributes nothing
            return
        for s in spans:
            if isinstance(s, dict):
                s["cell"] = key
                results[i].append(s)

    threads = [threading.Thread(target=work, args=(i, key, url),
                                daemon=True, name=f"trace-{key}")
               for i, (key, url, _rec) in enumerate(endpoints)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + timeout_s * 2 + 1.0
    for t in threads:
        t.join(timeout=max(0.1, deadline - time.monotonic()))
    out = [s for part in results for s in part]
    out.sort(key=lambda s: s.get("startedAt") or 0.0)
    return out


def fetch_timelines(endpoints: list[tuple[str, str, dict]],
                    n: int = 50,
                    timeout_s: float | None = None) -> list[dict]:
    """Union every cell's ``/v1/timeline`` flight-recorder ring (gateway
    included) into one engine-step list, each step tagged with its cell
    key. Same degradation contract as :func:`fetch_traces`: concurrent,
    per-cell timeout, never raises — a cell without a recorder or an
    unreachable one contributes nothing.

    Steps come back sorted by wall-clock stamp (oldest first) so
    `kuke timeline` can lay the fleet-wide step sequence without
    re-sorting."""
    import urllib.request

    if timeout_s is None:
        timeout_s = float(os.environ.get(SCRAPE_TIMEOUT_ENV, "") or
                          DEFAULT_SCRAPE_TIMEOUT_S)
    results: list[list[dict]] = [[] for _ in endpoints]

    def work(i: int, key: str, url: str) -> None:
        try:
            with urllib.request.urlopen(
                    url + f"/v1/timeline?n={int(n)}",
                    timeout=timeout_s) as r:
                steps = json.loads(r.read()).get("steps", [])
        except Exception:  # noqa: BLE001 — a dead/recorderless cell contributes nothing
            return
        for s in steps:
            if isinstance(s, dict):
                s["cell"] = key
                results[i].append(s)

    threads = [threading.Thread(target=work, args=(i, key, url),
                                daemon=True, name=f"timeline-{key}")
               for i, (key, url, _rec) in enumerate(endpoints)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + timeout_s * 2 + 1.0
    for t in threads:
        t.join(timeout=max(0.1, deadline - time.monotonic()))
    out = [s for part in results for s in part]
    out.sort(key=lambda s: s.get("t") or 0.0)
    return out


def _scrape_ok_family(scrapes: list[dict]) -> "fed.Family":
    """The per-cell scrape verdict as a synthetic family — both the
    federated Metrics exposition and the telemetry loop's TSDB ingest
    carry it, so `kuke query kukeon_cell_scrape_ok` and the CellScrapeDown
    alert read the same signal the operator sees."""
    return fed.Family(
        "kukeon_cell_scrape_ok", "gauge",
        "1 when this pass scraped the cell's /metrics; 0 marks a "
        "stale/unreachable cell.",
        [("kukeon_cell_scrape_ok", {"cell": s["cell"]},
          "1" if s["ok"] else "0") for s in scrapes])


class FleetTelemetry:
    """The daemon's telemetry backbone: a scrape tick pulls every cell's
    /metrics (the PR-4 parse/relabel path), records scrape health, ingests
    everything — the daemon's own registry included — into the in-daemon
    TSDB, and evaluates the alert rules.

    Separated from the server so tests drive :meth:`tick` synchronously
    with an injectable clock; :class:`DaemonServer` runs it on a
    background thread every ``KUKEON_SCRAPE_INTERVAL_S`` (default 10s).
    Scrapes and TSDB row-building happen outside every lock (kukesan-clean
    under ``KUKEON_SANITIZE=1``: snapshot outside, swap under lock)."""

    def __init__(self, ctl, registry=None, clock=time.time,
                 tsdb=None, rules=None):
        from kukeon_tpu.obs import alerts as alerts_mod
        from kukeon_tpu.obs import tsdb as tsdb_mod

        self.ctl = ctl
        self._clock = clock
        self._reg = registry if registry is not None else ctl.runner.registry
        self.tsdb = tsdb if tsdb is not None else tsdb_mod.TSDB(clock=clock)
        self.user_rules_error: str | None = None
        if rules is None:
            rules = alerts_mod.BUILTIN_RULES
            try:
                rules += alerts_mod.load_user_rules()
            except ValueError as e:
                # A typo'd user rule file must not take the daemon down —
                # but the error must stay visible: logged here, surfaced
                # by the Alerts RPC / `kuke alerts` until fixed.
                self.user_rules_error = str(e)
                import logging
                logging.getLogger("kukeon.alerts").error(
                    "ignoring %s: %s", alerts_mod.RULES_ENV, e)
        self.alerts = alerts_mod.AlertEngine(
            self.tsdb, rules=rules, registry=self._reg, clock=clock)
        # The autoscaling reconcile loop rides this same tick (after alert
        # evaluation, so its decision rules see the freshest ingest).
        from kukeon_tpu.runtime.scaler import FleetScaler

        self.scaler = FleetScaler(ctl, self.tsdb, registry=self._reg,
                                  clock=clock)
        self._m_scrape_dur = self._reg.histogram(
            "kukeon_daemon_scrape_duration_seconds",
            "Per-cell /metrics scrape wall time in the telemetry loop.",
            labels=("cell",))
        self._m_ticks = self._reg.counter(
            "kukeon_daemon_scrape_ticks_total",
            "Telemetry-loop scrape ticks completed.")
        self._m_consec_fail = self._reg.gauge(
            "kukeon_daemon_scrape_failures_consecutive",
            "Consecutive failed scrapes per cell (0 on success): a "
            "flapping cell oscillates, a dead one climbs.",
            labels=("cell",))
        # Only the telemetry tick mutates this (one loop thread); reads
        # happen through the gauge snapshot.
        self._consec_fail: dict[str, int] = {}
        # Last wall-clock time each cell's /metrics scrape SUCCEEDED.
        # Shared between the telemetry loop and the on-demand
        # Metrics/ScrapeCells RPCs (connection threads), hence the lock;
        # feeds kukeon_cell_scrape_age_seconds and `kuke top` dimming.
        self._ages_lock = sanitize.lock("FleetTelemetry._ages_lock")
        self._last_good: dict[str, float] = {}   # guarded-by: _ages_lock
        self._reg.gauge(
            "kukeon_tsdb_series",
            "Time series currently resident in the in-daemon store."
        ).set_function(lambda: self.tsdb.stats()["series"])
        self._reg.gauge(
            "kukeon_tsdb_points",
            "Total samples currently resident in the in-daemon store."
        ).set_function(lambda: self.tsdb.stats()["points"])
        self._reg.gauge(
            "kukeon_tsdb_dropped_series",
            "New series refused because the store hit "
            "KUKEON_TSDB_MAX_SERIES."
        ).set_function(lambda: self.tsdb.stats()["droppedSeries"])

    def note_scrapes(self, scrapes: list[dict],
                     at: float | None = None) -> dict[str, float]:
        """Record the last-good wall time per cell from any federated pass
        (the telemetry tick or an on-demand Metrics/ScrapeCells RPC),
        forget cells that left the fleet, and return the current
        {cell: seconds since last good scrape} map."""
        now = self._clock() if at is None else at
        seen = {s["cell"] for s in scrapes}
        with self._ages_lock:
            for s in scrapes:
                if s["ok"]:
                    self._last_good[s["cell"]] = now
            for cell in [c for c in self._last_good if c not in seen]:
                # Departed cell: a frozen age sample would read as "stale
                # cell" forever in `kuke top` — drop it with the cell.
                del self._last_good[cell]
            return {c: max(0.0, now - t)
                    for c, t in self._last_good.items()}

    def scrape_ages(self, at: float | None = None) -> dict[str, float]:
        """{cell: seconds since its last GOOD scrape}, cells never seen
        good absent (kukeon_cell_scrape_ok 0 marks those)."""
        now = self._clock() if at is None else at
        with self._ages_lock:
            return {c: max(0.0, now - t)
                    for c, t in self._last_good.items()}

    def tick(self, at: float | None = None) -> list[dict]:
        """One telemetry pass; returns the alert transitions it caused."""
        from kukeon_tpu.obs import expo

        now = self._clock() if at is None else at
        scrapes = scrape_fleet(self.ctl)
        seen = set()
        for s in scrapes:
            self._m_scrape_dur.observe(s["elapsedS"], cell=s["cell"])
            n = 0 if s["ok"] else self._consec_fail.get(s["cell"], 0) + 1
            self._consec_fail[s["cell"]] = n
            self._m_consec_fail.set(n, cell=s["cell"])
            seen.add(s["cell"])
        for cell in [c for c in self._consec_fail if c not in seen]:
            # The cell left the fleet; keep its gauge from lying forever.
            del self._consec_fail[cell]
        parts: list[dict] = []
        # Own registry AFTER the duration/failure updates above so this
        # very tick's scrape health lands in the store it feeds.
        parts.append(fed.parse(expo.render(self._reg)))
        for s in scrapes:
            if s["ok"]:
                fed.inject_label(s["families"], cell=s["cell"])
                parts.append(s["families"])
        parts.append({"kukeon_cell_scrape_ok": _scrape_ok_family(scrapes)})
        ages = self.note_scrapes(scrapes, at=now)
        if ages:
            parts.append({"kukeon_cell_scrape_age_seconds":
                          fed.scrape_age_family(ages)})
        for p in parts:
            self.tsdb.ingest(p, at=now)
        self._m_ticks.inc()
        transitions = self.alerts.evaluate(at=now)
        # The scaler reconciles AFTER alerting so its debounce rules read
        # this very tick's ingest. Its failures (including the armed
        # scaler.tick chaos seam) are survival-bounded HERE: counted,
        # logged, and the telemetry loop carries on — a crashed scaler
        # must degrade to "no scaling", never take sensing down with it.
        try:
            self.scaler.tick(at=now)
        except Exception:  # noqa: BLE001 — the chaos contract
            self.scaler.note_error()
            import logging
            logging.getLogger("kukeon.scaler").exception(
                "scaler tick failed; fleet unchanged this tick")
        return transitions


def _sample_value(fams: dict, name: str, **match) -> float | None:
    fam = fams.get(name)
    if fam is None:
        return None
    for _n, labels, value in fam.samples:
        if all(labels.get(k) == v for k, v in match.items()):
            return float(value)
    return None


def _sample_sum(fams: dict, name: str) -> float | None:
    fam = fams.get(name)
    if fam is None or not fam.samples:
        return None
    return sum(float(v) for _n, _l, v in fam.samples)


def summarize_cell_scrape(fams: dict) -> dict:
    """One cell's scraped families -> the `kuke top` row fields."""
    out: dict = {}
    info = fams.get("kukeon_cell_info")
    if info is not None and info.samples:
        out["model"] = info.samples[0][1].get("model")
    ready = _sample_value(fams, "kukeon_cell_ready")
    if ready is not None:
        out["ready"] = bool(ready)
    uptime = _sample_value(fams, "kukeon_cell_uptime_seconds")
    total = _sample_sum(fams, "kukeon_engine_requests_total")
    if uptime and total is not None:
        # Single-scrape QPS is necessarily the lifetime average; rate-over-
        # window lives in Prometheus once the federated scrape lands there.
        out["qps"] = round(total / max(uptime, 1e-9), 3)
    q = _sample_value(fams, "kukeon_engine_queue_depth")
    if q is not None:
        out["queueDepth"] = int(q)
    ttft = fams.get("kukeon_engine_ttft_seconds")
    if ttft is not None:
        bounds, counts = fed.histogram_counts(ttft)
        if bounds and sum(counts):
            out["ttftP50S"] = round(
                percentile_from_counts(bounds, counts, 0.5), 5)
            out["ttftP95S"] = round(
                percentile_from_counts(bounds, counts, 0.95), 5)
        # Exemplar: the trace id attached to the highest populated TTFT
        # bucket — `kuke top`'s p95 row links straight to a trace that
        # `kuke trace <id>` can reconstruct.
        def _le(labels: dict) -> float:
            le = labels.get("le", "")
            return float("inf") if le == "+Inf" else float(le or 0)
        if ttft.exemplars:
            _n, _lab, tid, _v = max(ttft.exemplars,
                                    key=lambda e: _le(e[1]))
            if tid:
                out["ttftP95TraceId"] = tid
    for key, name in (("hbmInUseBytes", "kukeon_hbm_bytes_in_use"),
                      ("hbmLimitBytes", "kukeon_hbm_bytes_limit")):
        v = _sample_sum(fams, name)
        if v is not None:
            out[key] = int(v)
    # Per-chip breakdown: the device collector already labels every HBM
    # sample with {device=}; federate those labels instead of collapsing
    # them so `kuke top` can show each chip of a sharded cell (a skewed
    # shard is invisible in the aggregate). Aggregate keys above stay —
    # single-chip rows and the alert rules keep reading them.
    per_device: dict[str, dict] = {}
    for key, name in (("inUse", "kukeon_hbm_bytes_in_use"),
                      ("limit", "kukeon_hbm_bytes_limit"),
                      ("peak", "kukeon_hbm_bytes_peak")):
        fam = fams.get(name)
        if fam is None:
            continue
        for _n, labels, value in fam.samples:
            dev = labels.get("device")
            if dev is not None:
                per_device.setdefault(dev, {})[key] = int(value)
    if per_device:
        out["hbmPerDevice"] = {
            d: per_device[d]
            for d in sorted(per_device, key=lambda x: (len(x), x))
        }
    mesh = _sample_value(fams, "kukeon_engine_mesh_chips")
    if mesh is not None:
        out["meshChips"] = int(mesh)
    burn = _sample_value(fams, "kukeon_slo_burn_rate",
                         slo="availability", window="1h")
    if burn is not None:
        out["sloBurn1h"] = round(burn, 4)
    return out


def summarize_gateway_scrape(fams: dict) -> dict:
    """A gateway scrape's `kuke top` row: aggregate QPS over its replicas,
    retry count, and the per-replica ready census (the gateway's own
    routing view — the same gauges it routes on)."""
    def family_total(name: str) -> float | None:
        """Sum over a family's samples; a DECLARED labelled counter with no
        label sets yet is an honest zero, not an absence."""
        fam = fams.get(name)
        if fam is None:
            return None
        return sum(float(v) for _n, _l, v in fam.samples)

    out: dict = {"kind": "gateway"}
    info = fams.get("kukeon_gateway_info")
    if info is not None and info.samples:
        out["model"] = info.samples[0][1].get("model")
    uptime = _sample_value(fams, "kukeon_gateway_uptime_seconds")
    total = family_total("kukeon_gateway_requests_total")
    if uptime and total is not None:
        out["qps"] = round(total / max(uptime, 1e-9), 3)
    retries = family_total("kukeon_gateway_retries_total")
    if retries is not None:
        out["retries"] = int(retries)
    shed = _sample_value(fams, "kukeon_gateway_shed_total")
    if shed is not None:
        out["shed"] = int(shed)
    ready_f = fams.get("kukeon_gateway_replica_ready")
    if ready_f is not None and ready_f.samples:
        vals = [float(v) for _n, _l, v in ready_f.samples]
        out["readyReplicas"] = int(sum(vals))
        out["replicas"] = len(vals)
    n = _sample_value(fams, "kukeon_gateway_replicas")
    if n is not None:
        out["replicas"] = int(n)
    # Disaggregated KV handoff activity: count + p50 cost straight from
    # the gateway's own histogram (zero on a mixed fleet — the families
    # are declared unconditionally).
    hand = fams.get("kukeon_handoff_seconds")
    if hand is not None:
        bounds, counts = fed.histogram_counts(hand)
        total_h = sum(counts)
        out["handoffs"] = int(total_h)
        if total_h:
            p50 = percentile_from_counts(bounds, counts, 0.5)
            if p50 is not None:
                out["handoffMsP50"] = round(p50 * 1000, 1)
        fallbacks = _sample_value(fams, "kukeon_handoff_fallback_total")
        if fallbacks:
            out["handoffFallbacks"] = int(fallbacks)
    out["ready"] = bool(out.get("readyReplicas"))
    return out


def _rollout_restart(ctl, rec, container_name: str) -> None:
    """The RolloutCell restart seam: bring one drained replica container
    back up on its own chip grant (module-level so tests can wrap it to
    also respawn their fake replica servers)."""
    ctl.runner.restart_container(rec.realm, rec.space, rec.stack, rec.name,
                                 container_name)


def build_controller(run_path: str,
                     settings: "config.Settings | None" = None) -> Controller:
    from kukeon_tpu.runtime import config
    from kukeon_tpu.runtime.net import NetworkManager
    from kukeon_tpu.runtime.runner import RunnerOptions

    from kukeon_tpu.runtime.cells import namespace as nsbackend

    s = settings or config.server_settings(run_path)
    ms = MetadataStore(run_path)
    store = ResourceStore(ms)
    cg = CgroupManager()
    # Real isolation when the host can do it (root + kukecell); the
    # process backend remains the non-root/dev fallback.
    # KUKEON_ISOLATION=0|process forces the fallback, =1 forces namespaces.
    backend = (
        nsbackend.NamespaceBackend() if nsbackend.available() else ProcessBackend()
    )
    runner = Runner(
        store,
        backend,
        cgroups=cg if cg.available() else None,
        devices=TPUDeviceManager(ms),
        netman=NetworkManager(
            store, subnet_pool=s.get("KUKEON_POD_SUBNET_CIDR")
        ),
        options=RunnerOptions(
            stop_grace_s=s.get("KUKEON_STOP_GRACE_SECONDS"),
            disk_pressure_block_pct=s.get("KUKEOND_DISK_PRESSURE_BLOCK_PCT"),
        ),
    )
    return Controller(store, runner)


class RPCService:
    """Verb facade mapping RPC methods onto the controller
    (reference: KukeonV1Service, rpcservice.go:30-470)."""

    def __init__(self, ctl: Controller, server: "DaemonServer | None" = None):
        self.ctl = ctl
        self.server = server
        self.started_at = time.time()
        # Daemon-side metrics land on the runner's registry so one scrape
        # (Metrics RPC / `kuke daemon metrics`) covers RPC traffic, the
        # reconcile loop, and cell lifecycle together.
        reg = ctl.runner.registry
        reg.gauge("kukeon_daemon_uptime_seconds",
                  "Seconds since the RPC service came up.").set_function(
            lambda: time.time() - self.started_at)
        self._m_rpc = reg.counter(
            "kukeon_daemon_rpc_requests_total",
            "RPC calls by method and result.", labels=("method", "result"))
        # The fleet telemetry backbone (scrape history + alerting). The
        # RPC service owns the state so Query/Alerts work on any service
        # instance; DaemonServer drives tick() on its background loop.
        self.telemetry = FleetTelemetry(ctl)

    # Every public method is an RPC endpoint.

    def Ping(self) -> dict:
        return {
            "protocol": PROTOCOL_VERSION,
            "pid": os.getpid(),
            "uptimeSeconds": time.time() - self.started_at,
        }

    def ApplyDocuments(self, yaml: str, team: str | None = None,
                       prune: bool = False) -> list[dict]:
        results = self.ctl.apply_documents(yaml, team=team, prune=prune)
        return [vars(r) for r in results]

    def DeleteDocuments(self, yaml: str) -> list[dict]:
        return [vars(r) for r in self.ctl.delete_documents(yaml)]

    # Scopes.
    def CreateRealm(self, name: str) -> dict:
        self.ctl.create_realm(name)
        return self.ctl.get_realm(name)

    def CreateSpace(self, realm: str, name: str) -> dict:
        self.ctl.create_space(realm, name)
        return self.ctl.get_space(realm or consts.DEFAULT_REALM, name)

    def CreateStack(self, realm: str, space: str, name: str) -> dict:
        self.ctl.create_stack(realm, space, name)
        return self.ctl.get_stack(realm or consts.DEFAULT_REALM,
                                  space or consts.DEFAULT_SPACE, name)

    def GetRealm(self, name: str) -> dict:
        return self.ctl.get_realm(name)

    def GetSpace(self, realm: str, name: str) -> dict:
        return self.ctl.get_space(realm, name)

    def GetStack(self, realm: str, space: str, name: str) -> dict:
        return self.ctl.get_stack(realm, space, name)

    def ListRealms(self) -> list[str]:
        return self.ctl.list_realms()

    def ListSpaces(self, realm: str) -> list[str]:
        return self.ctl.list_spaces(realm)

    def ListStacks(self, realm: str, space: str) -> list[str]:
        return self.ctl.list_stacks(realm, space)

    def DeleteRealm(self, name: str, purge: bool = False) -> None:
        self.ctl.delete_realm(name, purge)

    def DeleteSpace(self, realm: str, name: str, purge: bool = False) -> None:
        self.ctl.delete_space(realm, name, purge)

    def DeleteStack(self, realm: str, space: str, name: str, purge: bool = False) -> None:
        self.ctl.delete_stack(realm, space, name, purge)

    # Cells.
    def CreateCell(self, doc: dict, start: bool = True) -> dict:
        parsed = parser.parse_document(doc, "CreateCell.doc")
        if parsed.kind != t.KIND_CELL:
            raise InvalidArgument("CreateCell expects a Cell document")
        return self.ctl.create_cell(parsed, start=start)

    def GetCell(self, realm: str, space: str, stack: str, name: str) -> dict:
        return self.ctl.get_cell(realm, space, stack, name)

    def ListCells(self, realm: str, space: str | None = None,
                  stack: str | None = None) -> list[dict]:
        return self.ctl.list_cells(realm, space, stack)

    def StartCell(self, realm: str, space: str, stack: str, name: str) -> dict:
        return self.ctl.start_cell(realm, space, stack, name)

    def StopCell(self, realm: str, space: str, stack: str, name: str) -> dict:
        return self.ctl.stop_cell(realm, space, stack, name)

    def KillCell(self, realm: str, space: str, stack: str, name: str) -> dict:
        return self.ctl.kill_cell(realm, space, stack, name)

    def DeleteCell(self, realm: str, space: str, stack: str, name: str,
                   force: bool = False) -> None:
        self.ctl.delete_cell(realm, space, stack, name, force)

    # Secrets / blueprints / configs / volumes.
    def PutSecret(self, doc: dict) -> None:
        self.ctl.put_secret(parser.parse_document(doc, "PutSecret.doc"))

    def ListSecrets(self, realm: str, space: str | None = None,
                    stack: str | None = None) -> list[str]:
        return self.ctl.get_secret_names(realm, space, stack)

    def DeleteSecret(self, realm: str, space: str | None, stack: str | None,
                     name: str) -> None:
        self.ctl.delete_secret(realm, space, stack, name)

    def PutBlueprint(self, doc: dict) -> None:
        self.ctl.put_blueprint(parser.parse_document(doc, "PutBlueprint.doc"))

    def ListBlueprints(self, realm: str, space: str | None = None,
                       stack: str | None = None) -> list[str]:
        return self.ctl.list_blueprints(realm, space, stack)

    def DeleteBlueprint(self, realm: str, space: str | None, stack: str | None,
                        name: str) -> None:
        self.ctl.delete_blueprint(realm, space, stack, name)

    def PutConfig(self, doc: dict) -> None:
        self.ctl.put_config(parser.parse_document(doc, "PutConfig.doc"))

    def ListConfigs(self, realm: str, space: str | None = None,
                    stack: str | None = None) -> list[str]:
        return self.ctl.list_configs(realm, space, stack)

    def DeleteConfig(self, realm: str, space: str | None, stack: str | None,
                     name: str) -> None:
        self.ctl.delete_config(realm, space, stack, name)

    def PutVolume(self, doc: dict) -> None:
        self.ctl.put_volume(parser.parse_document(doc, "PutVolume.doc"))

    def ListVolumes(self, realm: str, space: str | None = None,
                    stack: str | None = None) -> list[str]:
        return self.ctl.list_volumes(realm, space, stack)

    def DeleteVolume(self, realm: str, space: str | None, stack: str | None,
                     name: str) -> None:
        self.ctl.delete_volume(realm, space, stack, name)

    def RunBlueprint(self, realm: str, space: str | None, stack: str | None,
                     blueprint: str, values: dict | None = None) -> dict:
        return self.ctl.run_blueprint(realm, space, stack, blueprint, values or {})

    def MaterializeConfig(self, realm: str, space: str | None, stack: str | None,
                          name: str) -> dict:
        return self.ctl.materialize_config(realm, space, stack, name)

    # Attach / logs: the daemon returns host paths; bytes flow directly
    # between the client and kuketty (reference design, attach.go:17-23).
    def AttachContainer(self, realm: str, space: str, stack: str, cell: str,
                        container: str | None = None) -> dict:
        rec_json = self.ctl.get_cell(realm, space, stack, cell)
        rec_containers = rec_json["status"]["containers"]
        if container is None:
            attachables = [
                c.name for c in self._cell_specs(realm, space, stack, cell)
                if c.attachable
            ]
            if not attachables:
                raise InvalidArgument(f"cell {cell!r} has no attachable container")
            container = attachables[0]
        st = next((c for c in rec_containers if c["name"] == container), None)
        if st is None:
            raise NotFound(f"container {container!r} not found in cell {cell!r}")
        if st["state"] != "running":
            raise InvalidArgument(f"container {container!r} is {st['state']}, not running")
        cdir = self.ctl.store.container_dir(realm, space, stack, cell, container)
        return {
            "socketPath": os.path.join(cdir, consts.TTY_SOCKET),
            "capturePath": os.path.join(cdir, consts.CAPTURE_FILE),
        }

    def Log(self, realm: str, space: str, stack: str, cell: str,
            container: str | None = None) -> dict:
        specs = self._cell_specs(realm, space, stack, cell)
        if container is None:
            if not specs:
                raise NotFound(f"cell {cell!r} has no containers")
            container = specs[0].name
        spec = next((c for c in specs if c.name == container), None)
        if spec is None:
            raise NotFound(f"container {container!r} not found in cell {cell!r}")
        cdir = self.ctl.store.container_dir(realm, space, stack, cell, container)
        # Exactly one of capture (attachable) or shim log (reference:
        # kukeonv1/types.go:725-746).
        if spec.attachable:
            return {"path": os.path.join(cdir, consts.CAPTURE_FILE), "kind": "capture"}
        return {"path": os.path.join(cdir, consts.SHIM_LOG), "kind": "log"}

    def _cell_specs(self, realm, space, stack, cell) -> list[t.ContainerSpec]:
        rec = self.ctl.store.read_cell(realm, space, stack, cell)
        return self.ctl.runner.cell_containers(rec)

    # Images (reference: kuke image verbs over internal/ctr image.go).
    def _image_store(self):
        from kukeon_tpu.runtime.images import ImageStore

        return ImageStore(self.ctl.store.ms.root)

    def ListImages(self) -> list[dict]:
        return [m.to_json() for m in self._image_store().list()]

    def GetImage(self, ref: str) -> dict:
        return self._image_store().get(ref).to_json()

    def DeleteImage(self, ref: str) -> None:
        from kukeon_tpu.runtime.images import split_ref

        # In-use guard: deleting an image a cell still references would brick
        # that cell's next restart (its container context can't resolve).
        want = "%s:%s" % split_ref(ref)
        in_use = {"%s:%s" % split_ref(r) for r in self.ctl.images_in_use()}
        if want in in_use:
            raise FailedPrecondition(
                f"image {ref!r} is referenced by a cell spec; "
                "delete the cell first or use prune"
            )
        self._image_store().delete(ref)

    def PruneImages(self) -> list[str]:
        return self._image_store().prune(self.ctl.images_in_use())

    def LoadImage(self, tarPath: str, ref: str) -> dict:
        return self._image_store().load_tar(tarPath, ref).to_json()

    def PullImage(self, ref: str, insecure: bool | None = None) -> dict:
        from kukeon_tpu.runtime import registry

        return registry.pull(self._image_store(), ref, insecure=insecure).to_json()

    def PushImage(self, ref: str, dest: str | None = None,
                  insecure: bool | None = None) -> str:
        from kukeon_tpu.runtime import registry

        return registry.push(self._image_store(), ref, dest=dest,
                             insecure=insecure)

    def SaveImage(self, ref: str, tarPath: str) -> None:
        self._image_store().save_tar(ref, tarPath)

    def ReconcileNow(self) -> dict:
        return self.ctl.reconcile_cells()

    def Metrics(self, federate: bool = True) -> dict:
        """Prometheus text exposition of the daemon process — RPC traffic,
        reconcile-loop activity, the runner's cell-lifecycle metrics —
        UNIONED with every running model cell's own /metrics, each cell's
        samples labelled ``cell="realm/space/stack/name"``. One daemon
        scrape sees the whole host's serving fleet; an unreachable cell is
        marked ``kukeon_cell_scrape_ok{cell=} 0`` instead of failing the
        scrape. The CLI surfaces it as `kuke daemon metrics`."""
        from kukeon_tpu.obs import expo

        own_text = expo.render(self.ctl.runner.registry)
        if not federate:
            return {"contentType": expo.CONTENT_TYPE, "text": own_text}
        scrapes = scrape_fleet(self.ctl)
        if not scrapes:
            return {"contentType": expo.CONTENT_TYPE, "text": own_text}
        ages = self.telemetry.note_scrapes(scrapes)
        parts = [fed.parse(own_text)]
        for s in scrapes:
            if s["ok"]:
                fed.inject_label(s["families"], cell=s["cell"])
                parts.append(s["families"])
        merged = fed.merge(parts)
        merged["kukeon_cell_scrape_ok"] = _scrape_ok_family(scrapes)
        if ages:
            merged["kukeon_cell_scrape_age_seconds"] = (
                fed.scrape_age_family(ages))
        return {"contentType": expo.CONTENT_TYPE,
                "text": fed.render(merged)}

    def ScrapeCells(self, timeoutS: float | None = None) -> dict:
        """One federated pass over the fleet, summarized per cell for
        `kuke top`: readiness, lifetime QPS, TTFT p50/p95, queue depth,
        HBM in-use/limit, restart counts — all read from each cell's own
        /metrics plus the daemon's records, never a second bookkeeping
        path."""
        rows = []
        scrapes = scrape_fleet(self.ctl, timeoutS)
        ages = self.telemetry.note_scrapes(scrapes)
        for s in scrapes:
            rec = s["record"]
            row = {"cell": s["cell"], "url": s["url"], "ok": s["ok"],
                   "phase": (rec.get("status") or {}).get("phase"),
                   "restarts": sum(
                       c.get("restarts", 0) for c in
                       (rec.get("status") or {}).get("containers", []))}
            m = (rec.get("spec") or {}).get("model") or {}
            base_key = "/".join((rec.get("realm", ""), rec.get("space", ""),
                                 rec.get("stack", ""), rec.get("name", "")))
            if m.get("maxReplicas") and s["cell"] == base_key:
                # The gateway row of an autoscaled cell carries the scale
                # state so `kuke top` shows desired/bounds at a glance.
                row["scale"] = {
                    "desired": ((rec.get("status") or {}).get(
                        "targetReplicas") or m.get("replicas") or 1),
                    "min": m.get("minReplicas") or 1,
                    "max": m["maxReplicas"],
                }
            if s["cell"] in ages:
                # Seconds since the last GOOD scrape (0 when this very
                # pass succeeded); `kuke top` dims rows past 2 intervals.
                row["scrapeAgeS"] = round(ages[s["cell"]], 3)
            if s["ok"]:
                fams = s["families"]
                # A replicated cell's base endpoint is its gateway; its
                # replicas ride along as key/rI rows with the normal
                # engine summary.
                if "kukeon_gateway_info" in fams:
                    row.update(summarize_gateway_scrape(fams))
                else:
                    row.update(summarize_cell_scrape(fams))
            else:
                row["error"] = s["error"]
            rows.append(row)
        return {"cells": rows}

    def Traces(self, traceId: str | None = None, n: int = 50,
               timeoutS: float | None = None) -> dict:
        """Federated trace reconstruction, mirroring the Metrics RPC's
        federation: union every running model cell's ``/v1/trace`` ring
        (gateway base endpoint + each replica) — filtered to one trace id
        when given — each span tagged with its cell key. `kuke trace
        <trace-id>` renders the result as a cross-component timeline."""
        spans = fetch_traces(model_cell_endpoints(self.ctl),
                             trace_id=traceId, n=n, timeout_s=timeoutS)
        return {"spans": spans}

    def Timeline(self, cell: str | None = None, n: int = 50,
                 timeoutS: float | None = None) -> dict:
        """Federated engine-step flight recorder, mirroring the Traces
        RPC: union every running model cell's ``/v1/timeline`` ring —
        narrowed to cells whose key contains ``cell`` when given — each
        step tagged with its cell key. `kuke timeline <cell>` renders the
        last N engine-loop steps (occupancy, chunk size, tokens,
        per-program wall time, preemptions, seated trace ids)."""
        endpoints = model_cell_endpoints(self.ctl)
        if cell:
            endpoints = [e for e in endpoints if cell in e[0]]
            if not endpoints:
                raise NotFound(f"no running model cell matches {cell!r}")
        steps = fetch_timelines(endpoints, n=n, timeout_s=timeoutS)
        return {"steps": steps}

    def Query(self, expr: str, windowS: float = 300.0, agg: str = "avg",
              stepS: float | None = None) -> dict:
        """Windowed query over the in-daemon TSDB: one aggregated value
        per matching series (``kuke query``), plus per-step value lists
        when ``stepS`` is given (the `kuke top --watch` sparkline shape).
        The store only holds what the telemetry loop has scraped — an
        empty result on a fresh daemon means "no history yet", not "no
        such metric"."""
        t = self.telemetry
        try:
            series = t.tsdb.query(expr, windowS, agg)
            out = {
                "expr": expr, "agg": agg,
                "windowS": float(tsdb_parse_window(windowS)),
                "retentionS": t.tsdb.retention_s,
                "series": [{"labels": labels, "value": value}
                           for labels, value in series],
            }
            if stepS is not None:
                out["stepS"] = float(tsdb_parse_window(stepS))
                out["range"] = [
                    {"labels": labels, "values": values}
                    for labels, values in t.tsdb.query_range(
                        expr, windowS, stepS, agg)
                ]
        except ValueError as e:
            raise InvalidArgument(str(e)) from None
        return out

    def Alerts(self, transitions: int = 50) -> dict:
        """The alert engine's current state machines (one row per rule,
        plus one per active labelset) and the recent transition ring —
        what `kuke alerts` renders."""
        t = self.telemetry
        out = {"alerts": t.alerts.states(),
               "transitions": t.alerts.transitions(transitions)}
        if t.user_rules_error:
            out["rulesError"] = t.user_rules_error
        return out

    def TelemetryTick(self) -> dict:
        """Force one synchronous telemetry pass (scrape -> ingest ->
        alert evaluation) outside the timer — the e2e tests' and an
        operator's "scrape now" button."""
        return {"transitions": self.telemetry.tick()}

    def RolloutCell(self, realm: str, space: str, stack: str, name: str,
                    drainTimeoutS: float = 60.0,
                    readyTimeoutS: float = 300.0,
                    standby: bool = True) -> dict:
        """Rolling restart of a replicated model cell with zero failed
        requests: one replica at a time, drain -> wait drained (a drained
        serving cell exits its HTTP server, so unreachable = drained) ->
        restart on the same chip grant -> wait /readyz 200. The gateway
        keeps the cell serving throughout — draining replicas leave its
        rotation and stragglers retry onto siblings.

        With ``standby`` (the default), a parked replica of an autoscaled
        cell is pre-warmed to /readyz BEFORE the first victim drains and
        parked again afterwards, so the ready census holds at N through
        every restart window. Cells with no parked capacity (no
        maxReplicas, or already at the bound) roll without one — the
        flag is a request, not a requirement."""
        from kukeon_tpu.gateway import rollout as ro

        rec = self.ctl.store.read_cell(realm or consts.DEFAULT_REALM,
                                       space or consts.DEFAULT_SPACE,
                                       stack or consts.DEFAULT_STACK, name)
        m = rec.spec.model
        if m is None:
            raise FailedPrecondition(f"cell {name!r} is not a model cell")
        # An autoscaled cell rolls its ACTIVE replicas only — restarting a
        # parked replica would start capacity the scaler turned off.
        active = self.ctl.runner.model_target(rec)
        if active <= 1:
            raise FailedPrecondition(
                f"cell {name!r} has replicas=1; a rolling restart needs a "
                "replicated model cell (set model.replicas >= 2)"
            )
        host = rec.status.ip or "127.0.0.1"
        steps = []
        for i in range(active):
            cname = f"model-server-{i}"
            url = f"http://{host}:{m.port + 1 + i}"

            def restart(cname=cname):
                _rollout_restart(self.ctl, rec, cname)

            steps.append(ro.RolloutStep(name=cname, url=url, restart=restart))
        from kukeon_tpu.runtime.apply.validate import model_scale_bound

        standby_step = None
        if standby and model_scale_bound(m) > active:
            sname = f"model-server-{active}"   # first parked index
            standby_step = ro.StandbyStep(
                name=sname,
                url=f"http://{host}:{m.port + 1 + active}",
                start=lambda: self.ctl.runner.start_parked_replica(
                    rec.realm, rec.space, rec.stack, rec.name),
                stop=lambda: self.ctl.runner.stop_parked_replica(
                    rec.realm, rec.space, rec.stack, rec.name, sname),
            )
        cell_key = "/".join((rec.realm, rec.space, rec.stack, rec.name))
        try:
            results = ro.rolling_restart(
                steps, drain_timeout_s=drainTimeoutS,
                ready_timeout_s=readyTimeoutS, standby=standby_step)
        except ro.RolloutError as e:
            # An aborted rollout is a RESULT, not an RPC failure: the
            # per-step outcome summary (which replicas finished, which one
            # stalled and why) is exactly what the operator needs to
            # resume by hand, so it must reach the CLI instead of dying
            # inside an error string.
            import logging
            logging.getLogger("kukeon.rollout").warning(
                "rollout of %s aborted: %s; per-step outcomes: %s",
                cell_key, e, e.results)
            return {"cell": cell_key, "aborted": True, "error": str(e),
                    "replicas": e.results}
        return {"cell": cell_key, "replicas": results}

    def ScaleStatus(self, events: int = 20) -> dict:
        """The FleetScaler's view: one row per autoscaled cell (bounds,
        active target, latest queue-ratio/burn signals, each decision
        rule's debounce state) plus the recent scale-event ring — what
        `kuke scale` renders. Rows reflect the last telemetry tick; a
        fresh daemon that has not ticked yet returns no cells."""
        scaler = self.telemetry.scaler
        return {"cells": scaler.states(), "events": scaler.events(events)}

    def Status(self) -> dict:
        ms = self.ctl.store.ms
        st = os.statvfs(ms.root)
        realms = self.ctl.list_realms()
        n_cells = sum(
            len(self.ctl.list_cells(r)) for r in realms
        )
        dm = self.ctl.runner.devices
        return {
            "pid": os.getpid(),
            "runPath": ms.root,
            "realms": realms,
            "cells": n_cells,
            "diskUsedPct": round(100.0 * (1 - st.f_bavail / max(st.f_blocks, 1)), 1),
            "tpuChips": {"total": len(dm.chips), "free": len(dm.free_chips()),
                         "allocations": {str(k): v for k, v in dm.allocated().items()}},
        }


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        service: RPCService = self.server.rpc_service  # type: ignore[attr-defined]
        for line in self.rfile:
            line = line.strip()
            if not line:
                continue
            req: dict | None = None
            method = ""
            try:
                req = json.loads(line)
                rid = req.get("id")
                method = req.get("method", "")
                params = req.get("params") or {}
                if method.startswith("_") or not hasattr(service, method):
                    raise InvalidArgument(f"unknown method {method!r}")
                result = getattr(service, method)(**params)
                resp = {"id": rid, "result": result}
                service._m_rpc.inc(method=method, result="ok")
            except KukeonError as e:
                resp = {"id": req.get("id") if isinstance(req, dict) else None,
                        "error": {"code": e.code, "message": str(e)}}
                # Unknown method names must not mint label values (a bad
                # client could otherwise grow the family without bound).
                known = bool(method) and hasattr(service, method)
                service._m_rpc.inc(method=method if known else "?",
                                   result=e.code)
            except Exception as e:  # noqa: BLE001 — daemon must not die on a bad request
                traceback.print_exc()
                resp = {"id": req.get("id") if isinstance(req, dict) else None,
                        "error": {"code": "internal", "message": f"{type(e).__name__}: {e}"}}
                known = bool(method) and hasattr(service, method)
                service._m_rpc.inc(method=method if known else "?",
                                   result="internal")
            try:
                self.wfile.write((json.dumps(resp) + "\n").encode())
                self.wfile.flush()
            except BrokenPipeError:
                return


class _ThreadingUnixServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True


class DaemonServer:
    def __init__(self, run_path: str, socket_path: str | None = None,
                 reconcile_interval_s: float | None = None):
        from kukeon_tpu.runtime import config

        self.run_path = run_path
        self.settings = config.server_settings(run_path)
        self.socket_path = (
            socket_path
            or self.settings.get("KUKEOND_SOCKET")
            or consts.socket_path(run_path)
        )
        self.reconcile_interval_s = (
            reconcile_interval_s
            if reconcile_interval_s is not None
            else self.settings.get("KUKEOND_RECONCILE_INTERVAL")
        )
        self.ctl = build_controller(run_path, self.settings)
        self._shutdown = sanitize.event("DaemonServer._shutdown")
        self._server: _ThreadingUnixServer | None = None

    def serve(self) -> None:
        from kukeon_tpu.runtime import config, logging_setup

        logging_setup.setup(self.settings.get("KUKEOND_LOG_LEVEL"))
        os.makedirs(self.run_path, exist_ok=True)
        # First daemon start persists the resolved configuration as a
        # commented document the operator can edit (reference:
        # serverconfig.go WriteDefault, O_EXCL first-write-only).
        config.write_default_server_configuration(
            config.server_config_path(self.run_path),
            {
                "runPath": self.run_path,
                "socket": self.socket_path,
                "reconcileInterval": self.reconcile_interval_s,
            },
        )
        # Instance pinning: refuse a run path bootstrapped under different
        # settings (reference: internal/instance/instance.go:21-28).
        from kukeon_tpu.runtime import instance

        runner = self.ctl.runner
        instance.pin_or_verify(self.run_path, {
            "subnetPool": str(runner.netman.subnets.parent)
            if runner.netman is not None else "",
            "cgroupBase": runner.cgroups.base if runner.cgroups else "",
            "backend": type(runner.backend).__name__,
        })
        self.ctl.bootstrap()
        # Stale socket from a previous daemon: unlink after a probe.
        if os.path.exists(self.socket_path):
            if self._socket_alive():
                raise KukeonError(f"daemon already listening on {self.socket_path}")
            os.unlink(self.socket_path)

        pid_file = os.path.join(self.run_path, "kukeond.pid")
        with open(pid_file, "w") as f:
            f.write(str(os.getpid()))

        self._server = _ThreadingUnixServer(self.socket_path, _Handler)
        self._server.rpc_service = RPCService(self.ctl, self)  # type: ignore[attr-defined]
        os.chmod(self.socket_path, 0o660)
        # Socket group access for non-root clients (reference: SocketGID,
        # server.go:42-116 — chown root:kukeon so group members can dial).
        gid = self.settings.get("KUKEOND_SOCKET_GID")
        if not gid:
            # Default to the provisioned `kukeon` group (sysuser) when
            # present, like the reference's root:kukeon socket.
            from kukeon_tpu.runtime import sysuser

            gid = sysuser.group_gid()
        if gid:
            try:
                os.chown(self.socket_path, -1, int(gid))
            except (OSError, PermissionError):
                pass  # non-root daemon: group access simply stays off

        # Boot heal: reboots flush iptables/bridges; re-assert every space
        # network, then the FORWARD admission chain (reference:
        # server.go:151-196, 307). Order matters for the kukenet driver:
        # the full-space pass must prime its whole-table state before any
        # commit, or a restart would wipe live deny chains.
        self.ctl.reconcile_space_networks()
        if self.ctl.runner.netman is not None:
            self.ctl.runner.netman.install_forward()
        # Eager reconcile pass: a host restart converges immediately
        # (reference: server.go:226-244).
        self.ctl.reconcile_cells()
        ticker = threading.Thread(target=self._reconcile_loop, daemon=True,
                                  name="reconcile")
        ticker.start()
        telemetry = threading.Thread(
            target=self._telemetry_loop,
            args=(self._server.rpc_service.telemetry,),  # type: ignore[attr-defined]
            daemon=True, name="telemetry")
        telemetry.start()

        def _stop(signum, frame):
            del signum, frame
            self.shutdown()

        signal.signal(signal.SIGTERM, _stop)
        signal.signal(signal.SIGINT, _stop)
        try:
            self._server.serve_forever(poll_interval=0.2)
        finally:
            self._shutdown.set()
            with open(pid_file, "w") as f:
                f.write("")
            if os.path.exists(self.socket_path):
                os.unlink(self.socket_path)

    def shutdown(self) -> None:
        self._shutdown.set()
        if self._server:
            threading.Thread(target=self._server.shutdown, daemon=True).start()

    def _reconcile_loop(self) -> None:
        reg = self.ctl.runner.registry
        m_ticks = reg.counter("kukeon_daemon_reconcile_ticks_total",
                              "Reconcile passes run by the ticker.")
        m_outcomes = reg.counter(
            "kukeon_daemon_reconcile_outcomes_total",
            "Per-cell reconcile outcomes accumulated over all ticks.",
            labels=("outcome",))
        m_dur = reg.histogram("kukeon_daemon_reconcile_seconds",
                              "Wall time of one full reconcile pass.")
        while not self._shutdown.wait(self.reconcile_interval_s):
            try:
                t0 = time.monotonic()
                counts = self.ctl.reconcile_cells()
                self.ctl.reconcile_space_networks()
                m_dur.observe(time.monotonic() - t0)
                m_ticks.inc()
                for outcome, n in counts.items():
                    m_outcomes.inc(n, outcome=outcome)
            except Exception:  # noqa: BLE001 — ticker must survive
                traceback.print_exc()

    def _telemetry_loop(self, telemetry: FleetTelemetry) -> None:
        """The scrape ticker: every KUKEON_SCRAPE_INTERVAL_S, pull the
        fleet's /metrics into the TSDB and evaluate the alert rules. The
        loop must survive anything a cell throws at it."""
        interval = float(os.environ.get(SCRAPE_INTERVAL_ENV, "")
                         or DEFAULT_SCRAPE_INTERVAL_S)
        while not self._shutdown.wait(interval):
            try:
                telemetry.tick()
            except Exception:  # noqa: BLE001 — ticker must survive
                traceback.print_exc()

    def _socket_alive(self) -> bool:
        try:
            s = socket.socket(socket.AF_UNIX)
            s.settimeout(1.0)
            s.connect(self.socket_path)
            s.close()
            return True
        except OSError:
            return False
