"""kukeon_tpu.runtime: the orchestration control plane (under construction).

Capability-parity layer with the reference's Go daemon (kukeond): manifests,
daemon, controller, reconciler, cells, secrets, volumes, teams. Built out
incrementally; see the repo README for current status.
"""
