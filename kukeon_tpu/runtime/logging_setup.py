"""Logging subsystem: one timestamped, quoted-message text format.

Reference: internal/logging/handler.go:28-40 — the slog ReformatHandler
every kukeon binary installs (`time level "message" key=value ...`), plus a
noop logger for tests. Here: a logging.Formatter with the same line shape,
a single ``setup()`` every entrypoint calls (daemon, CLI verbs, serving
cell), and level resolution from KUKEOND_LOG_LEVEL / ServerConfiguration.
"""

from __future__ import annotations

import logging
import sys
import time

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}


class ReformatFormatter(logging.Formatter):
    """`2026-01-02T15:04:05.000Z INFO "message" logger=kukeon.runner`
    — greppable, stable-width, message always quoted (the reference's
    text-handler shape)."""

    converter = time.gmtime

    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime("%Y-%m-%dT%H:%M:%S", self.converter(record.created))
        ms = int(record.msecs)
        msg = record.getMessage().replace('"', r"\"")
        line = f'{ts}.{ms:03d}Z {record.levelname} "{msg}" logger={record.name}'
        if record.exc_info:
            line += "\n" + self.formatException(record.exc_info)
        return line


def setup(level: str | int | None = None, stream=None) -> None:
    """Install the kukeon handler on the root `kukeon` logger (idempotent).

    ``level``: name or numeric; defaults to INFO. Child loggers
    (kukeon.runner, kukeon.net, ...) inherit.
    """
    if isinstance(level, str):
        level = _LEVELS.get(level.lower(), logging.INFO)
    root = logging.getLogger("kukeon")
    root.setLevel(level if level is not None else logging.INFO)
    stream = stream or sys.stderr
    for h in root.handlers:
        if getattr(h, "_kukeon", False):
            h.setStream(stream) if hasattr(h, "setStream") else None
            return
    handler = logging.StreamHandler(stream)
    handler.setFormatter(ReformatFormatter())
    handler._kukeon = True  # type: ignore[attr-defined]
    root.addHandler(handler)
    root.propagate = False


class NoopHandler(logging.Handler):
    """Swallow everything (the reference's noop logger for tests)."""

    def emit(self, record: logging.LogRecord) -> None:  # noqa: D102
        pass


def noop() -> None:
    root = logging.getLogger("kukeon")
    root.handlers = [NoopHandler()]
    root.propagate = False
