"""Logging subsystem: one timestamped, quoted-message text format, plus an
opt-in structured JSON mode.

Reference: internal/logging/handler.go:28-40 — the slog ReformatHandler
every kukeon binary installs (`time level "message" key=value ...`), plus a
noop logger for tests. Here: a logging.Formatter with the same line shape,
a single ``setup()`` every entrypoint calls (daemon, CLI verbs, serving
cell), and level resolution from KUKEOND_LOG_LEVEL / ServerConfiguration.

``KUKEON_LOG_FORMAT=json`` (or ``setup(fmt="json")``) switches every line
to one JSON object: ``{"ts", "level", "msg", "logger"}`` plus whatever
correlation fields the call site attached via ``extra=`` — the serving
engine stamps ``request_id``, ``trace_id``, and ``phase`` on
request-lifecycle records, and the ambient cell name (KUKEON_CELL,
injected by the runner) rides along as ``cell`` — so a log pipeline joins
log lines to /v1/trace spans (and to the cross-component trace ``kuke
trace`` reconstructs) on one key. Plain text remains the default.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

# Correlation fields lifted from record ``extra=`` into the JSON object.
# ``trace_id`` is the distributed-trace join key: a JSON log line and the
# /v1/trace span it belongs to share it, so logs and traces join on one
# key across gateway, replicas, and engines.
_EXTRA_FIELDS = ("request_id", "trace_id", "cell", "phase", "point",
                 "outcome", "alert", "severity")

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}


class ReformatFormatter(logging.Formatter):
    """`2026-01-02T15:04:05.000Z INFO "message" logger=kukeon.runner`
    — greppable, stable-width, message always quoted (the reference's
    text-handler shape)."""

    converter = time.gmtime

    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime("%Y-%m-%dT%H:%M:%S", self.converter(record.created))
        ms = int(record.msecs)
        msg = record.getMessage().replace('"', r"\"")
        line = f'{ts}.{ms:03d}Z {record.levelname} "{msg}" logger={record.name}'
        if record.exc_info:
            line += "\n" + self.formatException(record.exc_info)
        return line


class JsonFormatter(logging.Formatter):
    """One JSON object per line with correlation fields.

    ``cell`` defaults from KUKEON_CELL (the runner injects it into every
    container env) so multi-cell log aggregation needs no per-call-site
    plumbing; an explicit ``extra={"cell": ...}`` wins."""

    converter = time.gmtime

    def __init__(self):
        super().__init__()
        self._cell = os.environ.get("KUKEON_CELL")

    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime("%Y-%m-%dT%H:%M:%S", self.converter(record.created))
        obj = {
            "ts": f"{ts}.{int(record.msecs):03d}Z",
            "level": record.levelname,
            "msg": record.getMessage(),
            "logger": record.name,
        }
        if self._cell is not None:
            obj["cell"] = self._cell
        for key in _EXTRA_FIELDS:
            v = record.__dict__.get(key)
            if v is not None:
                obj[key] = v
        if record.exc_info:
            obj["exc"] = self.formatException(record.exc_info)
        return json.dumps(obj)


def _resolve_formatter(fmt: str | None) -> logging.Formatter:
    fmt = (fmt or os.environ.get("KUKEON_LOG_FORMAT") or "text").lower()
    return JsonFormatter() if fmt == "json" else ReformatFormatter()


def setup(level: str | int | None = None, stream=None,
          fmt: str | None = None) -> None:
    """Install the kukeon handler on the root `kukeon` logger (idempotent).

    ``level``: name or numeric; defaults to INFO. Child loggers
    (kukeon.runner, kukeon.net, ...) inherit. ``fmt``: "text" (default) or
    "json"; unset falls back to KUKEON_LOG_FORMAT.
    """
    if isinstance(level, str):
        level = _LEVELS.get(level.lower(), logging.INFO)
    root = logging.getLogger("kukeon")
    root.setLevel(level if level is not None else logging.INFO)
    stream = stream or sys.stderr
    for h in root.handlers:
        if getattr(h, "_kukeon", False):
            h.setStream(stream) if hasattr(h, "setStream") else None
            # Re-setup may switch formats (a test flips KUKEON_LOG_FORMAT;
            # the daemon re-reads its configuration).
            h.setFormatter(_resolve_formatter(fmt))
            return
    handler = logging.StreamHandler(stream)
    handler.setFormatter(_resolve_formatter(fmt))
    handler._kukeon = True  # type: ignore[attr-defined]
    root.addHandler(handler)
    root.propagate = False


class NoopHandler(logging.Handler):
    """Swallow everything (the reference's noop logger for tests)."""

    def emit(self, record: logging.LogRecord) -> None:  # noqa: D102
        pass


def noop() -> None:
    root = logging.getLogger("kukeon")
    root.handlers = [NoopHandler()]
    root.propagate = False
