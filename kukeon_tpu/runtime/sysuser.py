"""System group provisioning: the `kukeon` group gates non-root access.

Reference: internal/sysuser/sysuser.go (239 LoC) — `kuke init` provisions a
system `kukeon` group and chowns the tree so group members can dial the
daemon socket (mode 0660 root:kukeon) without being root.
"""

from __future__ import annotations

import grp
import logging
import os
import subprocess

log = logging.getLogger("kukeon.sysuser")

GROUP = "kukeon"


def group_gid(name: str = GROUP) -> int | None:
    try:
        return grp.getgrnam(name).gr_gid
    except KeyError:
        return None


def ensure_group(name: str = GROUP) -> int | None:
    """Provision the system group (root only); returns its gid, or None when
    it cannot exist (non-root, no groupadd)."""
    gid = group_gid(name)
    if gid is not None:
        return gid
    if os.geteuid() != 0:
        return None
    for argv in (["groupadd", "--system", name], ["addgroup", "--system", name]):
        try:
            p = subprocess.run(argv, capture_output=True, text=True, timeout=10)
        except OSError:
            continue
        if p.returncode == 0:
            return group_gid(name)
    log.warning("could not provision group %r (no groupadd/addgroup)", name)
    return None


def chown_tree(run_path: str, gid: int) -> None:
    """root:kukeon + group-traversable dirs so group members can reach the
    socket and read statuses; secrets stay 0400 root-only (the per-file
    modes set at staging win over the tree default)."""
    for dirpath, _dirnames, filenames in os.walk(run_path):
        try:
            os.chown(dirpath, -1, gid)
            os.chmod(dirpath, os.stat(dirpath).st_mode | 0o050)
        except OSError:
            continue
        for fn in filenames:
            p = os.path.join(dirpath, fn)
            try:
                if os.stat(p).st_mode & 0o077 == 0:
                    continue   # explicitly locked-down file (secrets)
                os.chown(p, -1, gid)
            except OSError:
                continue
