"""The imperative resource engine beneath the controller.

Reference: internal/controller/runner (provision.go, start.go, refresh.go,
cell_lock.go — 33.7k LoC of Go). Responsibilities here:

- provision realm/space/stack/cell trees (metadata dirs + cgroups),
- cell lifecycle: create/start/stop/kill/delete with per-cell locking and a
  10s SIGTERM->SIGKILL stop window (reference: ctr/container.go:173),
- TPU chip affinity: allocate chips at start, inject visibility env,
  release at stop (the libtpu device-manager seam, BASELINE north star),
- secret staging (files 0400 + env injection; reference ctr/secrets.go),
- model cells: materialize the in-tree serving container,
- refresh: re-derive status from the backend, enforce restart policy
  (always/on-failure/never + backoff + max retries; refresh.go:1110-1458)
  and AutoDelete reaping.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
import time

from kukeon_tpu import obs, sanitize
from kukeon_tpu.runtime import consts, model
from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.cells.backend import CellBackend, ContainerContext
from kukeon_tpu.runtime.cgroups import CgroupManager
from kukeon_tpu.runtime.devices import TPUDeviceManager
from kukeon_tpu.runtime.errors import (
    DiskPressure,
    FailedPrecondition,
    InvalidArgument,
    NotFound,
)
from kukeon_tpu.runtime.store import ResourceStore

# Reconcile outcomes (reference: runner/runner.go:33-56).
OUTCOME_STEADY = "steady"
OUTCOME_HEALED = "healed"
OUTCOME_RESTARTED = "restarted"
OUTCOME_AUTO_DELETED = "auto-deleted"
OUTCOME_VANISHED = "vanished"


@dataclasses.dataclass
class RunnerOptions:
    stop_grace_s: float = consts.DEFAULT_STOP_GRACE_S
    disk_pressure_block_pct: float = consts.DISK_PRESSURE_BLOCK_PCT
    serving_python: str = sys.executable


@sanitize.guard_class
class Runner:
    def __init__(
        self,
        store: ResourceStore,
        backend: CellBackend,
        cgroups: CgroupManager | None = None,
        devices: TPUDeviceManager | None = None,
        options: RunnerOptions | None = None,
        netman=None,
        registry: "obs.Registry | None" = None,
    ):
        self.store = store
        self.backend = backend
        self.cgroups = cgroups
        self.devices = devices or TPUDeviceManager(store.ms, chips=[])
        self.opts = options or RunnerOptions()
        self.netman = netman
        self._cell_locks: dict[tuple, threading.Lock] = {}
        self._locks_guard = sanitize.lock("Runner._locks_guard")
        # (owner, container, repo idx) -> last failed clone attempt time.
        self._repo_failures: dict[tuple, float] = {}
        # Cell-lifecycle metrics (daemon Metrics RPC / `kuke daemon
        # metrics` scrape them). Default registry is process-global: one
        # daemon process, one scrape; tests inject a fresh Registry.
        self.registry = registry or obs.get_default()
        reg = self.registry
        self._m_cell_starts = reg.counter(
            "kukeon_runner_cell_starts_total",
            "Cell start operations (initial starts; restarts count "
            "separately).", labels=("cell",))
        self._m_restarts = reg.counter(
            "kukeon_runner_container_restarts_total",
            "Restart-policy container restarts.",
            labels=("cell", "container"))
        self._m_exits = reg.counter(
            "kukeon_runner_container_exits_total",
            "Observed container exits by exit code.",
            labels=("cell", "container", "code"))
        self._m_uptime = reg.gauge(
            "kukeon_runner_container_uptime_seconds",
            "Continuous uptime of a running container (refreshed every "
            "reconcile tick; 0 when not running).",
            labels=("cell", "container"))
        self._m_backoff = reg.gauge(
            "kukeon_runner_restart_backoff_seconds",
            "Remaining restart backoff for an exited container "
            "(0 = no restart pending).", labels=("cell", "container"))
        self._m_exhausted = reg.gauge(
            "kukeon_runner_restart_budget_exhausted",
            "1 when a container crash-looped past restartMaxRetries.",
            labels=("cell", "container"))
        reg.register_collector(obs.faults_collector)

    # --- locking (reference: runner/cell_lock.go) --------------------------

    def cell_lock(self, realm: str, space: str, stack: str, cell: str) -> threading.Lock:
        # Every cell's lock shares ONE sanitizer identity
        # ("Runner._cell_locks"): the lock-order graph aggregates the
        # family into a single node (same-name edges are skipped, so
        # nesting two different cells' locks is invisible to kukesan —
        # an accepted blind spot; nothing in the runner nests them).
        key = (realm, space, stack, cell)
        with self._locks_guard:
            lk = self._cell_locks.get(key)
            if lk is None:
                lk = sanitize.lock("Runner._cell_locks")
                self._cell_locks[key] = lk
            return lk

    # --- provisioning ------------------------------------------------------

    def ensure_realm(self, name: str, spec: t.RealmSpec | None = None,
                     labels: dict | None = None) -> None:
        self.store.ms.ensure_dir(*self.store.realm_parts(name))
        if not self.store.ms.exists(*self.store.realm_parts(name), "realm.json"):
            rec = model.ScopeRecord(kind="Realm", name=name, labels=labels or {},
                                    spec_json=model.spec_to_json(spec or t.RealmSpec()))
            self.store.write_scope(rec)
        if self.cgroups:
            self.cgroups.ensure(name)

    def ensure_space(self, realm: str, name: str, spec: t.SpaceSpec | None = None,
                     labels: dict | None = None) -> None:
        self.store.read_realm(realm)
        self.store.ms.ensure_dir(*self.store.space_parts(realm, name))
        existing = self.store.ms.read_json_or(None, *self.store.space_parts(realm, name), "space.json")
        if existing is None or spec is not None:
            # Provision the network BEFORE persisting the spec: a rejected
            # subnet change must not leave a stored spec the reconcile loop
            # can never converge on.
            if self.netman is not None:
                self.netman.ensure_space_network(realm, name, spec or t.SpaceSpec())
            rec = model.ScopeRecord(kind="Space", name=name, realm=realm,
                                    labels=labels or {},
                                    spec_json=model.spec_to_json(spec or t.SpaceSpec()))
            self.store.write_scope(rec)
        if self.cgroups:
            self.cgroups.ensure(realm, name)

    def teardown_space_network(self, realm: str, name: str,
                               spec: t.SpaceSpec | None = None) -> None:
        if self.netman is not None:
            self.netman.teardown_space_network(realm, name, spec)

    def ensure_stack(self, realm: str, space: str, name: str,
                     spec: t.StackSpec | None = None, labels: dict | None = None) -> None:
        self.store.read_space(realm, space)
        self.store.ms.ensure_dir(*self.store.stack_parts(realm, space, name))
        if not self.store.ms.exists(*self.store.stack_parts(realm, space, name), "stack.json"):
            rec = model.ScopeRecord(kind="Stack", name=name, realm=realm, space=space,
                                    labels=labels or {},
                                    spec_json=model.spec_to_json(spec or t.StackSpec()))
            self.store.write_scope(rec)
        if self.cgroups:
            self.cgroups.ensure(realm, space, name)

    # --- disk pressure (reference: runner/create_cell.go:166) --------------

    def guard_disk_pressure(self, ignore: bool = False) -> None:
        if ignore:
            return
        try:
            st = os.statvfs(self.store.ms.root)
        except OSError:
            return
        used_pct = 100.0 * (1 - st.f_bavail / max(st.f_blocks, 1))
        if used_pct >= self.opts.disk_pressure_block_pct:
            raise DiskPressure(
                f"disk {used_pct:.1f}% full >= block threshold "
                f"{self.opts.disk_pressure_block_pct}%; refusing new cells"
            )

    # --- cell lifecycle ----------------------------------------------------

    # --- host-port registry -------------------------------------------------
    #
    # Host-network containers (and host-network model cells) bind REAL host
    # ports; two cells claiming the same port would fail at runtime with an
    # unhelpful EADDRINUSE inside the workload. The registry makes the claim
    # at create, where it can be rejected with a pointer to the holder
    # (VERDICT r3 item 7). Isolated cells need no claim: their ports live on
    # the cell IP in the sandbox netns.

    def _host_ports_of(self, rec: model.CellRecord) -> list[str]:
        ports: list[str] = []
        for c in self.cell_containers(rec):
            if not c.host_network:
                continue
            for p in c.ports:
                ports.append(f"{p.port}/{(p.protocol or 'tcp').lower()}")
        return ports

    def claim_host_ports(self, rec: model.CellRecord) -> None:
        ports = self._host_ports_of(rec)
        owner = self._owner_key(rec)
        with self.store.ms.lock():
            claims = self.store.ms.read_json_or({}, consts.HOST_PORTS_FILE)
            # Re-claim from scratch: an update that drops a port must also
            # drop its claim.
            claims = {k: o for k, o in claims.items() if o != owner}
            for key in ports:
                holder = claims.get(key)
                if holder is not None:
                    raise FailedPrecondition(
                        f"host port {key} already claimed by cell {holder}"
                    )
                claims[key] = owner
            self.store.ms.write_json(claims, consts.HOST_PORTS_FILE)

    def _release_host_ports(self, rec: model.CellRecord) -> None:
        owner = self._owner_key(rec)
        with self.store.ms.lock():
            claims = self.store.ms.read_json_or({}, consts.HOST_PORTS_FILE)
            remaining = {k: o for k, o in claims.items() if o != owner}
            if len(remaining) != len(claims):
                self.store.ms.write_json(remaining, consts.HOST_PORTS_FILE)

    def create_cell(self, rec: model.CellRecord) -> model.CellRecord:
        with self.cell_lock(rec.realm, rec.space, rec.stack, rec.name):
            self.store.read_stack(rec.realm, rec.space, rec.stack)
            self.guard_disk_pressure(rec.spec.ignore_disk_pressure)
            self.claim_host_ports(rec)
            try:
                self.store.ms.ensure_dir(
                    *self.store.cell_parts(rec.realm, rec.space, rec.stack, rec.name)
                )
                if self.cgroups:
                    self.cgroups.ensure(rec.realm, rec.space, rec.stack, rec.name)
                rec.status = model.CellStatus(
                    phase=model.PENDING,
                    containers=[
                        model.ContainerStatus(name=c.name)
                        for c in self.cell_containers(rec)
                    ],
                )
                self.store.write_cell(rec)
            except Exception:
                # A failed create must not strand its port claims: the cell
                # record does not exist, so no delete will ever release them.
                self._release_host_ports(rec)
                raise
            return rec

    def cell_containers(self, rec: model.CellRecord) -> list[t.ContainerSpec]:
        """Declared containers plus the materialized serving container(s)
        for model cells (N replicas + a gateway when ``replicas > 1``)."""
        containers = list(rec.spec.containers)
        if rec.spec.model is not None:
            containers.extend(self._model_containers(rec.spec.model))
        return containers

    def _model_containers(self, m: t.ModelSpec) -> list[t.ContainerSpec]:
        """The base-port scheme: a single engine keeps today's shape (one
        ``model-server`` on ``m.port``); ``replicas: N`` materializes
        ``model-server-0..N-1`` on ``port+1..port+N`` (each with its own
        ``chips`` grant — declaration order partitions the cell's chips
        deterministically, so a restarted replica gets ITS chips back) plus
        one chip-less ``gateway`` container on ``m.port`` so the
        client-facing endpoint never moves. An autoscaled cell
        (``maxReplicas``) materializes the FULL bound — replicas above the
        active target stay parked (never started) but keep their name,
        port, and chip slice, so the scaler's scale-up is just "start
        container i on its grant", never a re-partition."""
        from kukeon_tpu.runtime.apply.validate import (
            model_roles,
            model_scale_bound,
        )

        n = model_scale_bound(m)
        roles = model_roles(m)
        if n <= 1:
            return [self._model_container(m, role=roles[0])]
        out = [
            self._model_container(
                m, name=f"model-server-{i}", port=m.port + 1 + i,
                # Autoscaled cells are validated role="mixed"; a static
                # replica set keeps its per-replica role atoms.
                role=roles[i] if i < len(roles) else "mixed")
            for i in range(n)
        ]
        out.append(self._gateway_container(m))
        return out

    def _gateway_container(self, m: t.ModelSpec) -> t.ContainerSpec:
        cmd = [
            self.opts.serving_python, "-m", "kukeon_tpu.gateway.cell",
            "--model", m.model, "--port", str(m.port),
        ]
        if not m.host_network and self.backend.isolated:
            cmd += ["--host", "0.0.0.0"]
        # Replicas share the cell's netns (or the host loopback on the
        # process backend), so the gateway always reaches them on 127.0.0.1.
        # The gateway learns the FULL scale bound: a parked replica simply
        # polls unready until the scaler starts it, then joins rotation on
        # the next poll tick with no gateway restart.
        from kukeon_tpu.runtime.apply.validate import model_scale_bound

        for i in range(model_scale_bound(m)):
            cmd += ["--replica", f"http://127.0.0.1:{m.port + 1 + i}"]
        return t.ContainerSpec(
            name="gateway",
            command=cmd,
            restart_policy=t.RestartPolicy(policy="always",
                                           backoff_seconds=1.0),
            ports=[t.PortSpec(port=m.port, name="http")],
            host_network=m.host_network,
        )

    def _model_container(self, m: t.ModelSpec, *, name: str = "model-server",
                         port: int | None = None,
                         role: str = "mixed") -> t.ContainerSpec:
        port = m.port if port is None else port
        cmd = [
            self.opts.serving_python, "-m", "kukeon_tpu.runtime.serving_cell",
            "--model", m.model, "--port", str(port),
            "--num-slots", str(m.num_slots),
        ]
        if role != "mixed":
            # Disaggregation role (per replica, declaration order). The
            # gateway discovers pools from each cell's /v1/stats census, so
            # the gateway container itself needs no role flags.
            cmd += ["--role", role]
        if not m.host_network and self.backend.isolated:
            # In-space serving: bind all interfaces so in-space clients reach
            # the server on the cell's bridge IP (the sandbox netns has no
            # other route in); the space's default-deny egress still governs
            # every packet the cell originates (BASELINE config 4). Gated on
            # isolation: on the process backend 0.0.0.0 would be the REAL
            # host interfaces — strictly wider than the loopback default.
            cmd += ["--host", "0.0.0.0"]
        if m.max_seq_len:
            cmd += ["--max-seq-len", str(m.max_seq_len)]
        if m.checkpoint:
            cmd += ["--checkpoint", m.checkpoint]
        if m.dtype:
            cmd += ["--dtype", m.dtype]
        if m.kv_cache_int8:
            cmd += ["--kv-cache-int8"]
        if m.kv_page_tokens is not None:
            # 0 is meaningful (pin the legacy contiguous layout even when a
            # tuning profile prefers pages) — pass it through.
            cmd += ["--kv-page-tokens", str(m.kv_page_tokens)]
        if m.max_pending is not None:
            # 0 is meaningful (explicit unbounded opt-out) — pass it through.
            cmd += ["--max-pending", str(m.max_pending)]
        if m.deadline_s:
            cmd += ["--deadline-s", str(m.deadline_s)]
        if m.slo_ttft_p95_ms:
            cmd += ["--slo-ttft-p95-ms", str(m.slo_ttft_p95_ms)]
        if m.slo_availability:
            cmd += ["--slo-availability", str(m.slo_availability)]
        # The chip grant is always explicit: the cell builds an exactly-N
        # serving mesh (parallel/mesh.serving_mesh) instead of auto-meshing
        # over whatever it can see. On TPU hosts TPU_VISIBLE_DEVICES already
        # narrows visibility to the grant; on CPU hosts (forced multi-device
        # smokes) this flag is the only thing that makes the grant real.
        cmd += ["--chips", str(m.chips)]
        return t.ContainerSpec(
            name=name,
            command=cmd,
            resources=t.Resources(tpu_chips=m.chips),
            restart_policy=t.RestartPolicy(policy="always", backoff_seconds=2.0),
            ports=[t.PortSpec(port=port, name="http")],
            # Spec-visible decision (ModelSpec.host_network): default is the
            # space network + egress policy; true exempts the cell for hosts
            # whose TPU runtime plane requires the host net.
            host_network=m.host_network,
        )

    def _owner_key(self, rec: model.CellRecord) -> str:
        return f"{rec.realm}/{rec.space}/{rec.stack}/{rec.name}"

    @staticmethod
    def model_target(rec: model.CellRecord) -> int:
        """The ACTIVE replica count of a model cell: the scaler-written
        ``status.target_replicas`` when set, else the spec's static
        ``replicas`` — always clamped into [minReplicas, scale bound] so a
        stale record can never park the whole fleet or start past the
        bound."""
        from kukeon_tpu.runtime.apply.validate import model_scale_bound

        m = rec.spec.model
        if m is None:
            return 0
        bound = model_scale_bound(m)
        target = rec.status.target_replicas
        if target is None:
            target = m.replicas or 1
        return max(max(1, m.min_replicas or 1), min(target, bound))

    def _parked_names(self, rec: model.CellRecord) -> set[str]:
        """Container names of replicas scaled out of the active range:
        materialized (name/port/chip slice reserved) but intentionally not
        running — start, heal, and phase derivation all skip them."""
        from kukeon_tpu.runtime.apply.validate import model_scale_bound

        m = rec.spec.model
        if m is None:
            return set()
        bound = model_scale_bound(m)
        if bound <= 1:
            return set()
        target = self.model_target(rec)
        return {f"model-server-{i}" for i in range(target, bound)}

    def start_cell(self, realm: str, space: str, stack: str, name: str) -> model.CellRecord:
        with self.cell_lock(realm, space, stack, name):
            rec = self.store.read_cell(realm, space, stack, name)
            return self._start_cell_locked(rec)

    def _start_cell_locked(self, rec: model.CellRecord) -> model.CellRecord:
        containers = self.cell_containers(rec)
        # Multi-chip composition check (validate_cell is static and cannot
        # see the host): a grant that does not divide the host's chip count
        # can never partition into whole N-chip replica slices — fail loudly
        # here instead of letting a later replica starve mid-scale-up.
        m = rec.spec.model
        host_chips = len(self.devices.chips)
        if m is not None and m.chips > 1 and host_chips % m.chips:
            raise FailedPrecondition(
                f"model chip grant chips={m.chips} does not divide this "
                f"host's {host_chips} chips; replicas cannot partition into "
                "whole slices"
            )
        total_chips = sum(
            c.resources.tpu_chips or 0 for c in containers
        )
        chips: list[int] = []
        if total_chips:
            chips = self.devices.allocate(self._owner_key(rec), total_chips)
        rec.status.tpu_chips = chips
        self._ensure_cell_network(rec)

        slices = self._chip_slices(containers, chips)
        parked = self._parked_names(rec)
        new_statuses = []
        for spec in containers:
            ctx = self._container_context(rec, spec)
            grant = slices.get(spec.name, [])
            if grant:
                ctx.env.update(self.devices.visibility_env(grant))
                ctx.devices = self.devices.device_nodes(grant)
            st = rec.status.container(spec.name) or model.ContainerStatus(name=spec.name)
            live = self.backend.container_state(ctx)
            if not live.running and spec.name not in parked:
                self.backend.start_container(ctx)
                live = self.backend.container_state(ctx)
                st.started_at = time.time()
            st.state = live.state
            st.pid = live.pid
            st.exit_code = live.exit_code
            new_statuses.append(st)

        rec.status.containers = new_statuses
        rec.desired_state = "running"
        self._derive_phase(rec)
        self.store.write_cell(rec)
        self._m_cell_starts.inc(cell=self._owner_key(rec))
        return rec

    @staticmethod
    def _chip_slices(containers: list[t.ContainerSpec], chips: list[int]) -> dict[str, list[int]]:
        """Deterministic per-container chip assignment: declaration order
        partitions the cell's grant. Start and restart paths share this so a
        restarted container gets back ITS chips, not a sibling's."""
        out: dict[str, list[int]] = {}
        cursor = 0
        for spec in containers:
            n = spec.resources.tpu_chips or 0
            if n:
                out[spec.name] = chips[cursor : cursor + n]
                cursor += n
        return out

    def _cell_dir(self, rec: model.CellRecord) -> str:
        return self.store.ms.ensure_dir(
            *self.store.cell_parts(rec.realm, rec.space, rec.stack, rec.name)
        )

    def _ensure_cell_network(self, rec: model.CellRecord) -> None:
        """Attach the cell's sandbox netns to its space bridge (idempotent;
        reference: CNI ADD on cell start, runner/start.go:474-560)."""
        if not self.backend.isolated:
            return
        containers = self.cell_containers(rec)
        if containers and all(c.host_network for c in containers):
            # Nothing will use the sandbox netns; don't burn a bridge IP or
            # publish an address nothing listens on.
            return
        if self.netman is None or not self.netman.enforcing:
            # The sandbox netns exists but no bridge will ever reach it: a
            # Ready cell with a server bound in a disconnected netns is a
            # dead end that MUST be named in status (a silent no-IP cell is
            # undebuggable; use hostNetwork or enable net enforcement).
            rec.status.reason = (
                "cell is network-isolated but net enforcement is off: no "
                "bridge/IP will be attached (set hostNetwork: true or run "
                "with root + iptables/kukenet)"
            )
            return
        try:
            pid = self.backend.ensure_sandbox(self._cell_dir(rec), rec.name)
            rec.status.ip = self.netman.attach_cell(
                rec.realm, rec.space, self._owner_key(rec), pid
            )
            if rec.status.reason and rec.status.reason.startswith("network attach failed"):
                rec.status.reason = None
        except Exception as e:  # noqa: BLE001 — cells without a bridge still run
            import logging

            logging.getLogger("kukeon.runner").warning(
                "cell network attach failed for %s: %s", rec.name, e
            )
            # Surface the failure: a Ready cell with no IP and no recorded
            # reason is undebuggable from `kuke get/status` (VERDICT r3
            # weak 5). The record is written by the caller's status flush.
            rec.status.reason = f"network attach failed: {e}"

    def _container_context(self, rec: model.CellRecord, spec: t.ContainerSpec) -> ContainerContext:
        cdir = self.store.container_dir(rec.realm, rec.space, rec.stack, rec.name, spec.name)
        env: dict[str, str] = {
            "KUKEON_REALM": rec.realm,
            "KUKEON_SPACE": rec.space,
            "KUKEON_STACK": rec.stack,
            "KUKEON_CELL": rec.name,
            "KUKEON_CONTAINER": spec.name,
        }
        image_entrypoint: list[str] = []
        image_cmd: list[str] = []
        workdir = spec.workdir
        if spec.image:
            # Image-backed container: inherit the image's env/entry/workdir
            # (spec wins on conflict) + expose the bundle tree.
            from kukeon_tpu.runtime.images import ImageStore

            istore = ImageStore(self.store.ms.root)
            manifest = istore.get(spec.image)
            env.update(manifest.env)
            env["KUKEON_IMAGE"] = manifest.ref
            env["KUKEON_IMAGE_ROOTFS"] = istore.rootfs(manifest.ref)
            image_entrypoint = list(manifest.entrypoint)
            image_cmd = list(manifest.cmd)
            workdir = workdir or manifest.workdir or None
        for e in spec.env:
            env[e.name] = e.value
        binds: list[tuple[str, str, bool]] = []
        tmpfs: list[str] = []
        self._stage_secrets(rec, spec, cdir, env, binds)
        self._mount_volumes(rec, spec, cdir, env, binds, tmpfs)

        sandbox_pid = None
        if self.backend.isolated:
            # Cell-shared namespace set (idempotent; restart-safe pid file).
            sandbox_pid = self.backend.ensure_sandbox(self._cell_dir(rec), rec.name)

        cgroup_dir = None
        if self.cgroups and self.cgroups.available():
            cgroup_dir = self.cgroups.ensure(
                rec.realm, rec.space, rec.stack, rec.name, spec.name
            )
            self.cgroups.apply_limits(
                cgroup_dir,
                memory=spec.resources.memory,
                cpu=spec.resources.cpu,
                pids=spec.resources.pids,
            )
        self._stage_repos(rec, spec, cdir, env, binds)

        command = list(spec.command) + list(spec.args)
        if not spec.command and spec.image:
            # Docker/k8s semantics: spec.args replaces the image CMD while
            # keeping its entrypoint; with no args, entrypoint+cmd run.
            if spec.args:
                command = image_entrypoint + list(spec.args)
            else:
                command = image_entrypoint + image_cmd
        return ContainerContext(
            container_dir=cdir,
            spec=spec,
            env=env,
            command=command,
            cgroup_dir=cgroup_dir,
            workdir=workdir,
            sandbox_pid=sandbox_pid,
            binds=binds,
            tmpfs=tmpfs,
        )

    def _stage_secrets(self, rec: model.CellRecord, spec: t.ContainerSpec,
                       cdir: str, env: dict[str, str],
                       binds: list[tuple[str, str, bool]]) -> None:
        """Stage referenced secrets (reference: ctr/secrets.go:30-60,
        mode 0400) and/or export env vars. Under the namespace backend the
        staged file is bind-mounted read-only at its in-cell path
        (/run/kukeon/secrets/<name>.env or ref.path); the env pointer then
        names the in-cell path."""
        if not spec.secrets:
            return
        isolated = self.backend.isolated
        sdir = os.path.join(cdir, "secrets")
        os.makedirs(sdir, mode=0o700, exist_ok=True)
        for ref in spec.secrets:
            doc = self.store.resolve_scoped(
                consts.SECRETS_DIR, rec.realm, rec.space, rec.stack, ref.name
            )
            if doc is None:
                raise NotFound(
                    f"secret {ref.name!r} not found in scope "
                    f"{rec.realm}/{rec.space}/{rec.stack}"
                )
            data: dict[str, str] = doc.get("data", {})
            if ref.env:
                if len(data) == 1:
                    env[ref.env] = next(iter(data.values()))
                else:
                    for k, v in data.items():
                        env[f"{ref.env}_{k}"] = v
            staged = os.path.join(sdir, f"{ref.name}.env")
            if not isolated and ref.path:
                # Process backend honors an explicit host staging path.
                staged = ref.path
            content = "".join(f"{k}={v}\n" for k, v in sorted(data.items()))
            # The staged file is 0400; restaging (stop/start, restart policy)
            # must replace it, not reopen it (O_TRUNC on a 0400 file EACCESes
            # for non-root daemons).
            try:
                os.unlink(staged)
            except FileNotFoundError:
                pass
            fd = os.open(staged, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o400)
            try:
                os.write(fd, content.encode())
            finally:
                os.close(fd)
            cell_path = staged
            if isolated:
                cell_path = ref.path or os.path.join(
                    consts.SECRETS_MOUNT, f"{ref.name}.env"
                )
                binds.append((staged, cell_path, True))
            env[f"KUKEON_SECRET_{ref.name.upper().replace('-', '_')}"] = cell_path

    def _stage_repos(self, rec: model.CellRecord, spec: t.ContainerSpec,
                     cdir: str, env: dict[str, str],
                     binds: list[tuple[str, str, bool]]) -> None:
        """Pre-start git clone of declared repos, with setup-status reporting
        (reference: cmd/kuketty/repos.go clone stages +
        internal/kuketty/setupstatus typed reports).

        Clones land under the container dir and are bind-mounted at the
        declared in-cell path (namespace backend) or exposed via env pointer
        (process backend). Failures are REPORTED, not fatal: the cell still
        starts and `kuke get` shows state=failed with the git error, matching
        the reference's report-don't-block stage semantics. Existing clones
        are reused (restart-safe)."""
        if not spec.repos:
            return
        import subprocess

        rdir = os.path.join(cdir, "repos")
        os.makedirs(rdir, exist_ok=True)
        # Drop stale entries for this container (restart rewrites them).
        rec.status.setup = [
            s for s in rec.status.setup if s.container != spec.name
        ]
        for i, repo in enumerate(spec.repos):
            st = model.SetupStatus(
                container=spec.name, url=repo.url, path=repo.path,
                state="cloning",
            )
            rec.status.setup.append(st)
            base = os.path.basename(repo.path.rstrip("/")) or f"repo{i}"
            dest = os.path.join(rdir, f"{i}-{base}")
            # Failure cache: clone runs under the cell lock, and the restart
            # path re-enters here from the reconcile tick — a dead remote
            # must not stall daemon-wide supervision for its full timeout on
            # EVERY restart of a crash-looping sibling.
            # url/ref in the key: editing the spec to fix a bad repo must
            # bust the cache immediately, not serve the stale failure.
            fail_key = (self._owner_key(rec), spec.name, i, repo.url, repo.ref)
            last = self._repo_failures.get(fail_key, 0.0)
            if time.time() - last < consts.REPO_RETRY_SECONDS:
                st.state = "failed"
                st.error = "previous clone attempt failed; retry pending"
                continue
            try:
                if not os.path.isdir(os.path.join(dest, ".git")):
                    # `--`: a dash-prefixed url/dest must never parse as a
                    # git option (defense in depth; validate.py rejects them).
                    p = subprocess.run(
                        ["git", "clone", "--", repo.url, dest],
                        capture_output=True, text=True,
                        timeout=consts.REPO_CLONE_TIMEOUT_S,
                    )
                    if p.returncode != 0:
                        raise RuntimeError(p.stderr.strip()[-500:])
                if repo.ref:
                    p = subprocess.run(
                        ["git", "-C", dest, "checkout", "--quiet", repo.ref],
                        capture_output=True, text=True, timeout=60,
                    )
                    if p.returncode != 0:
                        raise RuntimeError(p.stderr.strip()[-500:])
                st.state = "ready"
                self._repo_failures.pop(fail_key, None)
            except (RuntimeError, OSError, subprocess.TimeoutExpired) as e:
                st.state = "failed"
                st.error = str(e)
                self._repo_failures[fail_key] = time.time()
                continue
            key = f"KUKEON_REPO_{i}"
            if self.backend.isolated:
                binds.append((dest, repo.path, False))
                env[key] = repo.path
            else:
                env[key] = dest
        # In-cell setup-status report, as the reference's kuketty writes for
        # attach clients; bound read-only at a fixed path.
        status_file = os.path.join(cdir, consts.SETUP_STATUS_FILE)
        with open(status_file, "w") as f:
            import json

            json.dump([dataclasses.asdict(s) for s in rec.status.setup
                       if s.container == spec.name], f, indent=1)
        if self.backend.isolated:
            binds.append((status_file, consts.SETUP_STATUS_MOUNT, True))

    def _mount_volumes(self, rec: model.CellRecord, spec: t.ContainerSpec,
                       cdir: str, env: dict[str, str],
                       binds: list[tuple[str, str, bool]],
                       tmpfs: list[str] | None = None) -> None:
        """Volume binding. Namespace backend: real bind mounts at the
        declared in-cell path honoring read_only, and tmpfs paths as real
        private tmpfs mounts (reference: ctr/spec.go volume + tmpfs
        mounts). Process backend: env pointer / scratch-dir fallback."""
        import shutil as _shutil

        tmpfs = tmpfs if tmpfs is not None else []
        for idx, vm in enumerate(spec.volumes):
            if vm.tmpfs:
                if self.backend.isolated:
                    tmpfs.append(vm.path)
                else:
                    # Process backend has no mount namespace: a private
                    # scratch dir (wiped each start) + env pointer. Indexed
                    # dir names: path mangling is lossy (/a/b vs /a-b) and
                    # colliding scratch dirs would alias "private" mounts.
                    scratch = os.path.join(cdir, f"tmpfs-{idx}")
                    _shutil.rmtree(scratch, ignore_errors=True)
                    os.makedirs(scratch, exist_ok=True)
                    env[f"KUKEON_TMPFS_{idx}"] = scratch
                continue
            if vm.host_path and self.backend.isolated:
                # Direct host bind (trusted manifests only).
                if vm.path:
                    binds.append((vm.host_path, vm.path, vm.read_only))
                continue
            if vm.name is None:
                continue
            vol = self.store.resolve_scoped(
                consts.VOLUMES_DIR + "-meta", rec.realm, rec.space, rec.stack, vm.name
            ) or self.store.resolve_scoped(
                consts.VOLUMES_DIR, rec.realm, rec.space, rec.stack, vm.name
            )
            if vol is None:
                raise NotFound(f"volume {vm.name!r} not found in scope")
            data_dir = vol.get("dataDir")
            if data_dir:
                key = f"KUKEON_VOLUME_{vm.name.upper().replace('-', '_')}"
                env[key] = data_dir
                if self.backend.isolated:
                    # Image-backed cells lose host-path visibility after
                    # pivot_root, so a path-less volume gets a default
                    # in-cell mount point; host-rootfs cells without an
                    # explicit path keep the host dir via env.
                    path = vm.path or (f"/mnt/{vm.name}" if spec.image else None)
                    if path:
                        binds.append((data_dir, path, vm.read_only))
                        env[key] = path

    def stop_cell(self, realm: str, space: str, stack: str, name: str,
                  grace_s: float | None = None) -> model.CellRecord:
        import signal as _signal

        grace = self.opts.stop_grace_s if grace_s is None else grace_s
        with self.cell_lock(realm, space, stack, name):
            rec = self.store.read_cell(realm, space, stack, name)
            contexts = [
                self._container_context_bare(rec, spec)
                for spec in self.cell_containers(rec)
            ]
            for ctx in contexts:
                if self.backend.container_state(ctx).running:
                    self.backend.signal_container(ctx, _signal.SIGTERM)
            deadline = time.monotonic() + grace
            while time.monotonic() < deadline:
                if not any(self.backend.container_state(c).running for c in contexts):
                    break
                time.sleep(0.05)
            for ctx in contexts:
                if self.backend.container_state(ctx).running:
                    self.backend.signal_container(ctx, _signal.SIGKILL)
            self._finish_stop(rec, contexts)
            return rec

    def kill_cell(self, realm: str, space: str, stack: str, name: str) -> model.CellRecord:
        with self.cell_lock(realm, space, stack, name):
            rec = self.store.read_cell(realm, space, stack, name)
            return self._kill_cell_locked(rec)

    def _kill_cell_locked(self, rec: model.CellRecord) -> model.CellRecord:
        import signal as _signal

        contexts = [
            self._container_context_bare(rec, spec)
            for spec in self.cell_containers(rec)
        ]
        for ctx in contexts:
            if self.backend.container_state(ctx).running:
                self.backend.signal_container(ctx, _signal.SIGKILL)
        self._finish_stop(rec, contexts)
        return rec

    def restart_container(self, realm: str, space: str, stack: str,
                          name: str, container: str) -> model.CellRecord:
        """Immediate single-container restart on the SAME chip grant — the
        rolling-restart primitive (`kuke rollout`). Unlike the reconcile
        path this honors no backoff: the caller already drained the replica
        and is gating on /readyz, so waiting out a crash-loop damper would
        only stretch the capacity hole. A container still running (drain
        wedged short of exit) gets the stop grace window, then SIGKILL —
        the drain already emptied it."""
        import signal as _signal

        with self.cell_lock(realm, space, stack, name):
            rec = self.store.read_cell(realm, space, stack, name)
            containers = self.cell_containers(rec)
            spec = next((c for c in containers if c.name == container), None)
            if spec is None:
                raise NotFound(
                    f"container {container!r} not found in cell {name!r}"
                )
            bare = self._container_context_bare(rec, spec)
            if self.backend.container_state(bare).running:
                self.backend.signal_container(bare, _signal.SIGTERM)
                deadline = time.monotonic() + self.opts.stop_grace_s
                while (time.monotonic() < deadline
                       and self.backend.container_state(bare).running):
                    time.sleep(0.05)
                if self.backend.container_state(bare).running:
                    self.backend.signal_container(bare, _signal.SIGKILL)
            self._ensure_cell_network(rec)
            ctx = self._container_context(rec, spec)
            grant = self._chip_slices(containers,
                                      rec.status.tpu_chips).get(spec.name, [])
            if grant:
                # The cell's grant partition is deterministic by declaration
                # order: the replica comes back on exactly its chips.
                ctx.env.update(self.devices.visibility_env(grant))
                ctx.devices = self.devices.device_nodes(grant)
            self.backend.start_container(ctx)
            live = self.backend.container_state(ctx)
            st = rec.status.container(spec.name)
            if st is None:
                st = model.ContainerStatus(name=spec.name)
                rec.status.containers.append(st)
            st.state = live.state
            st.pid = live.pid
            st.exit_code = live.exit_code
            st.restarts += 1
            st.last_restart_at = time.time()
            st.finished_at = None
            self._m_restarts.inc(cell=self._owner_key(rec),
                                 container=spec.name)
            self._derive_phase(rec)
            self.store.write_cell(rec)
            return rec

    def scale_model_cell(self, realm: str, space: str, stack: str,
                         name: str, target: int) -> model.CellRecord:
        """Set the ACTIVE replica count of an autoscaled model cell — the
        FleetScaler's one write primitive. Scale-up starts the newly
        in-range replicas on their pre-partitioned chip grants (the cell's
        whole ``maxReplicas`` grant was allocated at start, so no device
        negotiation happens here); scale-down stops the now-out-of-range
        replicas — the caller MUST have drained them through the gateway
        first, this method only finishes the exit. The record (target and
        statuses together) is written once at the end, so a crash mid-call
        degrades to "replica still active under the old target" — the
        reconcile loop heals it back to serving — never to a capacity
        hole. Starts are idempotent: a replica a crashed earlier attempt
        left running is simply adopted."""
        import signal as _signal

        from kukeon_tpu.runtime.apply.validate import model_scale_bound

        with self.cell_lock(realm, space, stack, name):
            rec = self.store.read_cell(realm, space, stack, name)
            m = rec.spec.model
            if m is None:
                raise InvalidArgument(f"cell {name!r} is not a model cell")
            bound = model_scale_bound(m)
            lo = max(1, m.min_replicas or 1)
            if bound <= 1:
                raise InvalidArgument(
                    f"cell {name!r} has no replica range to scale "
                    "(set model.maxReplicas)")
            if not (lo <= target <= bound):
                raise InvalidArgument(
                    f"cell {name!r}: target {target} outside "
                    f"[{lo}, {bound}]")
            old = self.model_target(rec)
            containers = self.cell_containers(rec)
            by_name = {c.name: c for c in containers}
            if target > old:
                for i in range(old, target):
                    spec = by_name[f"model-server-{i}"]
                    self._ensure_cell_network(rec)
                    ctx = self._container_context(rec, spec)
                    grant = self._chip_slices(
                        containers, rec.status.tpu_chips).get(spec.name, [])
                    if grant:
                        ctx.env.update(self.devices.visibility_env(grant))
                        ctx.devices = self.devices.device_nodes(grant)
                    if not self.backend.container_state(ctx).running:
                        self.backend.start_container(ctx)
                    live = self.backend.container_state(ctx)
                    st = rec.status.container(spec.name)
                    if st is None:
                        st = model.ContainerStatus(name=spec.name)
                        rec.status.containers.append(st)
                    st.state = live.state
                    st.pid = live.pid
                    st.exit_code = live.exit_code
                    st.started_at = time.time()
                    st.finished_at = None
            else:
                for i in range(target, old):
                    spec = by_name[f"model-server-{i}"]
                    bare = self._container_context_bare(rec, spec)
                    if self.backend.container_state(bare).running:
                        # Normally already exited (the drain shuts the
                        # cell down); the grace window covers a cell that
                        # drained but wedged short of exit.
                        self.backend.signal_container(bare, _signal.SIGTERM)
                        deadline = time.monotonic() + self.opts.stop_grace_s
                        while (time.monotonic() < deadline
                               and self.backend.container_state(bare).running):
                            time.sleep(0.05)
                        if self.backend.container_state(bare).running:
                            self.backend.signal_container(bare,
                                                          _signal.SIGKILL)
                    live = self.backend.container_state(bare)
                    st = rec.status.container(spec.name)
                    if st is not None:
                        st.state = live.state
                        st.pid = None
                        st.exit_code = live.exit_code
                        if st.finished_at is None:
                            st.finished_at = time.time()
            rec.status.target_replicas = target
            self._derive_phase(rec)
            self.store.write_cell(rec)
            return rec

    def start_parked_replica(self, realm: str, space: str, stack: str,
                             name: str) -> tuple[model.CellRecord, str]:
        """Boot the FIRST parked replica of an autoscaled model cell on its
        pre-partitioned chip grant WITHOUT touching ``target_replicas`` —
        the standby pre-warm primitive (rollout standby, scaler warm pool).
        The replica serves and answers /readyz but stays outside the active
        range: the gateway census, phase derivation, and the scaler all
        keep ignoring it, and reconcile never heals or stops it (parked
        containers are recorded, never managed). Idempotent — a standby
        already running is adopted, not restarted. Returns the record and
        the started container's name."""
        with self.cell_lock(realm, space, stack, name):
            rec = self.store.read_cell(realm, space, stack, name)
            m = rec.spec.model
            if m is None:
                raise InvalidArgument(f"cell {name!r} is not a model cell")
            parked = self._parked_names(rec)
            if not parked:
                raise FailedPrecondition(
                    f"cell {name!r} has no parked replica to pre-warm "
                    "(active target is already at the scale bound)")
            # Lowest parked index = the next scale-up promotion target, so
            # the scaler's first scale-up adopts the warm standby in place.
            cname = f"model-server-{self.model_target(rec)}"
            containers = self.cell_containers(rec)
            spec = next(c for c in containers if c.name == cname)
            self._ensure_cell_network(rec)
            ctx = self._container_context(rec, spec)
            grant = self._chip_slices(containers,
                                      rec.status.tpu_chips).get(spec.name, [])
            if grant:
                ctx.env.update(self.devices.visibility_env(grant))
                ctx.devices = self.devices.device_nodes(grant)
            if not self.backend.container_state(ctx).running:
                self.backend.start_container(ctx)
            live = self.backend.container_state(ctx)
            st = rec.status.container(spec.name)
            if st is None:
                st = model.ContainerStatus(name=spec.name)
                rec.status.containers.append(st)
            st.state = live.state
            st.pid = live.pid
            st.exit_code = live.exit_code
            st.started_at = time.time()
            st.finished_at = None
            self.store.write_cell(rec)
            return rec, cname

    def stop_parked_replica(self, realm: str, space: str, stack: str,
                            name: str, container: str) -> model.CellRecord:
        """Park a pre-warmed standby again: stop the named container iff it
        is OUTSIDE the active range (a replica scale-up promoted into the
        target is live capacity — stopping it would punch the hole the
        standby existed to prevent, so that's a silent no-op here).
        ``target_replicas`` is untouched either way."""
        import signal as _signal

        with self.cell_lock(realm, space, stack, name):
            rec = self.store.read_cell(realm, space, stack, name)
            if container not in self._parked_names(rec):
                return rec
            containers = self.cell_containers(rec)
            spec = next((c for c in containers if c.name == container), None)
            if spec is None:
                raise NotFound(
                    f"container {container!r} not found in cell {name!r}")
            bare = self._container_context_bare(rec, spec)
            if self.backend.container_state(bare).running:
                self.backend.signal_container(bare, _signal.SIGTERM)
                deadline = time.monotonic() + self.opts.stop_grace_s
                while (time.monotonic() < deadline
                       and self.backend.container_state(bare).running):
                    time.sleep(0.05)
                if self.backend.container_state(bare).running:
                    self.backend.signal_container(bare, _signal.SIGKILL)
            live = self.backend.container_state(bare)
            st = rec.status.container(spec.name)
            if st is not None:
                st.state = live.state
                st.pid = None
                st.exit_code = live.exit_code
                if st.finished_at is None:
                    st.finished_at = time.time()
            self.store.write_cell(rec)
            return rec

    def _container_context_bare(self, rec: model.CellRecord, spec: t.ContainerSpec) -> ContainerContext:
        """Context sufficient for signal/state/cleanup (no env building)."""
        cdir = self.store.container_dir(rec.realm, rec.space, rec.stack, rec.name, spec.name)
        return ContainerContext(container_dir=cdir, spec=spec, command=list(spec.command))

    def _finish_stop(self, rec: model.CellRecord, contexts: list[ContainerContext]) -> None:
        for ctx, st in zip(contexts, rec.status.containers):
            live = self.backend.container_state(ctx)
            st.state = live.state
            st.exit_code = live.exit_code
            st.pid = None
            st.finished_at = time.time()
        rec.desired_state = "stopped"
        rec.status.phase = model.STOPPED
        if rec.status.tpu_chips:
            self.devices.release(self._owner_key(rec))
            rec.status.tpu_chips = []
        if self.backend.isolated:
            if self.netman is not None:
                self.netman.detach_cell(rec.realm, rec.space, self._owner_key(rec))
            rec.status.ip = None
            self.backend.teardown_sandbox(self._cell_dir(rec))
        self.store.write_cell(rec)

    def delete_cell(self, realm: str, space: str, stack: str, name: str,
                    force: bool = False) -> None:
        # Read and running-check INSIDE the cell lock: every mutating verb
        # serializes on it, and checking outside raced a concurrent
        # start_cell — a cell observed stopped could be started by another
        # thread and then have its tree deleted around a live sandbox
        # (VERDICT r3 weak 6).
        with self.cell_lock(realm, space, stack, name):
            rec = self.store.read_cell(realm, space, stack, name)
            running = any(
                self.backend.container_state(
                    self._container_context_bare(rec, spec)
                ).running
                for spec in self.cell_containers(rec)
            )
            if running:
                if not force:
                    raise FailedPrecondition(
                        f"cell {name!r} is running; stop it first or use force"
                    )
                self._kill_cell_locked(rec)
            for spec in self.cell_containers(rec):
                self.backend.cleanup_container(self._container_context_bare(rec, spec))
            if self.backend.isolated:
                if self.netman is not None:
                    self.netman.detach_cell(realm, space, self._owner_key(rec))
                self.backend.teardown_sandbox(self._cell_dir(rec))
            self.devices.release(self._owner_key(rec))
            self._release_host_ports(rec)
            self.store.delete_cell_tree(realm, space, stack, name)
            if self.cgroups:
                self.cgroups.remove(realm, space, stack, name)

    # --- refresh / restart policy (reference: refresh.go:1110-1458) --------

    def refresh_cell(self, realm: str, space: str, stack: str, name: str) -> tuple[model.CellRecord | None, str]:
        with self.cell_lock(realm, space, stack, name):
            try:
                rec = self.store.read_cell(realm, space, stack, name)
            except NotFound:
                return None, OUTCOME_VANISHED
            return self._refresh_locked(rec)

    def _refresh_locked(self, rec: model.CellRecord) -> tuple[model.CellRecord, str]:
        outcome = OUTCOME_STEADY
        containers = self.cell_containers(rec)
        changed = False
        owner = self._owner_key(rec)
        parked = self._parked_names(rec)

        for spec in containers:
            st = rec.status.container(spec.name)
            if st is None:
                st = model.ContainerStatus(name=spec.name)
                rec.status.containers.append(st)
            ctx = self._container_context_bare(rec, spec)
            live = self.backend.container_state(ctx)
            if spec.name in parked:
                # Scaled out of the active range: record what the backend
                # sees (a drained replica exits 0) but never heal it — the
                # restart policy below would tug against the scaler's
                # scale-down forever.
                if (live.state, live.pid, live.exit_code) != (
                        st.state, st.pid, st.exit_code):
                    changed = changed or st.state != live.state
                    st.state = live.state
                    st.pid = live.pid
                    st.exit_code = live.exit_code
                    if live.exited and st.finished_at is None:
                        st.finished_at = time.time()
                continue
            if (live.state, live.pid, live.exit_code) != (st.state, st.pid, st.exit_code):
                if st.state != live.state:
                    changed = True
                st.state = live.state
                st.pid = live.pid
                st.exit_code = live.exit_code
                if live.exited and st.finished_at is None:
                    st.finished_at = time.time()
                    # Newly observed exit: count it by code so a crash
                    # loop's signature (e.g. the watchdog's 86) is visible
                    # on the daemon scrape, not only in `kuke get`.
                    self._m_exits.inc(cell=owner, container=spec.name,
                                      code=str(live.exit_code or 0))
                if live.exited and (live.exit_code or 0) != 0:
                    # Capture WHY before the restart path wipes the run
                    # artifacts: the log tail at a non-clean exit is the
                    # operator's only evidence in a crash loop (reference:
                    # markCellFailed with reason, runner/start.go:186,414).
                    tail = self._container_log_tail(ctx)
                    if tail:
                        st.last_error = tail
                        changed = True

            # Lifecycle gauges, refreshed every reconcile tick: uptime for
            # running containers, remaining restart backoff for exited
            # ones waiting on their window, budget-exhaustion as a flag.
            anchor = st.last_restart_at or st.started_at
            self._m_uptime.set(
                (time.time() - anchor) if (live.running and anchor) else 0.0,
                cell=owner, container=spec.name)
            self._m_backoff.set(
                self._backoff_remaining(spec, st) if live.exited else 0.0,
                cell=owner, container=spec.name)
            self._m_exhausted.set(
                1.0 if (live.exited
                        and spec.restart_policy.policy != "never"
                        and spec.restart_policy.max_retries is not None
                        and st.restarts >= spec.restart_policy.max_retries)
                else 0.0,
                cell=owner, container=spec.name)

            if live.running:
                # Restart-budget replenishment: a container that has stayed
                # up for a healthy-uptime window earns its budget back, so a
                # bounded `restartMaxRetries` guards against crash LOOPS, not
                # against a month of uptime with occasional crashes
                # (reference keeps a windowed restart-state map,
                # runner/refresh.go:1224-1458).
                anchor = st.last_restart_at or st.started_at
                if (
                    st.restarts > 0
                    and anchor is not None
                    and (time.time() - anchor) >= self.RESTART_RESET_UPTIME_S
                ):
                    st.restarts = 0
                    st.last_error = None
                    changed = True
                    # The crash is history now; stop alarming the operator.
                    if rec.status.reason and rec.status.reason.startswith(
                        f"container {spec.name} crash"
                    ):
                        rec.status.reason = None

            if (
                rec.desired_state == "running"
                and live.exited
                and self._restart_due(spec, st)
            ):
                self._ensure_cell_network(rec)   # sandbox may be recreated
                ctx_full = self._container_context(rec, spec)
                grant = self._chip_slices(containers, rec.status.tpu_chips).get(spec.name, [])
                if grant:
                    # Reuse the cell's grant (stable across restarts).
                    ctx_full.env.update(self.devices.visibility_env(grant))
                    ctx_full.devices = self.devices.device_nodes(grant)
                self.backend.start_container(ctx_full)
                prev_exit = st.exit_code
                live = self.backend.container_state(ctx_full)
                st.state = live.state
                st.pid = live.pid
                st.exit_code = live.exit_code
                st.restarts += 1
                st.last_restart_at = time.time()
                st.finished_at = None
                self._m_restarts.inc(cell=owner, container=spec.name)
                self._m_backoff.set(0.0, cell=owner, container=spec.name)
                if (prev_exit or 0) != 0:
                    why = f": {st.last_error}" if st.last_error else ""
                    rec.status.reason = (
                        f"container {spec.name} crashed (exit {prev_exit}, "
                        f"restart #{st.restarts}){why}"
                    )
                outcome = OUTCOME_RESTARTED
                changed = True
            elif (
                rec.desired_state == "running"
                and live.exited
                and (st.exit_code or 0) != 0
                and spec.restart_policy.policy != "never"
                and spec.restart_policy.max_retries is not None
                and st.restarts >= spec.restart_policy.max_retries
            ):
                why = f": {st.last_error}" if st.last_error else ""
                reason = (
                    f"container {spec.name} crash-looped: restart budget "
                    f"exhausted ({st.restarts}/{spec.restart_policy.max_retries}, "
                    f"last exit {st.exit_code}){why}"
                )
                if rec.status.reason != reason:
                    rec.status.reason = reason
                    changed = True

        # AutoDelete: reap once every container has exited
        # (reference: runner/runner.go:33-45).
        if (
            rec.spec.auto_delete
            and rec.desired_state == "running"
            and rec.status.containers
            and all(c.state == model.C_EXITED for c in rec.status.containers)
        ):
            self._finish_stop(rec, [
                self._container_context_bare(rec, spec) for spec in containers
            ])
            for spec in containers:
                self.backend.cleanup_container(self._container_context_bare(rec, spec))
            self._release_host_ports(rec)
            self.store.delete_cell_tree(rec.realm, rec.space, rec.stack, rec.name)
            if self.cgroups:
                self.cgroups.remove(rec.realm, rec.space, rec.stack, rec.name)
            return rec, OUTCOME_AUTO_DELETED

        old_phase = rec.status.phase
        self._derive_phase(rec)
        if changed or rec.status.phase != old_phase:
            self.store.write_cell(rec)
            if outcome == OUTCOME_STEADY:
                outcome = OUTCOME_HEALED
        return rec, outcome

    # Continuous uptime after which a container's restart count resets.
    RESTART_RESET_UPTIME_S = 300.0

    def _container_log_tail(self, ctx: ContainerContext, limit: int = 500) -> str | None:
        """Last few lines of the container's log (shim log, or the capture
        transcript for attachable containers) for crash-reason reporting."""
        names = [consts.CAPTURE_FILE] if ctx.spec.attachable else [consts.SHIM_LOG]
        for name in names:
            path = os.path.join(ctx.container_dir, name)
            try:
                with open(path, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    f.seek(max(0, size - 4096))
                    data = f.read().decode(errors="replace")
            except OSError:
                continue
            lines = [ln.strip() for ln in data.splitlines() if ln.strip()]
            if lines:
                return "\n".join(lines[-6:])[-limit:]
        return None

    def cell_metrics(self, rec: model.CellRecord) -> dict[str, dict]:
        """Live per-container cgroup metrics (memory_bytes, cpu_usec, pids)
        for `kuke get`/`status` (reference: internal/ctr/cgroups.go:484,
        task.go:50 feed cgroup/task metrics into status). Read-only: never
        creates cgroups, returns {} when the tree isn't managed."""
        if not self.cgroups:
            return {}
        out: dict[str, dict] = {}
        for spec in self.cell_containers(rec):
            d = self.cgroups.path(rec.realm, rec.space, rec.stack, rec.name, spec.name)
            if os.path.isdir(d):
                m = self.cgroups.metrics(d)
                if m:
                    out[spec.name] = m
        return out

    def _backoff_remaining(self, spec: t.ContainerSpec,
                           st: model.ContainerStatus) -> float:
        """Seconds until an exited container's restart window opens; 0 when
        no restart is pending (policy says no, budget spent, or due now)."""
        rp = spec.restart_policy
        if rp.policy == "never":
            return 0.0
        if rp.policy == "on-failure" and (st.exit_code == 0):
            return 0.0
        if rp.max_retries is not None and st.restarts >= rp.max_retries:
            return 0.0
        anchor = st.last_restart_at or st.finished_at
        if anchor is None:
            return 0.0
        return max(0.0, rp.backoff_seconds - (time.time() - anchor))

    def _restart_due(self, spec: t.ContainerSpec, st: model.ContainerStatus) -> bool:
        rp = spec.restart_policy
        if rp.policy == "never":
            return False
        if rp.policy == "on-failure" and (st.exit_code == 0):
            return False
        if rp.max_retries is not None and st.restarts >= rp.max_retries:
            return False
        anchor = st.last_restart_at or st.finished_at
        if anchor is not None and (time.time() - anchor) < rp.backoff_seconds:
            return False
        return True

    def _derive_phase(self, rec: model.CellRecord) -> None:
        # Parked (scaled-down) replicas are intentionally not running: a
        # cell at its autoscale minimum is READY, not degraded.
        parked = self._parked_names(rec)
        states = [c.state for c in rec.status.containers
                  if c.name not in parked]
        if not states:
            rec.status.phase = model.PENDING
            return
        if rec.desired_state == "stopped":
            rec.status.phase = model.STOPPED
            return
        running = sum(1 for s in states if s == model.C_RUNNING)
        if running == len(states):
            rec.status.phase = model.READY
        elif running > 0:
            rec.status.phase = model.DEGRADED
        elif all(s == model.C_EXITED for s in states):
            failed = any(
                (c.exit_code or 0) != 0 for c in rec.status.containers
            )
            rec.status.phase = model.FAILED if failed else model.STOPPED
        else:
            rec.status.phase = model.PENDING
