"""Hierarchy-name validation and ID scheme (reference: internal/util/naming).

Names are DNS-label-ish: lowercase alphanumerics and '-', must start/end
alphanumeric, max 63 chars. Container runtime IDs follow the reference's
``<space>_<stack>_<cell>[_<container>]`` scheme (naming.go:28-64).
"""

from __future__ import annotations

import re
import secrets

from kukeon_tpu.runtime.errors import InvalidArgument

_NAME_RE = re.compile(r"^[a-z0-9]([a-z0-9-]{0,61}[a-z0-9])?$")


def validate_name(name: str, what: str = "name") -> str:
    if not isinstance(name, str) or not _NAME_RE.match(name):
        raise InvalidArgument(
            f"invalid {what} {name!r}: must match [a-z0-9]([a-z0-9-]*[a-z0-9])?, max 63 chars"
        )
    return name


def runtime_id(space: str, stack: str, cell: str, container: str | None = None) -> str:
    parts = [space, stack, cell] + ([container] if container else [])
    return "_".join(parts)


def random_cell_name(prefix: str = "cell") -> str:
    """``<prefix>-<6hex>`` (reference: cellname.go:39-61)."""
    return f"{prefix}-{secrets.token_hex(3)}"


def resolve_under(root: str, relpath: str, what: str = "path") -> str:
    """Resolve ``relpath`` (absolute-style or relative, may contain '..')
    against ``root`` and reject anything that escapes it.

    The single containment clamp for every untrusted-path seam (Kukefile
    COPY src/dst, image-manifest workdir, volume subpaths)."""
    import os

    root_abs = os.path.abspath(root)
    candidate = os.path.abspath(os.path.join(root_abs, relpath.lstrip("/")))
    if candidate != root_abs and not candidate.startswith(root_abs + os.sep):
        raise InvalidArgument(f"{what} escapes {root!r}: {relpath!r}")
    return candidate
