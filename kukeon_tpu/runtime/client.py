"""Client SDK: the transport-agnostic Client + UnixClient + in-process client.

Reference: pkg/api/kukeonv1 (client.go:32, rpcclient.go:36-80, dial.go:37-50)
and internal/client/local (the "promotion" path: read/maintenance verbs can
run the controller in-process when the daemon isn't required).

``dial()`` picks the transport by scheme: ``unix://`` today; ``ssh://`` is
reserved for multi-host TPU-VM workers (same reservation as the reference).
"""

from __future__ import annotations

import errno
import json
import socket
import threading
import time

from kukeon_tpu.runtime.errors import KukeonError, NotSupported, Unavailable, from_code

DIAL_TIMEOUT_S = 5.0   # reference: rpcclient.go:34
# Transient-dial retry budget: during a daemon restart the socket is briefly
# missing (ENOENT) or unaccepted (ECONNREFUSED). CLI calls in that window
# retry with a short backoff (the attach client's PING_BACKOFF_S pattern)
# instead of hard-failing into the operator's face.
DIAL_RETRY_BUDGET_S = 2.0
DIAL_RETRY_BACKOFF_S = 0.1
_TRANSIENT_ERRNOS = (errno.ECONNREFUSED, errno.ENOENT)


class UnixClient:
    """Persistent-connection JSON-RPC client (lazy dial, thread-safe)."""

    def __init__(self, socket_path: str, timeout_s: float = DIAL_TIMEOUT_S,
                 retry_budget_s: float = DIAL_RETRY_BUDGET_S):
        self.socket_path = socket_path
        self.timeout_s = timeout_s
        self.retry_budget_s = retry_budget_s
        self._sock: socket.socket | None = None
        self._file = None
        self._id = 0
        self._lock = threading.Lock()

    # --- transport ---------------------------------------------------------

    def _ensure_conn(self):
        if self._sock is not None:
            return
        deadline = time.monotonic() + self.retry_budget_s
        while True:
            s = socket.socket(socket.AF_UNIX)
            s.settimeout(self.timeout_s)
            try:
                s.connect(self.socket_path)
                break
            except OSError as e:
                s.close()
                if (e.errno in _TRANSIENT_ERRNOS
                        and time.monotonic() < deadline):
                    time.sleep(DIAL_RETRY_BACKOFF_S)
                    continue
                raise Unavailable(
                    f"cannot reach kukeond at {self.socket_path}: {e} "
                    f"(is the daemon running? try `kuke daemon start`)"
                ) from None
        s.settimeout(None)
        self._sock = s
        self._file = s.makefile("rwb")

    def close(self):
        with self._lock:
            if self._file:
                self._file.close()
                self._file = None
            if self._sock:
                self._sock.close()
                self._sock = None

    def call(self, method: str, **params):
        with self._lock:
            self._ensure_conn()
            self._id += 1
            req = {"id": self._id, "method": method, "params": params}
            try:
                self._file.write((json.dumps(req) + "\n").encode())
                self._file.flush()
                line = self._file.readline()
            except OSError as e:
                self.close()
                raise Unavailable(f"daemon connection lost: {e}") from None
            if not line:
                self.close()
                raise Unavailable("daemon closed the connection")
        resp = json.loads(line)
        if "error" in resp and resp["error"]:
            err = resp["error"]
            raise from_code(err.get("code", "internal"), err.get("message", ""))
        return resp.get("result")

    def __getattr__(self, name: str):
        if name.startswith("_") or not name[0].isupper():
            raise AttributeError(name)

        def method(**params):
            return self.call(name, **params)

        return method


class LocalClient:
    """In-process client running the controller directly — the promotion
    path (reference: internal/client/local). Same call surface as UnixClient."""

    def __init__(self, run_path: str):
        from kukeon_tpu.runtime.daemon import RPCService, build_controller

        self.ctl = build_controller(run_path)
        self.ctl.bootstrap()
        self.service = RPCService(self.ctl)

    def call(self, method: str, **params):
        fn = getattr(self.service, method, None)
        if fn is None or method.startswith("_"):
            raise KukeonError(f"unknown method {method!r}")
        return fn(**params)

    def close(self):
        pass

    def __getattr__(self, name: str):
        if name.startswith("_") or not name[0].isupper():
            raise AttributeError(name)

        def method(**params):
            return self.call(name, **params)

        return method


def dial(target: str):
    """unix://<path> today; ssh://host reserved for multi-host slices."""
    if target.startswith("unix://"):
        return UnixClient(target[len("unix://") :])
    if target.startswith("ssh://"):
        raise NotSupported(
            "ssh:// transport (multi-host TPU workers) is reserved, not yet implemented"
        )
    if target.startswith("/"):
        return UnixClient(target)
    raise NotSupported(f"unsupported transport in {target!r}")
