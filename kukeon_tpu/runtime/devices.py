"""TPU device manager: chip discovery, per-cell affinity, visibility env.

The first TPU-native piece (SURVEY.md section 7 step 5; BASELINE.json north
star: "internal/ctr grows a libtpu device manager"). Chips are a schedulable
resource like the reference's memory limits: the runner asks for N chips at
cell start, the manager hands out concrete chip ids, persists the allocation
in the metadata store, and produces the env that makes libtpu/JAX see ONLY
those chips (libtpu is single-process-per-chip-set with no virtualization —
partitioning must be airtight; SURVEY.md "hard parts").

Discovery order: explicit override (KUKEON_TPU_CHIPS — used by tests and CI
hosts without TPUs), /dev/accel* device nodes (TPU-VM), /dev/vfio groups.
"""

from __future__ import annotations

import glob
import os
import re

from kukeon_tpu.runtime.errors import FailedPrecondition
from kukeon_tpu.runtime.metadata import MetadataStore

ALLOC_FILE = "tpu-allocations.json"


def discover_chips() -> list[int]:
    override = os.environ.get("KUKEON_TPU_CHIPS")
    if override is not None:
        override = override.strip()
        if not override:
            return []
        return [int(x) for x in override.split(",")]
    nodes = glob.glob("/dev/accel*")
    chips = []
    for n in nodes:
        m = re.search(r"accel(?:_)?(\d+)$", n)
        if m:
            chips.append(int(m.group(1)))
    if chips:
        return sorted(chips)
    vfio = glob.glob("/dev/vfio/[0-9]*")
    return sorted(int(os.path.basename(v)) for v in vfio)


def probe_tpu_runtime(timeout_s: float = 20.0) -> tuple[str, str]:
    """Live-runtime health probe: ('ok'|'wedged'|'unavailable', detail).

    Visible device nodes prove nothing about the runtime plane — a wedged
    libtpu/tunnel accepts the client and then blocks the first transfer
    forever (observed in r4/r5: a bare 64 MB device_put hangs). The probe
    runs a tiny device_put in a throwaway subprocess (libtpu is
    single-process, and only a subprocess is reliably killable mid-hang)
    and reports wall time, so `kuke doctor` distinguishes "no TPU" from
    "TPU present but the runtime is wedged"."""
    import subprocess
    import sys

    # Fault seam: KUKEON_FAULTS=devices.probe_wedged:1 makes the probe
    # report a wedged runtime without needing a chip to actually wedge —
    # the watchdog/restart path is tested by injection, not by timing.
    from kukeon_tpu import faults

    try:
        faults.maybe_fail("devices.probe_wedged")
    except faults.FaultInjected as e:
        return "wedged", f"fault-injected: {e}"

    code = (
        "import time, numpy, jax;"
        "t0 = time.monotonic();"
        "d = jax.device_put(numpy.ones((1024, 1024), numpy.int8));"
        "jax.block_until_ready(d);"
        "print(jax.default_backend(), round(time.monotonic() - t0, 2))"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return ("wedged",
                f"1MB device_put did not finish in {timeout_s:.0f}s "
                "(runtime hung / tunnel down — model cells will crash-loop)")
    if out.returncode != 0:
        err = out.stderr.strip().splitlines()
        return "unavailable", (err[-1][:200] if err else f"rc={out.returncode}")
    backend, dt = out.stdout.split()[-2:]
    if backend != "tpu" and discover_chips():
        # TPU init failed non-fatally and JAX fell back to another backend:
        # chips are visible but NOT usable — "ok backend=cpu" would read as
        # healthy while model cells pinned to the TPU crash-loop.
        return ("unavailable",
                f"chips visible but backend={backend} (TPU init failed; "
                "check libtpu / driver versions)")
    return "ok", f"backend={backend}, 1MB device_put in {dt}s"


class TPUDeviceManager:
    """Chip accounting, persisted so daemon restarts keep allocations."""

    def __init__(self, store: MetadataStore, chips: list[int] | None = None):
        self.store = store
        self.chips = chips if chips is not None else discover_chips()

    # allocations: {str(chip_id): "realm/space/stack/cell"}

    def _load(self) -> dict[str, str]:
        return self.store.read_json_or({}, ALLOC_FILE)

    def _save(self, allocs: dict[str, str]) -> None:
        self.store.write_json(allocs, ALLOC_FILE)

    def allocated(self) -> dict[int, str]:
        return {int(k): v for k, v in self._load().items()}

    def free_chips(self) -> list[int]:
        used = set(self.allocated())
        return [c for c in self.chips if c not in used]

    def allocate(self, owner: str, n: int) -> list[int]:
        """Grant n chips to ``owner`` (idempotent: an existing grant of the
        right size is returned as-is; a wrong-size grant is resized)."""
        with self.store.lock():
            allocs = self._load()
            mine = sorted(int(k) for k, v in allocs.items() if v == owner)
            if len(mine) == n:
                return mine
            for c in mine:   # resize: release then re-grant
                del allocs[str(c)]
            free = [c for c in self.chips if str(c) not in allocs]
            if len(free) < n:
                raise FailedPrecondition(
                    f"not enough TPU chips: want {n}, free {len(free)} of {len(self.chips)}"
                )
            grant = free[:n]
            for c in grant:
                allocs[str(c)] = owner
            self._save(allocs)
            return grant

    def release(self, owner: str) -> None:
        with self.store.lock():
            allocs = self._load()
            remaining = {k: v for k, v in allocs.items() if v != owner}
            if len(remaining) != len(allocs):
                self._save(remaining)

    @staticmethod
    def device_nodes(chips: list[int]) -> list[str]:
        """Host /dev nodes backing these chips (for namespace injection:
        the namespace backend's /dev contains ONLY what this returns plus
        the standard nodes — reference: internal/ctr/devices.go:23-171).
        Empty on hosts whose TPU plane is not device-node-backed (e.g. the
        axon loopback tunnel)."""
        out = []
        for c in chips:
            for cand in (f"/dev/accel{c}", f"/dev/accel_{c}", f"/dev/vfio/{c}"):
                if os.path.exists(cand):
                    out.append(cand)
        if out and os.path.exists("/dev/vfio/vfio"):
            out.append("/dev/vfio/vfio")
        return out

    @staticmethod
    def visibility_env(chips: list[int]) -> dict[str, str]:
        """Env that restricts libtpu/JAX to exactly these chips.

        TPU_VISIBLE_DEVICES is the libtpu chip-visibility knob on TPU-VMs;
        TPU_CHIPS_PER_PROCESS_BOUNDS/TPU_PROCESS_BOUNDS pin the topology for
        a chip subset (the multi-process-per-host recipe). KUKEON_TPU_DEVICES
        carries the raw device paths for backends that bind-mount nodes.
        """
        ids = ",".join(str(c) for c in chips)
        n = len(chips)
        env = {
            "TPU_VISIBLE_DEVICES": ids,
            "KUKEON_TPU_DEVICES": ",".join(f"/dev/accel{c}" for c in chips),
        }
        if 0 < n <= 4:
            env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = f"{n},1,1"
            env["TPU_PROCESS_BOUNDS"] = "1,1,1"
        return env
