"""Typed configuration registry + on-disk configuration documents.

Reference seams: cmd/config/env.go:25-260 (the ``Var`` registry twinning
every flag with a ``KUKE_*``/``KUKEON_*``/``KUKEOND_*`` env var),
internal/serverconfig (ServerConfiguration auto-written once on first daemon
start, commented so operators can edit without reading source), and
internal/clientconfig (client-side document).

Precedence, matching the reference exactly:

    explicit --flag  >  env var  >  configuration document  >  default

The server document lives at ``<run_path>/kukeond.yaml`` by default
(overridable with ``KUKEOND_CONFIGURATION``); the reference writes
``/etc/kukeon/kukeond.yaml``, but this build keeps every artifact under the
run path so parallel instances and tests never collide on /etc. The client
document lives at ``~/.kuke-tpu/config.yaml`` (``KUKEON_CLIENT_CONFIGURATION``).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any

import yaml

from kukeon_tpu.runtime import consts
from kukeon_tpu.runtime.errors import InvalidArgument

KIND_SERVER = "ServerConfiguration"
KIND_CLIENT = "ClientConfiguration"


@dataclasses.dataclass(frozen=True)
class Var:
    """One configuration knob: env var name, document spec key, default."""

    env: str
    key: str                    # camelCase key in the document's spec
    default: Any
    help: str = ""
    cast: str = "str"           # str | float | int | bool

    def parse(self, raw: str) -> Any:
        try:
            if self.cast == "float":
                return float(raw)
            if self.cast == "int":
                return int(raw)
            if self.cast == "bool":
                return raw.strip().lower() in ("1", "true", "yes", "on")
            return raw
        except ValueError as e:
            raise InvalidArgument(f"{self.env}={raw!r}: {e}") from e


# The registry. Every knob the daemon or CLI reads goes through here so the
# precedence chain is uniform (reference: cmd/config/env.go DefineKV).
REGISTRY: tuple[Var, ...] = (
    Var("KUKEON_RUN_PATH", "runPath", consts.DEFAULT_RUN_PATH,
        "metadata + state root for this instance"),
    Var("KUKEOND_SOCKET", "socket", "",
        "daemon unix socket; empty = <runPath>/kukeond.sock"),
    Var("KUKEOND_SOCKET_GID", "socketGID", 0,
        "group ID the daemon chowns its socket to (0 = root only)", "int"),
    Var("KUKEON_NO_DAEMON", "noDaemon", False,
        "run verbs against an in-process controller", "bool"),
    Var("KUKEOND_RECONCILE_INTERVAL", "reconcileInterval",
        consts.DEFAULT_RECONCILE_INTERVAL_S,
        "seconds between reconcile ticks (0 disables the loop)", "float"),
    Var("KUKEON_POD_SUBNET_CIDR", "podSubnetCIDR", consts.DEFAULT_SUBNET_POOL,
        "parent CIDR the per-space subnet allocator subdivides"),
    Var("KUKEOND_DISK_PRESSURE_WARN_PCT", "diskPressureWarnPct",
        consts.DISK_PRESSURE_WARN_PCT,
        "disk usage %% that logs a warning each reconcile tick", "float"),
    Var("KUKEOND_DISK_PRESSURE_BLOCK_PCT", "diskPressureBlockPct",
        consts.DISK_PRESSURE_BLOCK_PCT,
        "disk usage %% above which new cell creation is refused", "float"),
    Var("KUKEON_STOP_GRACE_SECONDS", "stopGraceSeconds",
        consts.DEFAULT_STOP_GRACE_S,
        "SIGTERM->SIGKILL escalation window for container stop", "float"),
    Var("KUKEON_TPU_CHIPS", "tpuChips", "",
        "comma-separated chip ids overriding /dev/accel* discovery"),
    Var("KUKEOND_LOG_LEVEL", "logLevel", "info",
        "daemon log level (debug|info|warn|error)"),
    Var("KUKEON_DEFAULT_MEMORY_LIMIT_BYTES", "defaultMemoryLimitBytes", 0,
        "fallback memory limit for containers without one (0 = none)", "int"),
    Var("KUKEON_CGROUP_ROOT", "cgroupRoot", "/kukeon-tpu",
        "cgroup-v2 subtree all cells live under"),
    Var("KUKEOND_CONFIGURATION", "", "",
        "path of the ServerConfiguration document (meta: not itself stored)"),
    Var("KUKEON_CLIENT_CONFIGURATION", "", "",
        "path of the ClientConfiguration document (meta)"),
)

_BY_ENV = {v.env: v for v in REGISTRY}


class Settings:
    """Resolves knob values through flag > env > document > default."""

    def __init__(self, doc_spec: dict | None = None):
        self.doc_spec = dict(doc_spec or {})

    def get(self, env_name: str, flag_value: Any = None) -> Any:
        var = _BY_ENV[env_name]
        if flag_value is not None:
            return flag_value
        raw = os.environ.get(var.env)
        if raw is not None and raw != "":
            return var.parse(raw)
        if var.key and var.key in self.doc_spec:
            val = self.doc_spec[var.key]
            # Document values arrive as YAML scalars; coerce strings.
            return var.parse(str(val)) if isinstance(val, str) else val
        return var.default


# --- configuration documents -------------------------------------------------


def server_config_path(run_path: str) -> str:
    return os.environ.get("KUKEOND_CONFIGURATION") or os.path.join(
        run_path, "kukeond.yaml"
    )


def client_config_path() -> str:
    return os.environ.get("KUKEON_CLIENT_CONFIGURATION") or os.path.join(
        os.path.expanduser("~"), ".kuke-tpu", "config.yaml"
    )


def load_configuration(path: str, kind: str) -> dict:
    """Parsed ``spec`` of the document at path. An absent file returns {}
    (callers fall back to env + defaults — reference: serverconfig.go:41-68);
    a present-but-invalid file is an error, never silently ignored."""
    try:
        with open(path) as f:
            doc = yaml.safe_load(f) or {}
    except FileNotFoundError:
        return {}
    except (OSError, yaml.YAMLError) as e:
        raise InvalidArgument(f"read configuration {path!r}: {e}") from e
    if not isinstance(doc, dict):
        raise InvalidArgument(f"configuration {path!r}: not a mapping")
    got = doc.get("kind", "")
    if got and got != kind:
        raise InvalidArgument(
            f"configuration {path!r} has kind {got!r}, want {kind!r}"
        )
    spec = doc.get("spec") or {}
    if not isinstance(spec, dict):
        raise InvalidArgument(f"configuration {path!r}: spec is not a mapping")
    return spec


def write_default_server_configuration(path: str, values: dict) -> bool:
    """First-start auto-write (reference: serverconfig.go WriteDefault):
    renders a fully commented document carrying the values the daemon
    actually bound to, O_EXCL so concurrent daemon starts can't both write,
    and never overwrites an existing file. Returns True only on create."""
    lines = [
        "# kukeond ServerConfiguration — auto-generated on first daemon start.",
        "# Precedence: explicit --flag > KUKEON_*/KUKEOND_* env > this file > default.",
        "# Existing files are never overwritten; delete this file to regenerate.",
        "apiVersion: kukeon.io/v1beta1",
        f"kind: {KIND_SERVER}",
        "metadata:",
        "  name: default",
        "spec:",
    ]
    for var in REGISTRY:
        if not var.key:
            continue
        val = values.get(var.key, var.default)
        lines.append(f"  # {var.help}  [env {var.env}]")
        lines.append(f"  # Default: {var.default!r}")
        lines.append("  " + yaml.safe_dump({var.key: val}).strip())
        lines.append("")
    rendered = "\n".join(lines).rstrip() + "\n"

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o600)
    except FileExistsError:
        return False
    with os.fdopen(fd, "w") as f:
        f.write(rendered)
    return True


def server_settings(run_path: str) -> Settings:
    return Settings(load_configuration(server_config_path(run_path), KIND_SERVER))


def client_settings() -> Settings:
    return Settings(load_configuration(client_config_path(), KIND_CLIENT))
