"""cgroup-v2 tree management for cells.

Reference: internal/ctr/cgroups.go:44-484 (create/load/delete, subtree
controller delegation incl. ancestors, metrics) + internal/cgroupcheck. The
tree mirrors the hierarchy: <root>/kukeon/<realm>/<space>/<stack>/<cell>.
Processes only ever join leaf cell cgroups, so the no-internal-process rule
is satisfied by construction; controllers are delegated down the ancestor
chain before a leaf is used.

The root is injectable so tests run against a fake tempdir root (the
reference tests cgroup logic against seeded tempdirs — cgroupcheck_test.go:85).
"""

from __future__ import annotations

import os

CONTROLLERS = ("cpu", "memory", "pids")


class CgroupManager:
    def __init__(self, root: str = "/sys/fs/cgroup", base: str = "kukeon"):
        self.root = root
        self.base = base

    # --- availability ------------------------------------------------------

    def available(self) -> bool:
        try:
            ctrl = os.path.join(self.root, "cgroup.controllers")
            if not os.path.exists(ctrl):
                return False
            os.makedirs(os.path.join(self.root, self.base), exist_ok=True)
            # Write-probe: delegation can make the dir creatable but the
            # controller files read-only (the cgroup-namespace trap the
            # reference disambiguates; internal/cgroupcheck/cgroupcheck.go).
            probe = os.path.join(self.root, self.base, "cgroup.subtree_control")
            with open(probe, "a"):
                pass
            return True
        except OSError:
            return False

    def controllers(self) -> set[str]:
        try:
            with open(os.path.join(self.root, "cgroup.controllers")) as f:
                return set(f.read().split())
        except OSError:
            return set()

    # --- tree ops ----------------------------------------------------------

    def path(self, *parts: str) -> str:
        return os.path.join(self.root, self.base, *parts)

    def ensure(self, *parts: str) -> str:
        """Create the cgroup and delegate controllers down the chain."""
        want = [c for c in CONTROLLERS if c in self.controllers()]
        cur = os.path.join(self.root, self.base)
        os.makedirs(cur, exist_ok=True)
        chain = [cur]
        for p in parts:
            cur = os.path.join(cur, p)
            os.makedirs(cur, exist_ok=True)
            chain.append(cur)
        # Enable controllers in every ancestor's subtree_control (leaf last,
        # which never needs it since processes live there).
        for d in chain[:-1]:
            self._enable_subtree(d, want)
        return chain[-1]

    def _enable_subtree(self, d: str, controllers: list[str]) -> None:
        if not controllers:
            return
        path = os.path.join(d, "cgroup.subtree_control")
        try:
            with open(path) as f:
                have = set(f.read().split())
        except OSError:
            return
        missing = [c for c in controllers if c not in have]
        if not missing:
            return
        try:
            with open(path, "w") as f:
                f.write(" ".join(f"+{c}" for c in missing))
        except OSError:
            pass  # best-effort: limits degrade gracefully

    def apply_limits(self, cgroup_dir: str, *, memory: str | None = None,
                     cpu: float | None = None, pids: int | None = None) -> None:
        if memory is not None:
            self._write(cgroup_dir, "memory.max", str(parse_memory(memory)))
        if cpu is not None:
            period = 100_000
            quota = int(cpu * period)
            self._write(cgroup_dir, "cpu.max", f"{quota} {period}")
        if pids is not None:
            self._write(cgroup_dir, "pids.max", str(pids))

    def metrics(self, cgroup_dir: str) -> dict:
        out = {}
        for name, key in (
            ("memory.current", "memory_bytes"),
            ("pids.current", "pids"),
        ):
            try:
                with open(os.path.join(cgroup_dir, name)) as f:
                    out[key] = int(f.read().strip())
            except (OSError, ValueError):
                pass
        try:
            with open(os.path.join(cgroup_dir, "cpu.stat")) as f:
                for line in f:
                    k, _, v = line.partition(" ")
                    if k == "usage_usec":
                        out["cpu_usec"] = int(v)
        except OSError:
            pass
        return out

    def remove(self, *parts: str) -> None:
        """Remove a cgroup subtree (children first; dirs must be empty of
        processes — callers stop tasks before removal)."""
        top = self.path(*parts)
        if not os.path.isdir(top):
            return
        for dirpath, dirnames, _ in os.walk(top, topdown=False):
            del dirnames
            try:
                os.rmdir(dirpath)
            except OSError:
                pass

    def _write(self, d: str, name: str, value: str) -> None:
        try:
            with open(os.path.join(d, name), "w") as f:
                f.write(value)
        except OSError:
            pass


def parse_memory(s: str) -> int:
    """'2Gi' / '512Mi' / '100M' / bytes-as-int."""
    s = s.strip()
    units = {
        "Ki": 1024, "Mi": 1024**2, "Gi": 1024**3, "Ti": 1024**4,
        "K": 1000, "M": 1000**2, "G": 1000**3, "T": 1000**4,
    }
    for suffix in sorted(units, key=len, reverse=True):
        if s.endswith(suffix):
            return int(float(s[: -len(suffix)]) * units[suffix])
    return int(s)
