"""Image subsystem: store + Kukefile builder (the kukebuild analog).

Reference seams covered (SURVEY.md §2.1 kukebuild, §2.6 internal/ctr
images): image load/list/get/delete/prune, and a standalone builder that
writes images straight into the store (the reference embeds BuildKit and
writes into containerd's namespace; here the store IS the runtime's image
namespace).

Process-backend image model: an image is a versioned bundle

  <run_path>/images/<encoded name:tag>/
    manifest.json     {name, tag, parent, entrypoint, cmd, env, workdir,
                       labels, createdAt}
    rootfs/           overlay tree the workload sees via KUKEON_IMAGE_*

A container whose spec names an image inherits the image's env/entrypoint/
workdir (spec wins on conflict) and gets KUKEON_IMAGE_ROOTFS pointing at
the bundle tree — full mount-namespace isolation belongs to a containerd
backend; this backend's contract is env + entry + files.

Kukefile grammar (Dockerfile subset, enough for the reference's team image
flow: FROM walk, build args, REGISTRY threading):

  ARG NAME[=default]
  FROM <image[:tag]> | scratch
  COPY <src> <dst>
  ENV KEY=VALUE
  WORKDIR <dir>
  LABEL k=v
  RUN <command...>              # executed with rootfs as cwd
  ENTRYPOINT ["a","b"] | cmd    # exec or shell form
  CMD ["a","b"] | cmd

``${ARG}``/`$ARG` substitution applies to FROM/COPY/ENV/LABEL/WORKDIR
values, matching how the reference threads the REGISTRY build-arg.
"""

from __future__ import annotations

import json
import os
import re
import shlex
import shutil
import subprocess
import threading
import time
from dataclasses import dataclass, field

from kukeon_tpu.runtime import naming
from kukeon_tpu.runtime.errors import InvalidArgument, NotFound

IMAGES_DIR = "images"


def split_ref(ref: str) -> tuple[str, str]:
    """name[:tag] -> (name, tag); tag defaults to latest."""
    if ":" in ref.rsplit("/", 1)[-1]:
        name, _, tag = ref.rpartition(":")
        return name, tag
    return ref, "latest"


def encode_ref(ref: str) -> str:
    name, tag = split_ref(ref)
    return f"{name}:{tag}".replace("/", "_")


@dataclass
class ImageManifest:
    name: str = ""
    tag: str = "latest"
    parent: str = ""                 # FROM ref ("" = scratch)
    entrypoint: list[str] = field(default_factory=list)
    cmd: list[str] = field(default_factory=list)
    env: dict[str, str] = field(default_factory=dict)
    workdir: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    created_at: float = 0.0

    @property
    def ref(self) -> str:
        return f"{self.name}:{self.tag}"

    def to_json(self) -> dict:
        return {
            "name": self.name, "tag": self.tag, "parent": self.parent,
            "entrypoint": self.entrypoint, "cmd": self.cmd, "env": self.env,
            "workdir": self.workdir, "labels": self.labels,
            "createdAt": self.created_at,
        }

    @staticmethod
    def from_json(d: dict) -> "ImageManifest":
        return ImageManifest(
            name=d.get("name", ""), tag=d.get("tag", "latest"),
            parent=d.get("parent", ""),
            entrypoint=list(d.get("entrypoint") or []),
            cmd=list(d.get("cmd") or []),
            env=dict(d.get("env") or {}),
            workdir=d.get("workdir", ""),
            labels=dict(d.get("labels") or {}),
            created_at=d.get("createdAt", 0.0),
        )


class ImageStore:
    def __init__(self, run_path: str):
        self.root = os.path.join(run_path, IMAGES_DIR)

    def _dir(self, ref: str) -> str:
        return os.path.join(self.root, encode_ref(ref))

    def rootfs(self, ref: str) -> str:
        return os.path.join(self._dir(ref), "rootfs")

    def exists(self, ref: str) -> bool:
        return os.path.exists(os.path.join(self._dir(ref), "manifest.json"))

    def get(self, ref: str) -> ImageManifest:
        path = os.path.join(self._dir(ref), "manifest.json")
        if not os.path.exists(path):
            raise NotFound(f"image {ref!r} not found")
        with open(path) as f:
            return ImageManifest.from_json(json.load(f))

    def list(self) -> list[ImageManifest]:
        if not os.path.isdir(self.root):
            return []
        out = []
        for entry in sorted(os.listdir(self.root)):
            if entry.startswith("."):   # .staging-* / .trash are not images
                continue
            path = os.path.join(self.root, entry, "manifest.json")
            if os.path.exists(path):
                with open(path) as f:
                    out.append(ImageManifest.from_json(json.load(f)))
        return out

    def put(self, manifest: ImageManifest) -> str:
        d = self._dir(manifest.ref)
        os.makedirs(os.path.join(d, "rootfs"), exist_ok=True)
        manifest.created_at = manifest.created_at or time.time()
        tmp = os.path.join(d, ".manifest.tmp")
        with open(tmp, "w") as f:
            json.dump(manifest.to_json(), f, indent=2)
        os.replace(tmp, os.path.join(d, "manifest.json"))
        return d

    def stage(self, ref: str) -> str:
        """A fresh, caller-private staging bundle dir (with empty rootfs/)
        for ref. Build or import into it, then commit(); a failure before
        commit never touches the live image at the same tag. The name is
        uniquified so concurrent builds/loads of the same ref can't destroy
        each other's staging mid-write (last commit wins)."""
        staging = os.path.join(
            self.root,
            f".staging-{encode_ref(ref)}-{os.getpid()}-{time.monotonic_ns()}",
        )
        os.makedirs(os.path.join(staging, "rootfs"))
        return staging

    # Serializes the swap step of commit() across daemon RPC threads; the
    # expensive build/extract work stays parallel (each in its own staging).
    _commit_lock = threading.Lock()

    def commit(self, manifest: ImageManifest, staging: str) -> str:
        """Atomically promote a staged bundle to the live image dir: the old
        bundle (stale rootfs included) is swapped out whole, never merged.
        Concurrent commits of the same ref serialize; last one wins.

        The displaced bundle is MOVED into ``.trash/`` and kept, not
        deleted: a running cell started from the previous image may hold its
        cwd (and open files) inside that rootfs, and deleting it would yank
        the directory out from under a live workload. The dot-dir keeps
        displaced bundles out of list()/prune() entirely (no phantom
        duplicate refs, no collisions with tags that contain '.old-');
        gc_old() reaps them later (prune / delete call it)."""
        manifest.created_at = manifest.created_at or time.time()
        with open(os.path.join(staging, "manifest.json"), "w") as f:
            json.dump(manifest.to_json(), f, indent=2)
        d = self._dir(manifest.ref)
        trash = os.path.join(self.root, ".trash")
        os.makedirs(trash, exist_ok=True)
        old = os.path.join(
            trash, f"{encode_ref(manifest.ref)}-{os.getpid()}-{time.monotonic_ns()}"
        )
        with self._commit_lock:
            try:
                os.rename(d, old)
            except FileNotFoundError:
                pass   # no previous image at this tag
            os.rename(staging, d)
        return d

    def gc_old(self) -> int:
        """Reap bundles displaced by rebuilds (``.trash/``). Safe when no
        cell is mid-flight on a pre-rebuild image; wired into prune and
        delete, which already imply operator-driven cleanup."""
        trash = os.path.join(self.root, ".trash")
        if not os.path.isdir(trash):
            return 0
        n = len(os.listdir(trash))
        shutil.rmtree(trash, ignore_errors=True)
        return n

    def abort(self, staging: str) -> None:
        shutil.rmtree(staging, ignore_errors=True)

    def delete(self, ref: str) -> None:
        if not self.exists(ref):
            raise NotFound(f"image {ref!r} not found")
        shutil.rmtree(self._dir(ref), ignore_errors=True)
        self.gc_old()

    def prune(self, in_use: set[str]) -> list[str]:
        """Delete images not referenced by any cell spec; returns refs
        removed. Parents of in-use images are kept (FROM chains stay
        rebuildable). in_use refs are normalized (bare ``tool`` == the
        stored ``tool:latest``) so spec shorthand never loses an image."""
        keep = set()
        for ref in in_use:
            cur = "%s:%s" % split_ref(ref)
            while cur and cur not in keep:
                keep.add(cur)
                try:
                    cur = self.get(cur).parent
                except NotFound:
                    break
                cur = "%s:%s" % split_ref(cur) if cur else cur
        removed = []
        for m in self.list():
            if m.ref not in keep:
                self.delete(m.ref)
                removed.append(m.ref)
        self.gc_old()
        return removed

    # --- tar import/export (kuke image load / save) -------------------------

    # The metadata tar member lives under rootfs/ in the archive layout:
    # `rootfs/...` entries are the filesystem, this sibling member is the
    # manifest — so a real /manifest.json INSIDE the image never collides.
    _TAR_META = "kukeon-manifest.json"
    _TAR_ROOTFS = "rootfs"

    def load_tar(self, tar_path: str, ref: str) -> ImageManifest:
        """Import a tarball as an image. Layout: ``rootfs/`` tree + optional
        sibling ``kukeon-manifest.json`` with runtime metadata. A flat tar
        (no rootfs/ prefix) imports as a bare rootfs for convenience."""
        import tarfile

        name, tag = split_ref(ref)
        m = ImageManifest(name=name, tag=tag)
        staging = self.stage(m.ref)
        try:
            rootfs = os.path.join(staging, "rootfs")

            def norm(n: str) -> str:
                # `tar -cf x.tar -C bundle .` produces ./-prefixed members;
                # they must still match the structured layout.
                return n[2:] if n.startswith("./") else n

            with tarfile.open(tar_path) as tf:
                names = [norm(n) for n in tf.getnames()]
                structured = any(
                    n == self._TAR_ROOTFS or n.startswith(self._TAR_ROOTFS + "/")
                    for n in names
                )
                if structured:
                    tf.extractall(staging, filter="data",
                                  members=[mem for mem in tf.getmembers()
                                           if norm(mem.name) == self._TAR_ROOTFS
                                           or norm(mem.name).startswith(self._TAR_ROOTFS + "/")])
                    meta_member = next(
                        (mem for mem in tf.getmembers()
                         if norm(mem.name) == self._TAR_META), None
                    )
                    if meta_member is not None:
                        meta = json.load(tf.extractfile(meta_member))
                        m.entrypoint = list(meta.get("entrypoint") or [])
                        m.cmd = list(meta.get("cmd") or [])
                        m.env = dict(meta.get("env") or {})
                        m.workdir = meta.get("workdir", "")
                        m.labels = dict(meta.get("labels") or {})
                else:
                    tf.extractall(rootfs, filter="data")
        except BaseException:
            self.abort(staging)
            raise
        self.commit(m, staging)
        return m

    def save_tar(self, ref: str, tar_path: str) -> None:
        import io
        import tarfile

        m = self.get(ref)
        rootfs = self.rootfs(ref)
        with tarfile.open(tar_path, "w") as tf:
            tf.add(rootfs, arcname=self._TAR_ROOTFS)
            meta = json.dumps({
                "entrypoint": m.entrypoint, "cmd": m.cmd, "env": m.env,
                "workdir": m.workdir, "labels": m.labels,
            }).encode()
            info = tarfile.TarInfo(self._TAR_META)
            info.size = len(meta)
            tf.addfile(info, io.BytesIO(meta))


# --- Kukefile ----------------------------------------------------------------


@dataclass
class Instruction:
    op: str
    args: list[str]


_VAR_RE = re.compile(r"\$\{(\w+)\}|\$(\w+)")


def parse_kukefile(text: str, origin: str = "Kukefile") -> list[Instruction]:
    out = []
    continuation = ""
    for lineno, raw in enumerate(text.splitlines(), 1):
        stripped = raw.strip()
        if continuation:
            # Docker semantics inside a continuation: comment lines are
            # skipped, blank lines dropped — neither terminates it.
            if not stripped or stripped.startswith("#"):
                continue
            line = continuation + stripped
        else:
            if not stripped or stripped.startswith("#"):
                continue
            line = stripped
        continuation = ""
        if line.endswith("\\"):
            continuation = line[:-1].rstrip() + " "
            continue
        op, _, rest = line.partition(" ")
        op = op.upper()
        if op not in ("ARG", "FROM", "COPY", "ENV", "WORKDIR", "LABEL",
                      "RUN", "ENTRYPOINT", "CMD"):
            raise InvalidArgument(f"{origin}:{lineno}: unknown instruction {op!r}")
        out.append(Instruction(op=op, args=[rest.strip()]))
    if continuation:
        raise InvalidArgument(f"{origin}: dangling line continuation")
    return out


def _subst(value: str, vars_: dict[str, str]) -> str:
    def repl(m):
        key = m.group(1) or m.group(2)
        return vars_.get(key, "")
    return _VAR_RE.sub(repl, value)


def _parse_kv(rest: str, op: str) -> tuple[str, str]:
    """ENV/LABEL value: `KEY=VALUE` or the Dockerfile space form `KEY value`.
    A lone key with neither separator is a build error, not a silent empty."""
    rest = rest.strip()
    if not rest:
        raise InvalidArgument(f"{op} wants KEY=VALUE or KEY value")
    if "=" in rest.split(None, 1)[0]:
        k, _, v = rest.partition("=")
        return k.strip(), v.strip()
    k, _, v = rest.partition(" ")
    if not v.strip():
        raise InvalidArgument(f"{op} wants KEY=VALUE or KEY value: {rest!r}")
    return k.strip(), v.strip()


def _parse_exec_form(rest: str) -> list[str]:
    rest = rest.strip()
    if rest.startswith("["):
        try:
            parsed = json.loads(rest)
        except json.JSONDecodeError as e:
            raise InvalidArgument(f"bad exec form {rest!r}: {e}") from e
        return [str(x) for x in parsed]
    return ["/bin/sh", "-c", rest]


def base_of(kukefile_path: str, build_args: dict[str, str] | None = None) -> str:
    """The (substituted) FROM ref, or "" for scratch — the teambuild
    FROM-order walk's input."""
    with open(kukefile_path) as f:
        instrs = parse_kukefile(f.read(), origin=kukefile_path)
    vars_ = dict(build_args or {})
    for ins in instrs:
        if ins.op == "ARG":
            name, _, default = ins.args[0].partition("=")
            vars_.setdefault(name.strip(), default.strip())
        elif ins.op == "FROM":
            ref = _subst(ins.args[0], vars_).strip()
            return "" if ref == "scratch" else ref
    return ""


class ImageBuilder:
    """Builds store images from Kukefiles (standalone, no daemon — like
    kukebuild writing straight into the namespace)."""

    def __init__(self, store: ImageStore):
        self.store = store

    def base_of(self, kukefile_path: str,
                build_args: dict[str, str] | None = None) -> str:
        return base_of(kukefile_path, build_args)

    def build(self, kukefile_path: str, context_dir: str, tag: str,
              build_args: dict[str, str] | None = None) -> ImageManifest:
        """Build, with Docker-style multi-stage support: ``FROM x AS name``
        starts a new stage; ``COPY --from=<name|idx> src dst`` copies out of
        an earlier stage's rootfs; only the LAST stage commits to the store
        (builder stages are scratch space, as in BuildKit)."""
        with open(kukefile_path) as f:
            instrs = parse_kukefile(f.read(), origin=kukefile_path)

        # Split into stages at each FROM. Docker semantics: ARGs declared
        # BEFORE the first FROM are global — visible to every stage's FROM
        # line (callers' --build-arg values still win).
        stages: list[list[Instruction]] = []
        current: list[Instruction] = []
        global_args: dict[str, str] = {}
        seen_any_from = False
        for ins in instrs:
            if ins.op == "ARG" and not seen_any_from:
                arg_name, _, default = ins.args[0].partition("=")
                global_args.setdefault(arg_name.strip(), default.strip())
            if ins.op == "FROM":
                if seen_any_from:
                    stages.append(current)
                    current = []
                seen_any_from = True
            current.append(ins)
        stages.append(current)

        name, tag_ = split_ref(tag)
        vars_ = {**global_args, **(build_args or {})}
        stage_roots: dict[str, str] = {}
        stage_manifests: dict[str, ImageManifest] = {}
        stagings: list[str] = []
        final: ImageManifest | None = None
        committed = False
        try:
            for idx, stage_instrs in enumerate(stages):
                m = ImageManifest(name=name, tag=tag_)
                staging = self.store.stage(f"{name}:{tag_}")
                stagings.append(staging)
                stage_name = self._run_instructions(
                    m, stage_instrs, staging, context_dir, dict(vars_),
                    kukefile_path, stage_roots, stage_manifests,
                )
                rootfs = os.path.join(staging, "rootfs")
                for key in (str(idx), stage_name):
                    if key:
                        stage_roots[key] = rootfs
                        stage_manifests[key] = m
                final = m
            assert final is not None
            self.store.commit(final, stagings[-1])
            committed = True
        finally:
            # On success the last staging was renamed by commit; on any
            # failure (including a failed commit) every staging still on
            # disk is reaped.
            for s in stagings[:-1] if committed else stagings:
                self.store.abort(s)
        return final

    def _run_instructions(self, m: ImageManifest, instrs: list[Instruction],
                          staging: str, context_dir: str,
                          vars_: dict[str, str], kukefile_path: str,
                          stage_roots: dict[str, str] | None = None,
                          stage_manifests: dict[str, ImageManifest] | None = None,
                          ) -> str | None:
        rootfs = os.path.join(staging, "rootfs")
        stage_roots = stage_roots or {}
        stage_manifests = stage_manifests or {}
        stage_name: str | None = None

        for ins in instrs:
            rest = ins.args[0]
            if ins.op == "ARG":
                arg_name, _, default = rest.partition("=")
                vars_.setdefault(arg_name.strip(), default.strip())
            elif ins.op == "FROM":
                base_ref = _subst(rest, vars_).strip()
                # `FROM x AS name` — record the stage alias.
                as_match = re.match(r"(.*?)\s+AS\s+(\S+)\s*$", base_ref,
                                    re.IGNORECASE)
                if as_match:
                    base_ref, stage_name = as_match.group(1).strip(), as_match.group(2)
                if base_ref in stage_roots:
                    # Docker semantics: FROM <stage> inherits the stage's
                    # config, not just its filesystem.
                    prev = stage_manifests.get(base_ref)
                    if prev is not None:
                        m.parent = prev.parent
                        m.entrypoint = list(prev.entrypoint)
                        m.cmd = list(prev.cmd)
                        m.env = dict(prev.env)
                        m.workdir = prev.workdir
                        m.labels = dict(prev.labels)
                    shutil.rmtree(rootfs, ignore_errors=True)
                    shutil.copytree(stage_roots[base_ref], rootfs, symlinks=True)
                elif base_ref != "scratch":
                    base = self.store.get(base_ref)   # NotFound if missing
                    m.parent = base.ref
                    m.entrypoint = list(base.entrypoint)
                    m.cmd = list(base.cmd)
                    m.env = dict(base.env)
                    m.workdir = base.workdir
                    m.labels = dict(base.labels)
                    shutil.rmtree(rootfs, ignore_errors=True)
                    shutil.copytree(self.store.rootfs(base.ref), rootfs,
                                    symlinks=True)
            elif ins.op == "COPY":
                parts = shlex.split(_subst(rest, vars_))
                src_root = context_dir
                if parts and parts[0].startswith("--from="):
                    stage_key = parts[0][len("--from="):]
                    if stage_key not in stage_roots:
                        raise InvalidArgument(
                            f"COPY --from={stage_key!r}: unknown stage "
                            f"(known: {sorted(stage_roots)})"
                        )
                    src_root = stage_roots[stage_key]
                    parts = parts[1:]
                if len(parts) != 2:
                    raise InvalidArgument(f"COPY wants <src> <dst>: {rest!r}")
                src = naming.resolve_under(src_root, parts[0], "COPY src")
                dst = naming.resolve_under(rootfs, parts[1], "COPY dst")
                if os.path.isdir(src):
                    shutil.copytree(src, dst, dirs_exist_ok=True, symlinks=True)
                else:
                    os.makedirs(os.path.dirname(dst), exist_ok=True)
                    shutil.copy2(src, dst)
            elif ins.op == "ENV":
                k, v = _parse_kv(_subst(rest, vars_), "ENV")
                m.env[k] = v
            elif ins.op == "WORKDIR":
                m.workdir = _subst(rest, vars_).strip()
            elif ins.op == "LABEL":
                k, v = _parse_kv(_subst(rest, vars_), "LABEL")
                m.labels[k] = v
            elif ins.op == "RUN":
                cmd = _parse_exec_form(_subst(rest, vars_))
                env = {**os.environ, **m.env, "KUKEON_BUILD_ROOT": rootfs}
                p = subprocess.run(cmd, cwd=rootfs, env=env,
                                   capture_output=True, text=True,
                                   timeout=600, check=False)
                if p.returncode != 0:
                    raise InvalidArgument(
                        f"RUN {rest!r} failed ({p.returncode}): "
                        f"{(p.stdout + p.stderr).strip()[-500:]}"
                    )
            elif ins.op == "ENTRYPOINT":
                m.entrypoint = _parse_exec_form(_subst(rest, vars_))
            elif ins.op == "CMD":
                m.cmd = _parse_exec_form(_subst(rest, vars_))
        return stage_name
