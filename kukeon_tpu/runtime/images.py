"""Image subsystem: store + Kukefile builder (the kukebuild analog).

Reference seams covered (SURVEY.md §2.1 kukebuild, §2.6 internal/ctr
images): image load/list/get/delete/prune, and a standalone builder that
writes images straight into the store (the reference embeds BuildKit and
writes into containerd's namespace; here the store IS the runtime's image
namespace).

Process-backend image model: an image is a versioned bundle

  <run_path>/images/<encoded name:tag>/
    manifest.json     {name, tag, parent, entrypoint, cmd, env, workdir,
                       labels, createdAt}
    rootfs/           overlay tree the workload sees via KUKEON_IMAGE_*

A container whose spec names an image inherits the image's env/entrypoint/
workdir (spec wins on conflict) and gets KUKEON_IMAGE_ROOTFS pointing at
the bundle tree — full mount-namespace isolation belongs to a containerd
backend; this backend's contract is env + entry + files.

Kukefile grammar (Dockerfile subset, enough for the reference's team image
flow: FROM walk, build args, REGISTRY threading):

  ARG NAME[=default]
  FROM <image[:tag]> | scratch
  COPY <src> <dst>
  ENV KEY=VALUE
  WORKDIR <dir>
  LABEL k=v
  RUN <command...>              # executed with rootfs as cwd
  ENTRYPOINT ["a","b"] | cmd    # exec or shell form
  CMD ["a","b"] | cmd

``${ARG}``/`$ARG` substitution applies to FROM/COPY/ENV/LABEL/WORKDIR
values, matching how the reference threads the REGISTRY build-arg.
"""

from __future__ import annotations

import json
import os
import re
import shlex
import shutil
import subprocess
import time
from dataclasses import dataclass, field

from kukeon_tpu.runtime.errors import InvalidArgument, NotFound

IMAGES_DIR = "images"


def split_ref(ref: str) -> tuple[str, str]:
    """name[:tag] -> (name, tag); tag defaults to latest."""
    if ":" in ref.rsplit("/", 1)[-1]:
        name, _, tag = ref.rpartition(":")
        return name, tag
    return ref, "latest"


def encode_ref(ref: str) -> str:
    name, tag = split_ref(ref)
    return f"{name}:{tag}".replace("/", "_")


@dataclass
class ImageManifest:
    name: str = ""
    tag: str = "latest"
    parent: str = ""                 # FROM ref ("" = scratch)
    entrypoint: list[str] = field(default_factory=list)
    cmd: list[str] = field(default_factory=list)
    env: dict[str, str] = field(default_factory=dict)
    workdir: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    created_at: float = 0.0

    @property
    def ref(self) -> str:
        return f"{self.name}:{self.tag}"

    def to_json(self) -> dict:
        return {
            "name": self.name, "tag": self.tag, "parent": self.parent,
            "entrypoint": self.entrypoint, "cmd": self.cmd, "env": self.env,
            "workdir": self.workdir, "labels": self.labels,
            "createdAt": self.created_at,
        }

    @staticmethod
    def from_json(d: dict) -> "ImageManifest":
        return ImageManifest(
            name=d.get("name", ""), tag=d.get("tag", "latest"),
            parent=d.get("parent", ""),
            entrypoint=list(d.get("entrypoint") or []),
            cmd=list(d.get("cmd") or []),
            env=dict(d.get("env") or {}),
            workdir=d.get("workdir", ""),
            labels=dict(d.get("labels") or {}),
            created_at=d.get("createdAt", 0.0),
        )


class ImageStore:
    def __init__(self, run_path: str):
        self.root = os.path.join(run_path, IMAGES_DIR)

    def _dir(self, ref: str) -> str:
        return os.path.join(self.root, encode_ref(ref))

    def rootfs(self, ref: str) -> str:
        return os.path.join(self._dir(ref), "rootfs")

    def exists(self, ref: str) -> bool:
        return os.path.exists(os.path.join(self._dir(ref), "manifest.json"))

    def get(self, ref: str) -> ImageManifest:
        path = os.path.join(self._dir(ref), "manifest.json")
        if not os.path.exists(path):
            raise NotFound(f"image {ref!r} not found")
        with open(path) as f:
            return ImageManifest.from_json(json.load(f))

    def list(self) -> list[ImageManifest]:
        if not os.path.isdir(self.root):
            return []
        out = []
        for entry in sorted(os.listdir(self.root)):
            path = os.path.join(self.root, entry, "manifest.json")
            if os.path.exists(path):
                with open(path) as f:
                    out.append(ImageManifest.from_json(json.load(f)))
        return out

    def put(self, manifest: ImageManifest) -> str:
        d = self._dir(manifest.ref)
        os.makedirs(os.path.join(d, "rootfs"), exist_ok=True)
        manifest.created_at = manifest.created_at or time.time()
        tmp = os.path.join(d, ".manifest.tmp")
        with open(tmp, "w") as f:
            json.dump(manifest.to_json(), f, indent=2)
        os.replace(tmp, os.path.join(d, "manifest.json"))
        return d

    def delete(self, ref: str) -> None:
        if not self.exists(ref):
            raise NotFound(f"image {ref!r} not found")
        shutil.rmtree(self._dir(ref), ignore_errors=True)

    def prune(self, in_use: set[str]) -> list[str]:
        """Delete images not referenced by any cell spec; returns refs
        removed. Parents of in-use images are kept (FROM chains stay
        rebuildable)."""
        keep = set()
        for ref in in_use:
            cur = ref
            while cur and cur not in keep:
                keep.add(cur)
                try:
                    cur = self.get(cur).parent
                except NotFound:
                    break
        removed = []
        for m in self.list():
            if m.ref not in keep:
                self.delete(m.ref)
                removed.append(m.ref)
        return removed

    # --- tar import/export (kuke image load / save) -------------------------

    # The metadata tar member lives under rootfs/ in the archive layout:
    # `rootfs/...` entries are the filesystem, this sibling member is the
    # manifest — so a real /manifest.json INSIDE the image never collides.
    _TAR_META = "kukeon-manifest.json"
    _TAR_ROOTFS = "rootfs"

    def load_tar(self, tar_path: str, ref: str) -> ImageManifest:
        """Import a tarball as an image. Layout: ``rootfs/`` tree + optional
        sibling ``kukeon-manifest.json`` with runtime metadata. A flat tar
        (no rootfs/ prefix) imports as a bare rootfs for convenience."""
        import tarfile

        name, tag = split_ref(ref)
        m = ImageManifest(name=name, tag=tag)
        d = self.put(m)
        rootfs = os.path.join(d, "rootfs")
        with tarfile.open(tar_path) as tf:
            names = tf.getnames()
            structured = any(
                n == self._TAR_ROOTFS or n.startswith(self._TAR_ROOTFS + "/")
                for n in names
            )
            if structured:
                tf.extractall(d, filter="data",
                              members=[mem for mem in tf.getmembers()
                                       if mem.name == self._TAR_ROOTFS
                                       or mem.name.startswith(self._TAR_ROOTFS + "/")])
                meta_member = next(
                    (mem for mem in tf.getmembers()
                     if mem.name == self._TAR_META), None
                )
                if meta_member is not None:
                    meta = json.load(tf.extractfile(meta_member))
                    m.entrypoint = list(meta.get("entrypoint") or [])
                    m.cmd = list(meta.get("cmd") or [])
                    m.env = dict(meta.get("env") or {})
                    m.workdir = meta.get("workdir", "")
                    m.labels = dict(meta.get("labels") or {})
            else:
                tf.extractall(rootfs, filter="data")
        self.put(m)
        return m

    def save_tar(self, ref: str, tar_path: str) -> None:
        import io
        import tarfile

        m = self.get(ref)
        rootfs = self.rootfs(ref)
        with tarfile.open(tar_path, "w") as tf:
            tf.add(rootfs, arcname=self._TAR_ROOTFS)
            meta = json.dumps({
                "entrypoint": m.entrypoint, "cmd": m.cmd, "env": m.env,
                "workdir": m.workdir, "labels": m.labels,
            }).encode()
            info = tarfile.TarInfo(self._TAR_META)
            info.size = len(meta)
            tf.addfile(info, io.BytesIO(meta))


# --- Kukefile ----------------------------------------------------------------


@dataclass
class Instruction:
    op: str
    args: list[str]


_VAR_RE = re.compile(r"\$\{(\w+)\}|\$(\w+)")


def parse_kukefile(text: str, origin: str = "Kukefile") -> list[Instruction]:
    out = []
    continuation = ""
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = continuation + raw.strip()
        continuation = ""
        if not line or line.startswith("#"):
            continue
        if line.endswith("\\"):
            continuation = line[:-1].rstrip() + " "
            continue
        op, _, rest = line.partition(" ")
        op = op.upper()
        if op not in ("ARG", "FROM", "COPY", "ENV", "WORKDIR", "LABEL",
                      "RUN", "ENTRYPOINT", "CMD"):
            raise InvalidArgument(f"{origin}:{lineno}: unknown instruction {op!r}")
        out.append(Instruction(op=op, args=[rest.strip()]))
    if continuation:
        raise InvalidArgument(f"{origin}: dangling line continuation")
    return out


def _subst(value: str, vars_: dict[str, str]) -> str:
    def repl(m):
        key = m.group(1) or m.group(2)
        return vars_.get(key, "")
    return _VAR_RE.sub(repl, value)


def _parse_exec_form(rest: str) -> list[str]:
    rest = rest.strip()
    if rest.startswith("["):
        try:
            parsed = json.loads(rest)
        except json.JSONDecodeError as e:
            raise InvalidArgument(f"bad exec form {rest!r}: {e}") from e
        return [str(x) for x in parsed]
    return ["/bin/sh", "-c", rest]


def base_of(kukefile_path: str, build_args: dict[str, str] | None = None) -> str:
    """The (substituted) FROM ref, or "" for scratch — the teambuild
    FROM-order walk's input."""
    with open(kukefile_path) as f:
        instrs = parse_kukefile(f.read(), origin=kukefile_path)
    vars_ = dict(build_args or {})
    for ins in instrs:
        if ins.op == "ARG":
            name, _, default = ins.args[0].partition("=")
            vars_.setdefault(name.strip(), default.strip())
        elif ins.op == "FROM":
            ref = _subst(ins.args[0], vars_).strip()
            return "" if ref == "scratch" else ref
    return ""


class ImageBuilder:
    """Builds store images from Kukefiles (standalone, no daemon — like
    kukebuild writing straight into the namespace)."""

    def __init__(self, store: ImageStore):
        self.store = store

    def base_of(self, kukefile_path: str,
                build_args: dict[str, str] | None = None) -> str:
        return base_of(kukefile_path, build_args)

    def build(self, kukefile_path: str, context_dir: str, tag: str,
              build_args: dict[str, str] | None = None) -> ImageManifest:
        with open(kukefile_path) as f:
            instrs = parse_kukefile(f.read(), origin=kukefile_path)

        name, tag_ = split_ref(tag)
        m = ImageManifest(name=name, tag=tag_)
        vars_ = dict(build_args or {})
        d = self.store.put(m)
        rootfs = os.path.join(d, "rootfs")
        seen_from = False

        for ins in instrs:
            rest = ins.args[0]
            if ins.op == "ARG":
                arg_name, _, default = rest.partition("=")
                vars_.setdefault(arg_name.strip(), default.strip())
            elif ins.op == "FROM":
                if seen_from:
                    raise InvalidArgument(
                        f"{kukefile_path}: multi-stage builds not supported"
                    )
                seen_from = True
                base_ref = _subst(rest, vars_).strip()
                if base_ref != "scratch":
                    base = self.store.get(base_ref)   # NotFound if missing
                    m.parent = base.ref
                    m.entrypoint = list(base.entrypoint)
                    m.cmd = list(base.cmd)
                    m.env = dict(base.env)
                    m.workdir = base.workdir
                    m.labels = dict(base.labels)
                    shutil.rmtree(rootfs, ignore_errors=True)
                    shutil.copytree(self.store.rootfs(base.ref), rootfs,
                                    symlinks=True)
            elif ins.op == "COPY":
                parts = shlex.split(_subst(rest, vars_))
                if len(parts) != 2:
                    raise InvalidArgument(f"COPY wants <src> <dst>: {rest!r}")
                ctx_abs = os.path.abspath(context_dir)
                src = os.path.abspath(os.path.join(ctx_abs, parts[0]))
                if src != ctx_abs and not src.startswith(ctx_abs + os.sep):
                    raise InvalidArgument(f"COPY src escapes context: {parts[0]!r}")
                dst = os.path.join(rootfs, parts[1].lstrip("/"))
                if os.path.isdir(src):
                    shutil.copytree(src, dst, dirs_exist_ok=True, symlinks=True)
                else:
                    os.makedirs(os.path.dirname(dst), exist_ok=True)
                    shutil.copy2(src, dst)
            elif ins.op == "ENV":
                k, _, v = _subst(rest, vars_).partition("=")
                m.env[k.strip()] = v.strip()
            elif ins.op == "WORKDIR":
                m.workdir = _subst(rest, vars_).strip()
            elif ins.op == "LABEL":
                k, _, v = _subst(rest, vars_).partition("=")
                m.labels[k.strip()] = v.strip()
            elif ins.op == "RUN":
                cmd = _parse_exec_form(_subst(rest, vars_))
                env = {**os.environ, **m.env, "KUKEON_BUILD_ROOT": rootfs}
                p = subprocess.run(cmd, cwd=rootfs, env=env,
                                   capture_output=True, text=True,
                                   timeout=600, check=False)
                if p.returncode != 0:
                    raise InvalidArgument(
                        f"RUN {rest!r} failed ({p.returncode}): "
                        f"{(p.stdout + p.stderr).strip()[-500:]}"
                    )
            elif ins.op == "ENTRYPOINT":
                m.entrypoint = _parse_exec_form(_subst(rest, vars_))
            elif ins.op == "CMD":
                m.cmd = _parse_exec_form(_subst(rest, vars_))

        self.store.put(m)
        return m
