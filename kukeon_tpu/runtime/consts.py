"""Path/label/name constants (reference: internal/consts/consts.go).

The on-disk layout mirrors the resource hierarchy:

  <run_path>/
    instance.json                     # instance pinning
    realms/<realm>/realm.json
    realms/<realm>/secrets/<name>.json
    realms/<realm>/blueprints/<name>.json
    realms/<realm>/configs/<name>.json
    realms/<realm>/volumes/<name>/volume.json + data/
    realms/<realm>/spaces/<space>/space.json
    .../stacks/<stack>/stack.json
    .../cells/<cell>/cell.json
    .../cells/<cell>/containers/<name>/   # logs, tty socket, pidfile
  kukeond.sock                        # daemon socket (next to run path by default)
"""

from __future__ import annotations

import os

DEFAULT_RUN_PATH = "/opt/kukeon-tpu"
DEFAULT_SOCKET_NAME = "kukeond.sock"
DEFAULT_REALM = "default"
SYSTEM_REALM = "kuke-system"
DEFAULT_SPACE = "default"
DEFAULT_STACK = "default"

REALMS_DIR = "realms"
SPACES_DIR = "spaces"
STACKS_DIR = "stacks"
CELLS_DIR = "cells"
CONTAINERS_DIR = "containers"
SECRETS_DIR = "secrets"
# In-cell mount point for staged secrets (reference: ctr/secrets.go:30-60).
SECRETS_MOUNT = "/run/kukeon/secrets"
BLUEPRINTS_DIR = "blueprints"
CONFIGS_DIR = "configs"
VOLUMES_DIR = "volumes"

INSTANCE_FILE = "instance.json"
# Host-port claims by host-network cells (runner enforces uniqueness).
HOST_PORTS_FILE = "host-ports.json"
# In-cell mount point for the setup-status report (repos staging).
SETUP_STATUS_MOUNT = "/run/kukeon/setup-status.json"
# Repo staging: per-clone budget, and how long a failed clone is cached
# before the restart path retries it (keeps a dead remote from stalling
# the reconcile tick for its full timeout on every restart).
REPO_CLONE_TIMEOUT_S = 120
REPO_RETRY_SECONDS = 300.0

# Label keys (team-prune and provenance; reference: *.kukeon.io labels).
LABEL_TEAM = "kukeon.io/team"
LABEL_PROVENANCE_CONFIG = "kukeon.io/config"
LABEL_PROVENANCE_BLUEPRINT = "kukeon.io/blueprint"

# TTY / attach file basenames inside a container dir.
TTY_SOCKET = "tty.sock"
CAPTURE_FILE = "capture.log"
SHIM_LOG = "container.log"
PID_FILE = "pid"
SETUP_STATUS_FILE = "setup-status.json"

# Default subnet pool for space networks (reference: KUKEON_POD_SUBNET_CIDR).
DEFAULT_SUBNET_POOL = "10.88.0.0/16"

# Reconcile defaults (reference: KUKEOND_RECONCILE_INTERVAL = 30s).
DEFAULT_RECONCILE_INTERVAL_S = 30.0
DEFAULT_STOP_GRACE_S = 10.0

# Disk-pressure thresholds (reference: KUKEOND_DISK_PRESSURE_*).
DISK_PRESSURE_WARN_PCT = 85.0
DISK_PRESSURE_BLOCK_PCT = 95.0


def socket_path(run_path: str) -> str:
    return os.path.join(run_path, DEFAULT_SOCKET_NAME)
