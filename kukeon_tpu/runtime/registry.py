"""OCI registry client: `kuke image pull` over the distribution HTTP API.

Reference: the kukebuild module's registry auth (cmd/kukebuild/auth.go:
125-154 — docker-config credential precedence) and internal/ctr/image.go
(pull into the runtime's image namespace). This client speaks the OCI
distribution spec directly — /v2 ping, Bearer token dance, manifest
(+ manifest list) negotiation, config blob, gzip layer blobs applied in
order with OCI whiteout semantics — and commits the result into the
ImageStore as a flattened bundle.

Auth precedence (highest wins), mirroring the reference's resolution:
  1. KUKE_REGISTRY_USER / KUKE_REGISTRY_PASSWORD env,
  2. docker config ($DOCKER_CONFIG/config.json, else ~/.docker/config.json):
     auths.<registry>.auth (base64 user:pass) or username/password fields,
  3. anonymous.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import re
import shutil
import tarfile
import urllib.error
import urllib.parse
import urllib.request

from kukeon_tpu.runtime.errors import InvalidArgument, KukeonError, NotFound
from kukeon_tpu.runtime.images import ImageManifest, ImageStore, split_ref

MT_MANIFEST_LIST = "application/vnd.docker.distribution.manifest.list.v2+json"
MT_OCI_INDEX = "application/vnd.oci.image.index.v1+json"
MT_MANIFEST = "application/vnd.docker.distribution.manifest.v2+json"
MT_OCI_MANIFEST = "application/vnd.oci.image.manifest.v1+json"
_ACCEPT = ", ".join((MT_OCI_MANIFEST, MT_MANIFEST, MT_OCI_INDEX, MT_MANIFEST_LIST))


def parse_image_ref(ref: str) -> tuple[str, str, str]:
    """ref -> (registry, repository, tag). Docker rules: the first path
    component is a registry host when it contains '.' or ':' or is
    'localhost'; bare refs have no registry (and cannot be pulled)."""
    name, tag = split_ref(ref)
    first, _, rest = name.partition("/")
    if rest and ("." in first or ":" in first or first == "localhost"):
        return first, rest, tag
    return "", name, tag


class RegistryAuth:
    """Credential resolution + Bearer token cache for one registry."""

    def __init__(self, registry: str):
        self.registry = registry
        self.basic = self._resolve_basic()
        self.token: str | None = None

    def _resolve_basic(self) -> str | None:
        user = os.environ.get("KUKE_REGISTRY_USER")
        password = os.environ.get("KUKE_REGISTRY_PASSWORD")
        if user and password is not None:
            return base64.b64encode(f"{user}:{password}".encode()).decode()
        cfg_dir = os.environ.get("DOCKER_CONFIG") or os.path.expanduser("~/.docker")
        path = os.path.join(cfg_dir, "config.json")
        try:
            with open(path) as f:
                cfg = json.load(f)
        except (OSError, ValueError):
            return None
        auths = cfg.get("auths") or {}
        entry = (
            auths.get(self.registry)
            or auths.get(f"https://{self.registry}")
            or auths.get(f"http://{self.registry}")
        )
        if not entry:
            return None
        if entry.get("auth"):
            return entry["auth"]
        if entry.get("username") is not None and entry.get("password") is not None:
            return base64.b64encode(
                f"{entry['username']}:{entry['password']}".encode()
            ).decode()
        return None

    def headers(self) -> dict[str, str]:
        if self.token:
            return {"Authorization": f"Bearer {self.token}"}
        if self.basic:
            return {"Authorization": f"Basic {self.basic}"}
        return {}

    def handle_challenge(self, www_authenticate: str) -> bool:
        """Bearer challenge -> fetch a token from the realm (with basic
        creds when we have them). Returns True when a token was obtained."""
        m = re.match(r"\s*Bearer\s+(.*)", www_authenticate, re.IGNORECASE)
        if not m:
            return False
        params = dict(re.findall(r'(\w+)="([^"]*)"', m.group(1)))
        realm = params.get("realm")
        if not realm:
            return False
        q = {k: v for k, v in params.items() if k in ("service", "scope")}
        url = realm + ("?" + urllib.parse.urlencode(q) if q else "")
        req = urllib.request.Request(url)
        if self.basic:
            req.add_header("Authorization", f"Basic {self.basic}")
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                doc = json.load(r)
        except (urllib.error.URLError, ValueError):
            return False
        self.token = doc.get("token") or doc.get("access_token")
        return bool(self.token)


class RegistryClient:
    def __init__(self, registry: str, *, insecure: bool | None = None):
        if not registry:
            raise InvalidArgument(
                "image ref has no registry host (want host[:port]/repo[:tag])"
            )
        self.registry = registry
        # Plain HTTP for localhost registries (the docker daemon's implicit
        # insecure-registry rule); everything else is HTTPS.
        if insecure is None:
            host = registry.split(":")[0]
            insecure = host in ("localhost", "127.0.0.1", "::1")
        self.scheme = "http" if insecure else "https"
        self.auth = RegistryAuth(registry)

    def _url(self, path: str) -> str:
        return f"{self.scheme}://{self.registry}{path}"

    def _request(self, path: str, accept: str | None = None, sink=None,
                 timeout: int = 120, retry_auth: bool = True):
        """One GET with the shared auth/error story. Without ``sink``,
        returns (bytes, headers); with a (seekable) ``sink``, streams the
        body into it and returns (sha256 hexdigest, headers). The 401
        challenge is retried at most once — a registry that rejects its own
        freshly issued tokens must fail cleanly, not recurse."""
        req = urllib.request.Request(self._url(path))
        if accept:
            req.add_header("Accept", accept)
        for k, v in self.auth.headers().items():
            req.add_header(k, v)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                if sink is None:
                    return r.read(), dict(r.headers)
                h = hashlib.sha256()
                while True:
                    chunk = r.read(1 << 20)
                    if not chunk:
                        break
                    h.update(chunk)
                    sink.write(chunk)
                return h.hexdigest(), dict(r.headers)
        except urllib.error.HTTPError as e:
            if e.code == 401 and retry_auth and self.auth.handle_challenge(
                e.headers.get("WWW-Authenticate", "")
            ):
                if sink is not None:
                    sink.seek(0)
                    sink.truncate()
                return self._request(path, accept, sink, timeout,
                                     retry_auth=False)
            if e.code == 404:
                raise NotFound(f"{self.registry}{path}: not found") from None
            raise KukeonError(
                f"registry {self.registry}: GET {path} -> {e.code}"
            ) from None
        except urllib.error.URLError as e:
            raise KukeonError(f"registry {self.registry}: {e.reason}") from None

    def _get(self, path: str, accept: str | None = None) -> tuple[bytes, dict]:
        return self._request(path, accept)

    # --- pull ---------------------------------------------------------------

    def manifest(self, repo: str, reference: str) -> dict:
        data, headers = self._get(
            f"/v2/{repo}/manifests/{reference}", accept=_ACCEPT
        )
        doc = json.loads(data)
        mt = doc.get("mediaType") or headers.get("Content-Type", "")
        if mt in (MT_MANIFEST_LIST, MT_OCI_INDEX) or "manifests" in doc:
            chosen = self._pick_platform(doc.get("manifests") or [])
            return self.manifest(repo, chosen["digest"])
        return doc

    @staticmethod
    def _pick_platform(entries: list[dict]) -> dict:
        import platform

        arch = {"x86_64": "amd64", "aarch64": "arm64"}.get(
            platform.machine(), platform.machine()
        )
        for e in entries:
            p = e.get("platform") or {}
            if p.get("os", "linux") == "linux" and p.get("architecture") == arch:
                return e
        have = sorted({
            f"{(e.get('platform') or {}).get('os', '?')}/"
            f"{(e.get('platform') or {}).get('architecture', '?')}"
            for e in entries
        })
        # Pulling a foreign-arch image "successfully" just moves the failure
        # to an exec-format crash-loop in the cell; fail here, with names.
        raise KukeonError(
            f"no manifest for linux/{arch}; image provides: {have or 'none'}"
        )

    def blob(self, repo: str, digest: str) -> bytes:
        data, _ = self._get(f"/v2/{repo}/blobs/{digest}")
        self._verify_digest(data, digest)
        return data

    @staticmethod
    def _verify_digest(data: bytes, digest: str) -> None:
        algo, _, want = digest.partition(":")
        if algo == "sha256":
            got = hashlib.sha256(data).hexdigest()
            if got != want:
                raise KukeonError(
                    f"blob {digest}: digest mismatch (got sha256:{got})"
                )

    def blob_to_file(self, repo: str, digest: str, out) -> None:
        """Stream a blob to a (seekable) file object with incremental
        digest verification — layer blobs can be multi-GB and the daemon is
        long-lived; buffering them whole would spike RSS per pull."""
        got, _ = self._request(f"/v2/{repo}/blobs/{digest}", sink=out,
                               timeout=300)
        algo, _, want = digest.partition(":")
        if algo == "sha256" and got != want:
            raise KukeonError(
                f"blob {digest}: digest mismatch (got sha256:{got})"
            )

    # --- push ---------------------------------------------------------------

    def _send(self, method: str, path_or_url: str, data=None,
              content_type: str | None = None, timeout: int = 300,
              retry_auth: bool = True, ok_codes: tuple[int, ...] = (),
              max_redirects: int = 5):
        """Non-GET request with the shared auth story. ``data`` may be bytes
        or a seekable file object (streamed, Content-Length from its size).
        Returns (status, headers). HTTP errors whose code is in ``ok_codes``
        are returned instead of raised (HEAD-existence probes).

        3xx responses are followed to their Location (S3-backed registries
        answer blob/manifest PUTs with 307/302 to object storage): same
        method and body — file bodies re-seek to 0 per hop — except 303,
        which per RFC converts to a bodyless GET. Auth is re-derived per
        hop below, so credentials never travel to a cross-host Location.

        Built on http.client, NOT urllib.request: urllib silently replaces
        an explicit Content-Length with Transfer-Encoding: chunked for file
        bodies, and registries (monolithic upload is Content-Length-framed
        in the distribution spec) then read chunk framing as blob bytes."""
        import http.client

        url = (path_or_url if path_or_url.startswith("http")
               else self._url(path_or_url))
        for _hop in range(max_redirects + 1):
            split = urllib.parse.urlsplit(url)
            path = split.path + (f"?{split.query}" if split.query else "")
            headers: dict[str, str] = {}
            if content_type and data is not None:
                headers["Content-Type"] = content_type
            if data is not None and hasattr(data, "seek"):
                data.seek(0, os.SEEK_END)
                headers["Content-Length"] = str(data.tell())
                data.seek(0)
            elif data is not None:
                headers["Content-Length"] = str(len(data))
            # Auth only travels to the registry itself. Registries commonly
            # redirect blob uploads to object storage via an absolute
            # Location; forwarding Basic/Bearer there would hand credentials
            # to a third party (docker-style clients strip auth on
            # cross-host redirects).
            if split.netloc == self.registry:
                headers.update(self.auth.headers())
            conn_cls = (http.client.HTTPSConnection if split.scheme == "https"
                        else http.client.HTTPConnection)
            conn = conn_cls(split.netloc, timeout=timeout)
            try:
                conn.request(method, path, body=data, headers=headers)
                r = conn.getresponse()
                r.read()
                status, rheaders = r.status, dict(r.getheaders())
            except OSError as e:
                raise KukeonError(f"registry {self.registry}: {e}") from None
            finally:
                conn.close()
            if status in (301, 302, 303, 307, 308) and _hop < max_redirects:
                loc = rheaders.get("Location") or rheaders.get("location")
                if loc:
                    url = urllib.parse.urljoin(url, loc)
                    if status == 303:
                        method, data, content_type = "GET", None, None
                    continue
            break
        if status == 401 and retry_auth and self.auth.handle_challenge(
            rheaders.get("WWW-Authenticate", "")
        ):
            return self._send(method, path_or_url, data, content_type,
                              timeout, retry_auth=False, ok_codes=ok_codes,
                              max_redirects=max_redirects)
        if status >= 400 and status not in ok_codes:
            raise KukeonError(
                f"registry {self.registry}: {method} {split.path} -> {status}"
            )
        return status, rheaders

    def blob_exists(self, repo: str, digest: str) -> bool:
        status, _ = self._send("HEAD", f"/v2/{repo}/blobs/{digest}",
                               ok_codes=(404,))
        return status == 200

    def upload_blob(self, repo: str, digest: str, data) -> None:
        """Monolithic blob upload: POST an upload session, PUT the bytes at
        the returned Location with ?digest=. Skips blobs the registry
        already has (cross-push dedup, the registry's content store is
        content-addressed)."""
        if self.blob_exists(repo, digest):
            return
        status, headers = self._send("POST", f"/v2/{repo}/blobs/uploads/",
                                     data=b"")
        loc = headers.get("Location") or headers.get("location")
        if status not in (201, 202) or not loc:
            raise KukeonError(
                f"registry {self.registry}: upload session for {repo} "
                f"refused (status {status}, no Location)"
            )
        loc = urllib.parse.urljoin(self._url("/"), loc)
        sep = "&" if "?" in loc else "?"
        url = loc + sep + urllib.parse.urlencode({"digest": digest})
        status, _ = self._send("PUT", url, data=data,
                               content_type="application/octet-stream")
        if status not in (201, 204):
            raise KukeonError(
                f"registry {self.registry}: blob {digest} PUT -> {status}"
            )

    def put_manifest(self, repo: str, reference: str, body: bytes,
                     media_type: str) -> None:
        status, _ = self._send("PUT", f"/v2/{repo}/manifests/{reference}",
                               data=body, content_type=media_type)
        if status not in (201, 202):
            raise KukeonError(
                f"registry {self.registry}: manifest {repo}:{reference} "
                f"PUT -> {status}"
            )


def push(store: ImageStore, ref: str, *, dest: str | None = None,
         insecure: bool | None = None) -> str:
    """Push a local image to an OCI registry; returns the pushed ref.

    ``dest`` (registry/repo[:tag]) overrides the target; without it the
    image's own ref must name a registry host. The store keeps flattened
    bundles, so the pushed image is a single gzip layer built from the
    rootfs plus a config blob carrying entrypoint/cmd/env/workdir/labels —
    a faithful round-trip through ``pull`` (reference: kukebuild pushes what
    it builds, cmd/kukebuild/auth.go:125-154 resolving the push creds).
    """
    import gzip
    import platform
    import tempfile

    m = store.get(ref)
    target = dest or m.ref
    registry_host, repo, tag = parse_image_ref(target)
    client = RegistryClient(registry_host, insecure=insecure)
    arch = {"x86_64": "amd64", "aarch64": "arm64"}.get(
        platform.machine(), platform.machine()
    )

    def file_sha256(f) -> str:
        f.seek(0)
        h = hashlib.sha256()
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            h.update(chunk)
        return "sha256:" + h.hexdigest()

    with tempfile.TemporaryFile() as plain, tempfile.TemporaryFile() as zipped:
        # Uncompressed tar first: its digest is the diff_id the config
        # must carry (the content-addressed identity of the LAYER, not of
        # the gzip stream around it).
        with tarfile.open(fileobj=plain, mode="w") as tf:
            tf.add(store.rootfs(m.ref), arcname=".")
        diff_id = file_sha256(plain)
        plain.seek(0)
        # mtime=0 keeps the gzip stream (and so the blob digest) stable
        # across re-pushes of identical content.
        with gzip.GzipFile(fileobj=zipped, mode="wb", mtime=0) as gz:
            shutil.copyfileobj(plain, gz)
        layer_digest = file_sha256(zipped)
        zipped.seek(0, os.SEEK_END)
        layer_size = zipped.tell()

        config = {
            "architecture": arch,
            "os": "linux",
            "config": {
                "Entrypoint": list(m.entrypoint),
                "Cmd": list(m.cmd),
                "Env": [f"{k}={v}" for k, v in sorted(m.env.items())],
                "WorkingDir": m.workdir or "",
                "Labels": dict(m.labels),
            },
            "rootfs": {"type": "layers", "diff_ids": [diff_id]},
        }
        cfg_bytes = json.dumps(config, sort_keys=True).encode()
        cfg_digest = "sha256:" + hashlib.sha256(cfg_bytes).hexdigest()

        manifest = json.dumps({
            "schemaVersion": 2,
            "mediaType": MT_OCI_MANIFEST,
            "config": {
                "mediaType": "application/vnd.oci.image.config.v1+json",
                "digest": cfg_digest, "size": len(cfg_bytes),
            },
            "layers": [{
                "mediaType": "application/vnd.oci.image.layer.v1.tar+gzip",
                "digest": layer_digest, "size": layer_size,
            }],
        }).encode()

        client.upload_blob(repo, cfg_digest, cfg_bytes)
        client.upload_blob(repo, layer_digest, zipped)
        client.put_manifest(repo, tag, manifest, MT_OCI_MANIFEST)
    return f"{registry_host}/{repo}:{tag}"


def _apply_layer(rootfs: str, tar_file, media_type: str) -> None:
    """Extract one layer over the rootfs with OCI whiteout semantics:
    `.wh.<name>` deletes <name> from lower layers; `.wh..wh..opq` makes the
    directory opaque (drops all lower content).

    Whiteout targets are clamped under the rootfs (naming.resolve_under) —
    the daemon pulls as root and a hostile layer naming
    ``../../etc/.wh.shadow`` must die loudly, never delete host files. The
    ``data`` extraction filter already rejects escaping paths/symlinks for
    regular members.
    """
    from kukeon_tpu.runtime import naming

    tar_file.seek(0)
    head = tar_file.read(2)
    tar_file.seek(0)
    mode = "r:gz" if (media_type.endswith("gzip") or head == b"\x1f\x8b") else "r:"
    with tarfile.open(fileobj=tar_file, mode=mode) as tf:
        members = tf.getmembers()
        for mem in members:
            name = mem.name.lstrip("./")
            base = os.path.basename(name)
            if base == ".wh..wh..opq":
                target = naming.resolve_under(
                    rootfs, os.path.dirname(name), "layer whiteout")
                if os.path.isdir(target) and not os.path.islink(target):
                    for entry in os.listdir(target):
                        p = os.path.join(target, entry)
                        shutil.rmtree(p) if os.path.isdir(p) and not os.path.islink(p) else os.unlink(p)
                continue
            if base.startswith(".wh."):
                target = naming.resolve_under(
                    rootfs,
                    os.path.join(os.path.dirname(name), base[len(".wh."):]),
                    "layer whiteout",
                )
                if os.path.isdir(target) and not os.path.islink(target):
                    shutil.rmtree(target, ignore_errors=True)
                elif os.path.lexists(target):
                    os.unlink(target)
                continue
        tf.extractall(rootfs, filter="data", members=[
            mem for mem in members
            if not os.path.basename(mem.name).startswith(".wh.")
        ])


def pull(store: ImageStore, ref: str, *, insecure: bool | None = None) -> ImageManifest:
    """Pull ``registry/repo[:tag]`` into the store as a flattened bundle."""
    registry, repo, tag = parse_image_ref(ref)
    client = RegistryClient(registry, insecure=insecure)
    manifest = client.manifest(repo, tag)

    config: dict = {}
    cfg_desc = manifest.get("config") or {}
    if cfg_desc.get("digest"):
        config = json.loads(client.blob(repo, cfg_desc["digest"]))
    cc = config.get("config") or {}

    name = f"{registry}/{repo}"
    m = ImageManifest(
        name=name, tag=tag,
        entrypoint=list(cc.get("Entrypoint") or []),
        cmd=list(cc.get("Cmd") or []),
        env={k: v for k, _, v in
             (e.partition("=") for e in (cc.get("Env") or []))},
        workdir=cc.get("WorkingDir") or "",
        labels=dict(cc.get("Labels") or {}),
    )
    m.labels["kukeon.io/pulled-from"] = registry
    staging = store.stage(m.ref)
    try:
        import tempfile

        rootfs = os.path.join(staging, "rootfs")
        layers = manifest.get("layers") or []
        digests = []
        for layer in layers:
            with tempfile.TemporaryFile(dir=staging) as tmp:
                client.blob_to_file(repo, layer["digest"], tmp)
                _apply_layer(rootfs, tmp, layer.get("mediaType", ""))
            digests.append(layer["digest"])
        m.labels["kukeon.io/layers"] = ",".join(d[-16:] for d in digests)
    except BaseException:
        store.abort(staging)
        raise
    store.commit(m, staging)
    return m
