"""kuke: the CLI (reference: cmd/kuke, 23 verbs).

Verbs: init, daemon (serve/start/stop/kill/restart/status/logs/metrics),
apply,
create, delete, get, run, start, stop, kill, attach, log, purge, refresh,
rollout, status, top, trace, query, alerts, doctor, image, build, team,
uninstall, version, autocomplete.

Workload verbs route to the daemon; read/maintenance verbs "promote" to an
in-process controller when --no-daemon / KUKEON_NO_DAEMON is set (reference
process model: docs/site/architecture/process-model.md). Every knob resolves
flag > env > configuration document > default through the config registry
(kukeon_tpu/runtime/config.py).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

import yaml

from kukeon_tpu import __version__
from kukeon_tpu.runtime import consts
from kukeon_tpu.runtime.client import LocalClient, UnixClient
from kukeon_tpu.runtime.errors import KukeonError


def _parse_kv_args(pairs, flag: str) -> dict[str, str]:
    """KEY=VALUE arg list -> dict, with a usage error (not a traceback) on a
    malformed pair."""
    out = {}
    for kv in pairs or []:
        k, sep, v = kv.partition("=")
        if not sep or not k:
            raise KukeonError(f"{flag} wants KEY=VALUE, got {kv!r}")
        out[k] = v
    return out


def _client_settings():
    """Client-side knob resolution: flag > env > ClientConfiguration doc >
    default (reference: cmd/config precedence; internal/clientconfig)."""
    from kukeon_tpu.runtime import config

    try:
        return config.client_settings()
    except KukeonError as e:
        print(f"warning: {e}", file=sys.stderr)
        return config.Settings()


def _run_path(args) -> str:
    return _client_settings().get("KUKEON_RUN_PATH", args.run_path)


def _client(args):
    s = _client_settings()
    if getattr(args, "no_daemon", False) or s.get("KUKEON_NO_DAEMON"):
        return LocalClient(_run_path(args))
    sock = s.get("KUKEOND_SOCKET", args.socket) or consts.socket_path(_run_path(args))
    return UnixClient(sock)


def _scope(args) -> dict:
    return {
        "realm": getattr(args, "realm", None) or consts.DEFAULT_REALM,
        "space": getattr(args, "space", None) or consts.DEFAULT_SPACE,
        "stack": getattr(args, "stack", None) or consts.DEFAULT_STACK,
    }


def _print(obj, as_json=False):
    if as_json:
        print(json.dumps(obj, indent=2))
    else:
        print(yaml.safe_dump(obj, sort_keys=False, default_flow_style=False).rstrip())


# --- verb implementations ----------------------------------------------------

def cmd_image(args):
    c = _client(args)
    sub = args.image_cmd
    if sub in ("get", "delete", "save", "load") and not args.ref:
        print(f"error: image {sub} needs an image ref", file=sys.stderr)
        return 2
    if sub == "load" and not args.input:
        print("error: image load needs -i/--input <tarball>", file=sys.stderr)
        return 2
    if sub == "save" and not args.output:
        print("error: image save needs -o/--output <tarball>", file=sys.stderr)
        return 2
    if sub == "list":
        rows = c.call("ListImages")
        if args.json:
            _print(rows, True)
        else:
            print(f"{'REF':40} {'PARENT':30} CREATED")
            for m in rows:
                created = time.strftime("%Y-%m-%d %H:%M",
                                        time.localtime(m["createdAt"]))
                print(f"{m['name'] + ':' + m['tag']:40} "
                      f"{m['parent'] or '-':30} {created}")
    elif sub == "get":
        _print(c.call("GetImage", ref=args.ref), args.json)
    elif sub == "delete":
        c.call("DeleteImage", ref=args.ref)
        print(f"image/{args.ref}: deleted")
    elif sub == "prune":
        removed = c.call("PruneImages")
        for r in removed:
            print(f"image/{r}: pruned")
        print(f"{len(removed)} image(s) pruned")
    elif sub == "load":
        m = c.call("LoadImage", tarPath=os.path.abspath(args.input), ref=args.ref)
        print(f"image/{m['name']}:{m['tag']}: loaded")
    elif sub == "pull":
        if not args.ref:
            print("error: image pull needs a registry/repo[:tag] ref", file=sys.stderr)
            return 2
        m = c.call("PullImage", ref=args.ref,
                   insecure=True if args.insecure else None)
        print(f"image/{m['name']}:{m['tag']}: pulled")
    elif sub == "push":
        if not args.ref:
            print("error: image push needs a local image ref", file=sys.stderr)
            return 2
        pushed = c.call("PushImage", ref=args.ref, dest=args.to,
                        insecure=True if args.insecure else None)
        print(f"image/{args.ref}: pushed to {pushed}")
    elif sub == "save":
        c.call("SaveImage", ref=args.ref, tarPath=os.path.abspath(args.output))
        print(f"image/{args.ref}: saved to {args.output}")
    else:
        print(f"unknown image subcommand {sub!r}", file=sys.stderr)
        return 2
    return 0


def cmd_build(args):
    # Standalone like the reference's kukebuild: writes straight into the
    # store, no daemon required.
    from kukeon_tpu.runtime.images import ImageBuilder, ImageStore

    context = os.path.abspath(args.context)
    kukefile = args.file or os.path.join(context, "Kukefile")
    build_args = _parse_kv_args(args.build_arg, "--build-arg")
    builder = ImageBuilder(ImageStore(_run_path(args)))
    m = builder.build(kukefile, context_dir=context, tag=args.tag,
                      build_args=build_args)
    print(f"image/{m.ref}: built")
    return 0


def cmd_team(args):
    from kukeon_tpu.runtime.teams import TeamHost, team_init

    if args.team_cmd != "init":
        print(f"unknown team subcommand {args.team_cmd!r}", file=sys.stderr)
        return 2
    c = None if args.dry_run else _client(args)

    def apply_fn(blob, team, prune):
        return c.call("ApplyDocuments", yaml=blob, team=team, prune=prune)

    builder = None
    pusher = None
    if args.build:
        try:
            from kukeon_tpu.runtime.images import ImageBuilder, ImageStore
        except ImportError:
            print("error: the image builder is not available in this build; "
                  "run team init without --build", file=sys.stderr)
            return 1
        builder = ImageBuilder(ImageStore(_run_path(args)))
    if getattr(args, "push", False):
        from kukeon_tpu.runtime import registry as regmod
        from kukeon_tpu.runtime.images import ImageStore, split_ref

        def pusher(tag, reg):
            _, repo, t = regmod.parse_image_ref(tag)
            return regmod.push(ImageStore(_run_path(args)), tag,
                               dest=f"{reg}/{repo}:{t}")
    res = team_init(
        None if args.dry_run else apply_fn,
        args.file,
        host=TeamHost(),
        dry_run=args.dry_run,
        build=args.build,
        builder=builder,
        pusher=pusher,
    )
    print(f"team {res.project}: source at {res.checkout}")
    if res.built_images:
        for img in res.built_images:
            print(f"  built {img}")
    for img in res.pushed_images:
        print(f"  pushed {img}")
    if res.secret_names:
        print(f"  secrets: {', '.join(res.secret_names)}")
    if args.dry_run and res.rendered:
        from kukeon_tpu.runtime.apply.parser import dump_documents

        print(dump_documents(res.rendered.blueprints + res.rendered.configs))
        return 0
    for r in res.applied:
        print(f"  {r['kind'].lower()}/{r['name']} ({r['scope']}): {r['action']}")
    return 0


def cmd_version(args):
    del args
    print(f"kuke {__version__} (kukeon-tpu)")
    return 0


def cmd_init(args):
    """Host bootstrap: run path, hierarchy, daemon start (reference:
    cmd/kuke/init, init.go:484)."""
    run_path = _run_path(args)
    os.makedirs(run_path, exist_ok=True)
    local = LocalClient(run_path)     # bootstrap happens in the constructor
    del local
    # System group so non-root clients can dial the 0660 socket
    # (reference: internal/sysuser — kuke init provisions `kukeon`).
    from kukeon_tpu.runtime import sysuser

    gid = sysuser.ensure_group()
    if gid is not None:
        sysuser.chown_tree(run_path, gid)
        print(f"Group: {sysuser.GROUP} (gid {gid})")
    print(f"Run path: {run_path}")
    print(f"Realm: {consts.DEFAULT_REALM}")
    print(f"System realm: {consts.SYSTEM_REALM}")
    if not args.no_daemon_start:
        rc = _daemon_start(run_path, args.socket)
        if rc != 0:
            return rc
        print(f"kukeond is ready (unix://{args.socket or consts.socket_path(run_path)})")
    return 0


def _daemon_start(run_path: str, socket_path: str | None) -> int:
    sock = socket_path or consts.socket_path(run_path)
    if os.path.exists(sock):
        try:
            UnixClient(sock).call("Ping")
            print("daemon already running")
            return 0
        except KukeonError:
            pass
    log_path = os.path.join(run_path, "kukeond.log")
    with open(log_path, "a") as log:
        subprocess.Popen(
            [sys.executable, "-m", "kukeon_tpu.runtime.cli", "daemon", "serve",
             "--run-path", run_path, "--socket", sock],
            stdout=log, stderr=log, stdin=subprocess.DEVNULL,
            start_new_session=True,
        )
    deadline = time.monotonic() + 10.0   # reference: e2e daemon budget <=10s
    while time.monotonic() < deadline:
        try:
            UnixClient(sock).call("Ping")
            return 0
        except KukeonError:
            time.sleep(0.1)
    print(f"error: daemon did not come up within 10s (see {log_path})", file=sys.stderr)
    return 1


def cmd_daemon(args):
    run_path = _run_path(args)
    sock = args.socket or consts.socket_path(run_path)
    if args.daemon_cmd == "serve":
        from kukeon_tpu.runtime.daemon import DaemonServer

        # Socket + interval resolution (flag > env > ServerConfiguration
        # doc > default) happens inside DaemonServer via the config registry.
        DaemonServer(run_path, args.socket).serve()
        return 0
    if args.daemon_cmd == "start":
        return _daemon_start(run_path, args.socket)
    if args.daemon_cmd in ("stop", "kill"):
        pid_file = os.path.join(run_path, "kukeond.pid")
        try:
            pid = int(open(pid_file).read().strip())
        except (OSError, ValueError):
            print("daemon not running (no pid file)")
            return 0
        sig = signal.SIGTERM if args.daemon_cmd == "stop" else signal.SIGKILL
        try:
            os.kill(pid, sig)
        except ProcessLookupError:
            print("daemon not running (stale pid)")
            return 0
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            try:
                os.kill(pid, 0)
                time.sleep(0.1)
            except ProcessLookupError:
                break
        else:
            os.kill(pid, signal.SIGKILL)
        print("daemon stopped")
        return 0
    if args.daemon_cmd == "status":
        try:
            _print(UnixClient(sock).call("Status"), args.json)
            return 0
        except KukeonError as e:
            print(f"daemon unreachable: {e}", file=sys.stderr)
            return 1
    if args.daemon_cmd == "metrics":
        # Prometheus text straight from the daemon's registry: cell
        # lifecycle (starts/restarts/exit codes/backoff/uptime), reconcile
        # loop, RPC traffic, fault-injection fire counts.
        try:
            out = UnixClient(sock).call("Metrics")
        except KukeonError as e:
            print(f"daemon unreachable: {e}", file=sys.stderr)
            return 1
        print(out["text"], end="")
        return 0
    if args.daemon_cmd == "logs":
        log_path = os.path.join(run_path, "kukeond.log")
        return _tail(log_path, follow=args.follow)
    if args.daemon_cmd == "restart":
        args.daemon_cmd = "stop"
        cmd_daemon(args)
        return _daemon_start(run_path, args.socket)
    print(f"unknown daemon subcommand {args.daemon_cmd!r}", file=sys.stderr)
    return 2


def cmd_apply(args):
    blob = sys.stdin.read() if args.file == "-" else open(args.file).read()
    c = _client(args)
    results = c.call("ApplyDocuments", yaml=blob, team=args.team, prune=args.prune)
    for r in results:
        print(f"{r['kind'].lower()}/{r['name']} ({r['scope']}): {r['action']}")
    return 0


def cmd_delete(args):
    c = _client(args)
    if args.file:
        blob = sys.stdin.read() if args.file == "-" else open(args.file).read()
        for r in c.call("DeleteDocuments", yaml=blob):
            print(f"{r['kind'].lower()}/{r['name']} ({r['scope']}): {r['action']}")
        return 0
    kind, name = args.kind, args.name
    s = _scope(args)
    if kind in ("cell", "cells"):
        c.call("DeleteCell", **s, name=name, force=args.force)
    elif kind in ("realm", "realms"):
        c.call("DeleteRealm", name=name, purge=args.force)
    elif kind in ("space", "spaces"):
        c.call("DeleteSpace", realm=s["realm"], name=name, purge=args.force)
    elif kind in ("stack", "stacks"):
        c.call("DeleteStack", realm=s["realm"], space=s["space"], name=name, purge=args.force)
    elif kind in ("secret", "secrets"):
        c.call("DeleteSecret", realm=s["realm"], space=args.space, stack=args.stack, name=name)
    elif kind in ("blueprint", "blueprints", "cellblueprint"):
        c.call("DeleteBlueprint", realm=s["realm"], space=args.space, stack=args.stack, name=name)
    elif kind in ("config", "configs", "cellconfig"):
        c.call("DeleteConfig", realm=s["realm"], space=args.space, stack=args.stack, name=name)
    elif kind in ("volume", "volumes"):
        c.call("DeleteVolume", realm=s["realm"], space=args.space, stack=args.stack, name=name)
    else:
        print(f"unknown kind {kind!r}", file=sys.stderr)
        return 2
    print(f"{kind}/{name}: deleted")
    return 0


def cmd_create(args):
    """Imperative create (reference: cmd/kuke/create — realm, space, stack,
    cell, secret, volume by name or any kind via -f)."""
    c = _client(args)
    s = _scope(args)
    if args.file:
        blob = sys.stdin.read() if args.file == "-" else open(args.file).read()
        results = c.call("ApplyDocuments", yaml=blob)
        for r in results:
            print(f"{r['kind'].lower()}/{r['name']} ({r['scope']}): {r['action']}")
        return 0
    kind, name = args.kind, args.name
    if not kind or not name:
        print("error: kuke create wants -f FILE or KIND NAME", file=sys.stderr)
        return 2
    if kind in ("realm", "realms"):
        c.call("CreateRealm", name=name)
    elif kind in ("space", "spaces"):
        c.call("CreateSpace", realm=s["realm"], name=name)
    elif kind in ("stack", "stacks"):
        c.call("CreateStack", realm=s["realm"], space=s["space"], name=name)
    elif kind in ("cell", "cells"):
        main = {"name": "main"}
        if args.image:
            main["image"] = args.image
        if args.command:
            main["command"] = args.command
        doc = {
            "apiVersion": "kukeon.io/v1beta1", "kind": "Cell",
            "metadata": {"name": name, **{k: v for k, v in s.items() if v}},
            "spec": {"containers": [main]},
        }
        rec = c.call("CreateCell", doc=doc, start=not args.no_start)
        print(f"cell/{name}: {rec['status']['phase']}")
        return 0
    elif kind in ("secret", "secrets"):
        data = _parse_kv_args(args.data, "--data")
        if not data:
            print("error: kuke create secret wants --data KEY=VALUE", file=sys.stderr)
            return 2
        blob = yaml.safe_dump({
            "apiVersion": "kukeon.io/v1beta1", "kind": "Secret",
            "metadata": {"name": name, "realm": s["realm"]},
            "spec": {"data": data},
        })
        c.call("ApplyDocuments", yaml=blob)
    elif kind in ("volume", "volumes"):
        blob = yaml.safe_dump({
            "apiVersion": "kukeon.io/v1beta1", "kind": "Volume",
            "metadata": {"name": name, "realm": s["realm"]},
            "spec": {"reclaimPolicy": args.reclaim_policy},
        })
        c.call("ApplyDocuments", yaml=blob)
    else:
        print(f"unknown kind {kind!r}", file=sys.stderr)
        return 2
    print(f"{kind}/{name}: created")
    return 0


def cmd_get(args):
    c = _client(args)
    s = _scope(args)
    kind = args.kind
    if kind in ("realms", "realm"):
        if args.name:
            _print(c.call("GetRealm", name=args.name), args.json)
        else:
            for r in c.call("ListRealms"):
                print(r)
    elif kind in ("spaces", "space"):
        if args.name:
            _print(c.call("GetSpace", realm=s["realm"], name=args.name), args.json)
        else:
            for x in c.call("ListSpaces", realm=s["realm"]):
                print(x)
    elif kind in ("stacks", "stack"):
        if args.name:
            _print(c.call("GetStack", realm=s["realm"], space=s["space"], name=args.name), args.json)
        else:
            for x in c.call("ListStacks", realm=s["realm"], space=s["space"]):
                print(x)
    elif kind in ("cells", "cell"):
        if args.name:
            _print(c.call("GetCell", **s, name=args.name), args.json)
        else:
            rows = c.call("ListCells", realm=s["realm"],
                          space=getattr(args, "space", None),
                          stack=getattr(args, "stack", None))
            if args.json:
                _print(rows, True)
            else:
                fmt = "{:<24} {:<10} {:<28} {:<9} {:<10} {}"
                print(fmt.format("NAME", "PHASE", "SCOPE", "CHIPS", "SYNC", "CONTAINERS"))
                for r in rows:
                    scope = f"{r['realm']}/{r['space']}/{r['stack']}"
                    chips = ",".join(map(str, r["status"].get("tpuChips", []))) or "-"
                    st = r["status"]
                    # SYNC column mirrors the reference's three-way verdict:
                    # config-lineage cells show Synced/OutOfSync/Error, others "-".
                    if st.get("outOfSyncError"):
                        sync = "Error"
                    elif st.get("outOfSync"):
                        sync = "OutOfSync"
                    elif (r.get("provenance") or {}).get("config"):
                        sync = "Synced"
                    else:
                        sync = "-"
                    conts = ",".join(
                        f"{cs['name']}:{cs['state']}"
                        + (f"(x{cs['restarts']})" if cs.get("restarts") else "")
                        for cs in st["containers"]
                    )
                    print(fmt.format(r["name"], st["phase"], scope, chips, sync, conts))
    elif kind in ("secrets", "secret"):
        for x in c.call("ListSecrets", realm=s["realm"], space=args.space, stack=args.stack):
            print(x)
    elif kind in ("blueprints", "blueprint", "cellblueprints"):
        for x in c.call("ListBlueprints", realm=s["realm"], space=args.space, stack=args.stack):
            print(x)
    elif kind in ("configs", "config", "cellconfigs"):
        for x in c.call("ListConfigs", realm=s["realm"], space=args.space, stack=args.stack):
            print(x)
    elif kind in ("volumes", "volume"):
        for x in c.call("ListVolumes", realm=s["realm"], space=args.space, stack=args.stack):
            print(x)
    else:
        print(f"unknown kind {kind!r}", file=sys.stderr)
        return 2
    return 0


def cmd_lifecycle(args):
    c = _client(args)
    s = _scope(args)
    out = c.call(args.verb.capitalize() + "Cell", **s, name=args.name)
    print(f"cell/{args.name}: {out['status']['phase']}")
    return 0


def cmd_run(args):
    """Create-or-attach state machine (reference: cmd/kuke/run)."""
    c = _client(args)
    s = _scope(args)
    name = args.name

    if args.from_blueprint:
        values = _parse_kv_args(args.param, "--param")
        rec = c.call("RunBlueprint", realm=s["realm"], space=s["space"], stack=s["stack"],
                     blueprint=args.from_blueprint, values=values)
        name = rec["name"]
    elif args.from_config:
        rec = c.call("MaterializeConfig", realm=s["realm"], space=s["space"],
                     stack=s["stack"], name=args.from_config)
        name = rec["name"]
    elif args.file:
        blob = sys.stdin.read() if args.file == "-" else open(args.file).read()
        docs = list(yaml.safe_load_all(blob))
        cells = [d for d in docs if d and d.get("kind") == "Cell"]
        if len(cells) != 1:
            print("error: kuke run -f needs exactly one Cell document", file=sys.stderr)
            return 2
        doc = cells[0]
        if args.rm:
            doc.setdefault("spec", {})["autoDelete"] = True
        name = doc.get("metadata", {}).get("name")
        md = doc.get("metadata", {})
        s = {"realm": md.get("realm") or s["realm"], "space": md.get("space") or s["space"],
             "stack": md.get("stack") or s["stack"]}
        try:
            existing = c.call("GetCell", **s, name=name)
        except KukeonError:
            existing = None
        if existing is None:
            rec = c.call("CreateCell", doc=doc)
        elif existing["status"]["phase"] in ("stopped", "failed"):
            rec = c.call("StartCell", **s, name=name)
        else:
            rec = existing
    elif name:
        try:
            rec = c.call("GetCell", **s, name=name)
            if rec["status"]["phase"] in ("stopped", "failed"):
                rec = c.call("StartCell", **s, name=name)
        except KukeonError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
    else:
        print("error: kuke run needs a cell name, -f, -b, or -c", file=sys.stderr)
        return 2

    print(f"cell/{name}: {rec['status']['phase']}")
    if args.detach:
        return 0
    return _attach(c, s, name, args.container)


def cmd_attach(args):
    c = _client(args)
    s = _scope(args)
    return _attach(c, s, args.name, args.container)


def _attach(c, s, name, container) -> int:
    from kukeon_tpu.runtime.attach import run_attach

    info = c.call("AttachContainer", realm=s["realm"], space=s["space"],
                  stack=s["stack"], cell=name, container=container)
    return run_attach(info["socketPath"])


def cmd_log(args):
    c = _client(args)
    s = _scope(args)
    info = c.call("Log", realm=s["realm"], space=s["space"], stack=s["stack"],
                  cell=args.name, container=args.container)
    return _tail(info["path"], follow=args.follow)


def _tail(path: str, follow: bool = False) -> int:
    if not os.path.exists(path):
        print(f"(no log yet at {path})", file=sys.stderr)
        if not follow:
            return 1
    pos = 0
    try:
        while True:
            if os.path.exists(path):
                with open(path, "rb") as f:
                    f.seek(pos)
                    chunk = f.read()
                    pos = f.tell()
                if chunk:
                    sys.stdout.buffer.write(chunk)
                    sys.stdout.flush()
            if not follow:
                return 0
            time.sleep(1.0)   # reference: 1s poll (log.go:63-84)
    except KeyboardInterrupt:
        return 0


def cmd_status(args):
    try:
        c = _client(args)
        t0 = time.monotonic()
        ping = c.call("Ping")
        rtt_ms = (time.monotonic() - t0) * 1000
        status = c.call("Status")
        status["daemon"] = {"pid": ping["pid"], "rttMs": round(rtt_ms, 2),
                            "uptimeSeconds": round(ping["uptimeSeconds"], 1)}
        _print(status, args.json)
        return 0
    except KukeonError as e:
        print(f"daemon: unreachable ({e})", file=sys.stderr)
        return 1


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    for unit in ("B", "K", "M", "G", "T"):
        if abs(n) < 1024 or unit == "T":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}T"


def _fmt_ms(s) -> str:
    return "-" if s is None else f"{s * 1000:.0f}ms"


def render_top(rows, sparks=None) -> str:
    """The `kuke top` table as a string (pure so tests and the --watch
    repaint share it). ``sparks`` is {cell: {qps/p95/queue: [values]}}
    from the TSDB's range queries; when present each cell row grows a
    history line of sparklines drawn from the daemon's own scrape
    history rather than a single instantaneous scrape."""
    from kukeon_tpu.obs.tsdb import sparkline

    if not rows:
        return "no running model cells"
    # Staleness dimming: a row whose last GOOD scrape (ScrapeCells'
    # scrapeAgeS, from the daemon's kukeon_cell_scrape_age_seconds
    # bookkeeping) is older than 2 scrape intervals renders ANSI-dim —
    # its numbers are last-known-good, not current. Env name mirrors
    # daemon.SCRAPE_INTERVAL_ENV (not imported: the daemon module drags
    # in the whole controller stack).
    stale_after_s = 2 * float(
        os.environ.get("KUKEON_SCRAPE_INTERVAL_S", "") or 10.0)
    lines = []
    fmt = "{:<32} {:<8} {:<6} {:>7} {:>8} {:>8} {:>6} {:>14} {:>9}"
    lines.append(fmt.format("CELL", "MODEL", "READY", "QPS", "P50TTFT",
                            "P95TTFT", "QUEUE", "HBM", "RESTARTS"))
    for r in rows:
        if (r.get("scrapeAgeS") or 0.0) > stale_after_s:
            add = lambda ln: lines.append(f"\x1b[2m{ln}\x1b[0m")  # noqa: E731
        else:
            add = lines.append
        if not r.get("ok"):
            add(fmt.format(r["cell"], "-", "down", "-", "-", "-",
                           "-", "-", r.get("restarts", 0))
                + f"  ({r.get('error', 'scrape failed')})")
            continue
        if r.get("kind") == "gateway":
            # Gateway row: the replicated cell's front door. READY is the
            # replica census, QPS the aggregate over replicas; latency/HBM
            # live on the per-replica rows beneath it.
            ready = (f"{r.get('readyReplicas', 0)}/{r.get('replicas', '?')}")
            extra = f"  (gateway, retries={r.get('retries', 0)}"
            if r.get("scale"):
                # Autoscaled cell: the FleetScaler's current target and
                # the declared bounds.
                sc = r["scale"]
                extra += (f", scale={sc.get('desired', '?')}"
                          f"[{sc.get('min', 1)}..{sc.get('max', '?')}]")
            if r.get("handoffs"):
                # Disaggregated fleet: how many prefill->decode KV
                # handoffs this gateway drove, at what median cost.
                extra += (f", handoffs={r['handoffs']}"
                          + (f" p50={r['handoffMsP50']}ms"
                             if r.get("handoffMsP50") is not None else "")
                          + (f" fallbacks={r['handoffFallbacks']}"
                             if r.get("handoffFallbacks") else ""))
            add(fmt.format(
                r["cell"], r.get("model") or "-", ready,
                f"{r['qps']:.1f}" if r.get("qps") is not None else "-",
                "-", "-", "-", "-", r.get("restarts", 0))
                + extra + ")")
            continue
        hbm = "-"
        if r.get("hbmInUseBytes") is not None:
            hbm = (f"{_fmt_bytes(r['hbmInUseBytes'])}"
                   f"/{_fmt_bytes(r.get('hbmLimitBytes'))}")
        # The TTFT histogram's top-bucket exemplar: the p95 row links
        # directly to a reconstructable trace (`kuke trace <id>`).
        exemplar = (f"  (p95 trace={r['ttftP95TraceId']})"
                    if r.get("ttftP95TraceId") else "")
        add(fmt.format(
            r["cell"], r.get("model") or "-",
            "yes" if r.get("ready") else "no",
            f"{r['qps']:.1f}" if r.get("qps") is not None else "-",
            _fmt_ms(r.get("ttftP50S")), _fmt_ms(r.get("ttftP95S")),
            r.get("queueDepth", "-"), hbm, r.get("restarts", 0))
            + exemplar)
        if r.get("meshChips", 1) > 1 and r.get("hbmPerDevice"):
            # Sharded cell: one line per chip of the serving mesh. The
            # aggregate HBM cell above hides shard skew — a single chip
            # near its limit OOMs the whole mesh, so show each one with
            # its high-water mark.
            for dev, h in r["hbmPerDevice"].items():
                add(
                    f"  chip {dev}: hbm {_fmt_bytes(h.get('inUse'))}"
                    f"/{_fmt_bytes(h.get('limit'))}"
                    f" peak {_fmt_bytes(h.get('peak'))}")
        sp = (sparks or {}).get(r["cell"])
        if sp:
            add("  {:<30} qps {:<12} p95 {:<12} queue {:<12}".format(
                "history:", sparkline(sp.get("qps", ()), 10),
                sparkline(sp.get("p95", ()), 10),
                sparkline(sp.get("queue", ()), 10)).rstrip())
    return "\n".join(lines)


def _top_sparklines(c) -> dict:
    """Three range queries against the daemon's TSDB -> per-cell value
    lists for the --watch history columns (QPS summed over outcome
    series). A daemon without history yet (or an old one without the
    Query RPC) simply yields no sparklines."""
    out: dict[str, dict[str, list]] = {}
    specs = (("qps", "kukeon_engine_requests_total", "rate"),
             ("p95", "kukeon_engine_ttft_seconds", "p95"),
             ("queue", "kukeon_engine_queue_depth", "avg"))
    for col, family, agg in specs:
        try:
            res = c.call("Query", expr=family, windowS="5m", agg=agg,
                         stepS="30s")
        except KukeonError:
            continue
        for row in res.get("range", []):
            cell = row["labels"].get("cell")
            if not cell:
                continue
            vals = row["values"]
            slot = out.setdefault(cell, {})
            prev = slot.get(col)
            if prev is None:
                slot[col] = list(vals)
            else:
                # requests_total carries an outcome label: sum the
                # per-outcome rate series into one QPS line.
                slot[col] = [
                    None if (a is None and b is None)
                    else (a or 0) + (b or 0)
                    for a, b in zip(prev, vals)]
    return out


def cmd_top(args):
    """One-screen fleet view from a single federated scrape: the daemon
    pulls every running model cell's /metrics (ScrapeCells) and this
    renders the per-cell table — ready, QPS, TTFT p50/p95, queue depth,
    HBM, restarts. Unreachable cells show their scrape error instead of
    silently vanishing. ``--watch`` repaints in place and adds per-cell
    sparkline history (QPS, TTFT p95, queue depth) from the daemon's
    in-memory scrape history instead of a single scrape."""
    watch = getattr(args, "watch", False)
    interval = getattr(args, "interval", None) or 5.0
    c = _client(args)
    try:
        while True:
            try:
                out = c.call("ScrapeCells")
            except KukeonError as e:
                print(f"daemon unreachable: {e}", file=sys.stderr)
                return 1
            rows = out.get("cells", [])
            if args.json:
                _print(rows, True)
                return 0
            sparks = _top_sparklines(c) if (watch and rows) else None
            body = render_top(rows, sparks)
            if watch:
                sys.stdout.write("\x1b[H\x1b[2J")
                print(time.strftime("%H:%M:%S")
                      + f" — kuke top (every {interval:g}s, history = last"
                        " 5m; ctrl-c to exit)")
            print(body)
            if not watch:
                return 0
            sys.stdout.flush()
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0


def _fmt_label_set(labels: dict) -> str:
    if not labels:
        return "(no labels)"
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) + "}"


def cmd_query(args):
    """Windowed query against the daemon's in-memory scrape history
    (obs/tsdb.py): `kuke query 'kukeon_engine_ttft_seconds{cell=...}'
    --window 5m --agg p95`. One row per matching series; --step adds a
    sparkline of per-step values over the window."""
    out = _client(args).call("Query", expr=args.expr, windowS=args.window,
                             agg=args.agg, stepS=args.step)
    if args.json:
        _print(out, True)
        return 0
    series = out.get("series", [])
    if not series:
        print(f"no data for {args.expr!r} over the last {args.window} "
              "(series outside retention, or the daemon has no history "
              "yet)")
        return 1
    from kukeon_tpu.obs.tsdb import sparkline
    rng = {json.dumps(r["labels"], sort_keys=True): r["values"]
           for r in out.get("range", [])}
    width = max(len(_fmt_label_set(s["labels"])) for s in series)
    width = max(width, len("SERIES"))
    print(f"{'SERIES':<{width}}  {args.agg.upper():>12}"
          + ("  TREND" if rng else ""))
    for s in sorted(series, key=lambda s: _fmt_label_set(s["labels"])):
        line = (f"{_fmt_label_set(s['labels']):<{width}}  "
                f"{s['value']:>12.6g}")
        vals = rng.get(json.dumps(s["labels"], sort_keys=True))
        if vals:
            line += "  " + sparkline(vals)
        print(line)
    return 0


def cmd_alerts(args):
    """The alert engine's live state (one row per rule, plus one per
    active labelset) and its recent firing/resolved transitions — the
    operator view of kukeon_alerts_firing. ``--check`` turns it into a
    health gate for CI and cron: exit 1 while any rule is firing, 2 when
    the user rule file is broken (rulesError), 0 on a quiet fleet."""
    out = _client(args).call("Alerts",
                             transitions=getattr(args, "transitions", 50))
    check = getattr(args, "check", False)
    if args.json:
        _print(out, True)
        if check:
            if any(r["state"] == "firing" for r in out.get("alerts", [])):
                return 1
            return 2 if out.get("rulesError") else 0
        return 0
    if out.get("rulesError"):
        print(f"warning: KUKEON_ALERT_RULES ignored: {out['rulesError']}",
              file=sys.stderr)
    fmt = "{:<24} {:<9} {:<8} {:>12} {:>8} {}"
    print(fmt.format("ALERT", "SEVERITY", "STATE", "VALUE", "FOR",
                     "LABELS"))
    now = time.time()
    for r in out.get("alerts", []):
        state = r["state"]
        value = f"{r['value']:.4g}" if r.get("value") is not None else "-"
        dur = (f"{max(0.0, now - r['since']):.0f}s"
               if state != "ok" and r.get("since") is not None else "-")
        labels = (_fmt_label_set(r["labels"]) if r.get("labels") else "-")
        print(fmt.format(r["alert"], r["severity"], state, value, dur,
                         labels))
    trs = out.get("transitions", [])
    if trs:
        print("\nrecent transitions:")
        for tr in trs[-10:]:
            ts = time.strftime("%H:%M:%S", time.localtime(tr["at"]))
            extra = f" cell={tr['cell']}" if tr.get("cell") else ""
            if tr.get("trace_id"):
                extra += f" trace={tr['trace_id']}"
            print(f"  {ts} {tr['alert']} -> {tr['state']} "
                  f"(value {tr['value']:.4g} vs {tr['threshold']:.4g})"
                  f"{extra}")
    if check:
        firing = [r["alert"] for r in out.get("alerts", [])
                  if r["state"] == "firing"]
        if firing:
            print(f"\ncheck: {len(firing)} rule(s) firing: "
                  + ", ".join(sorted(set(firing))), file=sys.stderr)
            return 1
        if out.get("rulesError"):
            # Nothing firing, but the operator's rule file is broken —
            # the gate cannot vouch for rules that never loaded.
            return 2
        print("\ncheck: fleet healthy (nothing firing)")
    return 0


def _span_detail(span: dict) -> str:
    """One span's human detail column: replica attempts and retry hops for
    gateway spans, token counts for engine spans, error text for failures."""
    bits: list[str] = []
    hops = [e for e in span.get("events", [])
            if e.get("event") in ("proxy_attempt", "proxy_retry")]
    if hops:
        parts = []
        for e in hops:
            a = e.get("attrs") or {}
            if e["event"] == "proxy_attempt":
                parts.append(a.get("replica", "?"))
            else:
                parts[-1:] = [f"{parts[-1] if parts else '?'}"
                              f"!{a.get('reason', 'retry')}"]
        bits.append("attempts " + " -> ".join(parts))
    for e in span.get("events", []):
        # The disaggregated KV handoff hop: which prefill cell fed which
        # decode cell, and what the transfer moved.
        if e.get("event") == "kv_handoff":
            a = e.get("attrs") or {}
            bits.append(f"handoff {a.get('prefill', '?')}->"
                        f"{a.get('decode', '?')} "
                        f"{a.get('pages', '?')}p/{a.get('bytes', '?')}B")
        elif e.get("event") == "handoff_fallback":
            a = e.get("attrs") or {}
            bits.append(f"handoff fallback (stage {a.get('stage', '?')})")
    if span.get("tokens"):
        bits.append(f"{span['tokens']} tokens")
    if span.get("attrs", {}).get("retries"):
        bits.append(f"retries={span['attrs']['retries']}")
    if span.get("error"):
        bits.append(span["error"])
    return "; ".join(bits)


def render_trace(trace_id: str, spans: list[dict]) -> str:
    """The reconstructed cross-component timeline for one trace: every
    span (gateway proxy, each replica attempt's engine span, boot spans)
    on one time axis, children indented under their parent span, with
    stage, cell, phase durations, retry hops, and outcome. Pure so tests
    drive it without a daemon."""
    if not spans:
        return f"trace {trace_id}: no spans found"
    base = min(s.get("startedAt") or 0.0 for s in spans)
    by_id = {s.get("spanId"): s for s in spans}

    def depth(s: dict) -> int:
        d, seen = 0, set()
        while s.get("parentSpanId") in by_id and s["spanId"] not in seen:
            seen.add(s["spanId"])
            s = by_id[s["parentSpanId"]]
            d += 1
        return d

    lines = [f"trace {trace_id} — {len(spans)} span(s)"]
    for s in sorted(spans, key=lambda x: (x.get("startedAt") or 0.0)):
        indent = "  " * (1 + depth(s))
        offset = (s.get("startedAt") or base) - base
        phases = " | ".join(
            f"{k} {v * 1000:.1f}ms" for k, v in (s.get("phasesS") or
                                                 {}).items() if v)
        detail = _span_detail(s)
        lines.append(
            f"{indent}+{offset:7.3f}s {s.get('component', '?'):<8}"
            f" {s.get('cell', '-'):<28}"
            f" {s.get('outcome') or '?':<9}"
            f" e2e {(s.get('e2eS') or 0) * 1000:8.1f}ms"
            + (f"  [{phases}]" if phases else "")
            + (f"  {detail}" if detail else ""))
    return "\n".join(lines)


def cmd_trace(args):
    """Render one distributed trace end to end: the daemon unions every
    model cell's /v1/trace ring (gateway + all replicas) for this trace id
    and this prints the reconstructed timeline — which replica(s) a
    request hit, every retry hop, and how the engine phases partition the
    request's wall time."""
    try:
        out = _client(args).call("Traces", traceId=args.trace_id)
    except KukeonError as e:
        print(f"daemon unreachable: {e}", file=sys.stderr)
        return 1
    spans = out.get("spans", [])
    if args.json:
        _print(spans, True)
        return 0
    print(render_trace(args.trace_id, spans))
    return 0 if spans else 1


def render_timeline(steps: list[dict]) -> str:
    """The engine-step flight recorder as a table: one line per recorded
    engine-loop step — wall time, batch occupancy, decode chunk size,
    tokens emitted, host transfers, preemptions, the per-program wall
    split, and the trace ids seated that step (each resolvable via
    `kuke trace <id>`). Pure so tests drive it without a daemon."""
    if not steps:
        return ("no recorded engine steps "
                "(cell idle, or no flight recorder)")
    base = min(s.get("t") or 0.0 for s in steps)
    fmt = "{:>9} {:>5} {:>9} {:>5} {:>5} {:>6} {:>5} {:>4} {:>5}"
    lines = [fmt.format("+T", "SEQ", "WALL", "OCC", "CHUNK", "TOKENS",
                        "XFER", "PRE", "QUEUE") + "  DETAIL"]
    for s in sorted(steps, key=lambda x: (x.get("t") or 0.0,
                                          x.get("seq") or 0)):
        occ = (f"{s.get('occupancy', 0)}/{s['slots']}" if s.get("slots")
               else str(s.get("occupancy", 0)))
        xfer = (s.get("fetches") or 0) + (s.get("uploads") or 0)
        progs = " ".join(
            f"{k} {v * 1000:.1f}ms"
            for k, v in sorted((s.get("programs") or {}).items()))
        traces = ",".join(s.get("traces") or ())
        detail = "  ".join(b for b in (
            progs,
            f"traces={traces}" if traces else "",
            f"[{s['cell']}]" if s.get("cell") else "") if b)
        lines.append(fmt.format(
            f"+{(s.get('t') or base) - base:.3f}s",
            s.get("seq", "-"),
            f"{(s.get('wall_s') or 0) * 1000:.1f}ms",
            occ, s.get("chunk_k", "-"), s.get("tokens", 0),
            xfer, s.get("preemptions", 0), s.get("queue_depth", "-"))
            + (f"  {detail}" if detail else ""))
    return "\n".join(lines)


def cmd_timeline(args):
    """The flight-recorder view: the daemon unions the matching cells'
    /v1/timeline rings (Timeline RPC) and this renders the last N
    engine-loop steps — what the batch looked like, where the step's
    wall time went per program, and which traces were seated, so a
    latency spike localizes to a step before `kuke trace` zooms in."""
    try:
        out = _client(args).call("Timeline", cell=args.cell, n=args.n)
    except KukeonError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    steps = out.get("steps", [])
    if args.json:
        _print(steps, True)
        return 0
    print(render_timeline(steps))
    return 0 if steps else 1


def _fmt_count(n) -> str:
    if n is None:
        return "-"
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(n) < 1000 or unit == "P":
            return f"{n:.1f}{unit}" if unit else f"{n:.0f}"
        n /= 1000.0
    return f"{n:.1f}P"


def render_layer_profile(key: str, prof: dict) -> str:
    """One persisted per-layer cost profile (obs/profile.profile_layers,
    written by `bench.py --profile-layers`) as a table: per component and
    shape, the XLA cost-analysis FLOPs/bytes and measured wall time,
    with the whole-model totals as the roofline reference. Pure; reads
    no accelerator state."""
    head = [f"{key}  ({prof.get('schema', '?')}"
            + (f", profiled {prof['profiled_at']}"
               if prof.get("profiled_at") else "") + ")"]
    head.append(
        f"  layers={prof.get('num_layers', '?')}"
        f" prefill_len={prof.get('prefill_len', '?')}"
        f" decode_batch={prof.get('decode_batch', '?')}"
        f" model_flops={_fmt_count(prof.get('model_flops'))}"
        f" model_bytes={_fmt_bytes(prof.get('model_bytes'))}")
    if prof.get("errors"):
        head.append(f"  {prof['errors']} component(s) failed to profile")
    fmt = "  {:<10} {:<9} {:>10} {:>10} {:>10}"
    lines = head + [fmt.format("COMPONENT", "SHAPE", "FLOPS", "BYTES",
                               "WALL")]
    for comp in prof.get("components", []):
        name = comp.get("name", "?")
        if comp.get("error"):
            lines.append(fmt.format(name, "-", "-", "-", "-")
                         + f"  ({comp['error']})")
            continue
        for shape in ("prefill", "decode"):
            rec = comp.get(shape)
            if not isinstance(rec, dict):
                continue
            wall = (f"{rec['wall_s'] * 1000:.2f}ms"
                    if rec.get("wall_s") is not None else "-")
            lines.append(fmt.format(
                name, shape, _fmt_count(rec.get("flops")),
                _fmt_bytes(rec.get("bytes")), wall))
    return "\n".join(lines)


def cmd_profile(args):
    """Render persisted per-layer cost profiles. Reads the local profile
    file (serving/tuning.py, next to the serving tune) only — no daemon,
    no accelerator runtime — so it works anywhere the bench ran
    `--profile-layers`. An optional key substring narrows the listing
    (keys are ``model|backend|n_chips``)."""
    from kukeon_tpu.serving import tuning

    profs = tuning.load_layer_profiles()
    if args.key:
        profs = {k: v for k, v in profs.items() if args.key in k}
    if args.json:
        _print(profs, True)
        return 0
    if not profs:
        print("no persisted layer profiles"
              + (f" matching {args.key!r}" if args.key else "")
              + f" in {tuning.layer_profile_path()}"
              " (run bench.py --profile-layers)")
        return 1
    print("\n\n".join(render_layer_profile(k, v)
                      for k, v in sorted(profs.items())))
    return 0


def cmd_scale(args):
    """The autoscaler's status verb: one row per autoscaled model cell —
    active target vs declared bounds, the latest queue-pressure and SLO
    burn signals, each decision rule's debounce state — plus the recent
    scale events (up/down/aborted with reasons). Read-only: the scaler
    itself decides; this is how the operator watches it decide."""
    out = _client(args).call("ScaleStatus")
    cells = out.get("cells", [])
    if getattr(args, "name", None):
        cells = [c for c in cells if c["cell"].endswith("/" + args.name)
                 or c["cell"] == args.name]
    if args.json:
        _print({"cells": cells, "events": out.get("events", [])}, True)
        return 0
    if not cells:
        print("no autoscaled model cells (set model.minReplicas/"
              "maxReplicas, and give the daemon a telemetry tick)")
        return 1
    fmt = "{:<32} {:>8} {:>7} {:>11} {:>8} {}"
    print(fmt.format("CELL", "REPLICAS", "BOUNDS", "QUEUE-RATIO", "BURN",
                     "RULES"))
    for c in sorted(cells, key=lambda c: c["cell"]):
        rules = c.get("rules") or {}
        lit = [f"{k}={v}" for k, v in sorted(rules.items()) if v != "ok"]
        print(fmt.format(
            c["cell"], c.get("active", "?"),
            f"{c.get('min', 1)}..{c.get('max', '?')}",
            f"{c.get('queueRatio', 0):.3f}", f"{c.get('burnRate', 0):.2f}",
            " ".join(lit) if lit else "quiet"))
    events = out.get("events", [])
    if events:
        print("\nrecent scale events:")
        for ev in events[-10:]:
            ts = time.strftime("%H:%M:%S", time.localtime(ev["at"]))
            arrow = {"up": "+1", "down": "-1"}.get(ev["direction"], "?")
            print(f"  {ts} {ev['cell']} {arrow} -> {ev.get('to', '?')} "
                  f"[{ev['result']}] {ev.get('reason', '')}")
    return 0


def cmd_rollout(args):
    """Rolling restart of a replicated model cell (drain -> restart ->
    ready, one replica at a time; the daemon drives it, the gateway keeps
    traffic flowing). Zero failed requests is the contract."""
    c = _client(args)
    s = _scope(args)
    out = c.call("RolloutCell", **s, name=args.name,
                 drainTimeoutS=args.drain_timeout,
                 readyTimeoutS=args.ready_timeout,
                 standby=getattr(args, "standby", True))
    if args.json:
        _print(out, True)
        return 1 if out.get("aborted") else 0
    sb = next((r["standby"] for r in out["replicas"]
               if isinstance(r.get("standby"), dict)), None)
    if sb is not None:
        print(f"  standby {sb['replica']}: ready in {sb['readyS']}s "
              "(census held at N throughout)")
    for r in out["replicas"]:
        if r.get("standby") is True:
            # The standby itself failed before any replica drained.
            print(f"  standby {r['replica']}: FAILED: {r.get('error')}")
            continue
        drained = "drained" if r["drained"] else "drain timeout (restarted anyway)"
        if r.get("error"):
            print(f"  {r['replica']}: {drained}, FAILED: {r['error']}")
        else:
            print(f"  {r['replica']}: {drained}, ready again in {r['readyS']}s")
    if out.get("aborted"):
        # The per-step records above say exactly which replicas finished;
        # re-running `kuke rollout` after fixing the stalled one is safe
        # (a healthy replica just drains and restarts again).
        done = sum(1 for r in out["replicas"] if not r.get("error"))
        print(f"cell/{args.name}: rollout ABORTED after {done} replica(s): "
              f"{out.get('error')}", file=sys.stderr)
        return 1
    print(f"cell/{args.name}: rollout complete "
          f"({len(out['replicas'])} replicas)")
    return 0


def cmd_doctor(args):
    """Host pre-flight checks (reference: kuke doctor / internal/cgroupcheck:
    controller availability + delegation detail; all five native tools; the
    isolation and egress-enforcement layers the security story depends on)."""
    from kukeon_tpu.runtime import instance, sysuser
    from kukeon_tpu.runtime.cgroups import CgroupManager
    from kukeon_tpu.runtime.devices import discover_chips

    checks = []
    cg = CgroupManager()
    if cg.available():
        try:
            with open(os.path.join(cg.root, "cgroup.controllers")) as f:
                avail = set(f.read().split())
            with open(os.path.join(cg.root, cg.base, "cgroup.subtree_control")) as f:
                delegated = set(f.read().split())
        except OSError:
            avail, delegated = set(), set()
        want = {"cpu", "memory", "pids"}
        missing = want - delegated
        detail = f"controllers={sorted(avail & want)} delegated={sorted(delegated & want)}"
        if missing & avail:
            detail += f" (NOT delegated: {sorted(missing & avail)})"
        checks.append(("cgroup-v2", f"ok — {detail}"))
    else:
        checks.append(("cgroup-v2", "unavailable (limits degrade)"))
    chips = discover_chips()
    checks.append(("tpu-chips", f"{len(chips)} visible ({chips})" if chips else "none visible"))
    from kukeon_tpu.runtime.devices import probe_tpu_runtime

    state, detail = probe_tpu_runtime(
        timeout_s=float(os.environ.get("KUKEON_DOCTOR_PROBE_TIMEOUT", "20"))
    )
    checks.append(("tpu-runtime",
                   f"{state} — {detail}" if state != "ok" else f"ok — {detail}"))
    bin_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bin")
    for b in ("kukepause", "kukeshim", "kuketty", "kukecell", "kukenet"):
        ok = os.access(os.path.join(bin_dir, b), os.X_OK)
        checks.append((f"native/{b}", "ok" if ok else "MISSING (run `make -C native`)"))
    # The two enforcement layers:
    from kukeon_tpu.runtime.cells import namespace as nsb

    checks.append(("isolation", "namespace sandbox (kukecell)" if nsb.available()
                   else "process backend (no sandboxing — need root + kukecell)"))
    # Same predicate the daemon uses — the preflight must never claim
    # enforcement the runtime would run without.
    from kukeon_tpu.runtime.net.kukenet import kukenet_usable
    from kukeon_tpu.runtime.net.manager import _enforcement_enabled
    from kukeon_tpu.runtime.net.runners import ShellRunner

    r = ShellRunner()
    if not _enforcement_enabled(r):
        checks.append(("net-enforce", "OFF (need root + ip + iptables/kukenet, "
                       "or KUKEON_NET_ENFORCE=1)"))
    elif r.available("iptables"):
        checks.append(("net-enforce", "on (iptables CLI)"))
    elif kukenet_usable():
        checks.append(("net-enforce", "on (kukenet, native xtables)"))
    else:
        checks.append(("net-enforce", "forced on (KUKEON_NET_ENFORCE=1) but no "
                       "enforcer binary — policies will fail"))
    gid = sysuser.group_gid()
    checks.append(("group-kukeon", f"gid {gid}" if gid is not None
                   else "absent (kuke init as root provisions it)"))
    run_path = _run_path(args)
    checks.append(("run-path", run_path + (" (exists)" if os.path.isdir(run_path) else " (not initialized — run `kuke init`)")))
    pinned = instance.read(run_path)
    if pinned:
        checks.append(("instance", ", ".join(f"{k}={v}" for k, v in sorted(pinned.items()))))
    for name, result in checks:
        print(f"{name:<18} {result}")
    return 0


def cmd_purge(args):
    c = _client(args)
    s = _scope(args)
    if args.kind in ("realm", "realms"):
        c.call("DeleteRealm", name=args.name, purge=True)
    elif args.kind in ("space", "spaces"):
        c.call("DeleteSpace", realm=s["realm"], name=args.name, purge=True)
    elif args.kind in ("stack", "stacks"):
        c.call("DeleteStack", realm=s["realm"], space=s["space"], name=args.name, purge=True)
    else:
        print(f"purge supports realm|space|stack, not {args.kind!r}", file=sys.stderr)
        return 2
    print(f"{args.kind}/{args.name}: purged")
    return 0


def cmd_refresh(args):
    c = _client(args)
    counts = c.call("ReconcileNow")
    _print(counts, args.json)
    return 0


_BASH_COMPLETION = """\
# kuke bash completion — source this file (kuke autocomplete bash).
_kuke_complete() {
    local cur="${COMP_WORDS[COMP_CWORD]}" prev="${COMP_WORDS[COMP_CWORD-1]}"
    local verbs="init apply create build daemon get delete doctor start status \
stop team kill purge refresh rollout run attach log top trace query alerts \
scale autocomplete image uninstall version"
    if [ "$COMP_CWORD" -eq 1 ]; then
        COMPREPLY=($(compgen -W "$verbs" -- "$cur")); return
    fi
    case "$prev" in
        start|stop|kill|attach|log|run|rollout|scale)
            COMPREPLY=($(compgen -W "$(kuke autocomplete cells 2>/dev/null)" -- "$cur"));;
        get|delete|purge|create)
            COMPREPLY=($(compgen -W "realm space stack cell secret blueprint \
config volume" -- "$cur"));;
    esac
}
complete -F _kuke_complete kuke
"""


def cmd_autocomplete(args):
    """Shell completion: `bash` emits the completion script; resource kinds
    emit live names for dynamic completion (reference: cmd/config
    autocomplete.go — daemon-backed completions)."""
    what = args.what
    if what == "bash":
        print(_BASH_COMPLETION, end="")
        return 0
    try:
        c = _client(args)
        realm = getattr(args, "realm", None) or consts.DEFAULT_REALM
        if what == "realms":
            names = c.call("ListRealms")
        elif what == "spaces":
            names = c.call("ListSpaces", realm=realm)
        elif what == "stacks":
            names = c.call("ListStacks", realm=realm,
                           space=getattr(args, "space", None) or consts.DEFAULT_SPACE)
        elif what == "cells":
            names = [r["name"] for r in c.call("ListCells", realm=realm,
                                               space=None, stack=None)]
        elif what == "blueprints":
            names = c.call("ListBlueprints", realm=realm, space=None, stack=None)
        elif what == "configs":
            names = c.call("ListConfigs", realm=realm, space=None, stack=None)
        else:
            return 2
        for n in names:
            print(n)
        return 0
    except KukeonError:
        return 0   # completion must never error loudly


def cmd_uninstall(args):
    run_path = _run_path(args)
    if not args.yes:
        print(f"would remove {run_path}; pass --yes to confirm", file=sys.stderr)
        return 2
    try:
        args.daemon_cmd = "stop"
        args.socket = None
        cmd_daemon(args)
    except Exception:  # noqa: BLE001
        pass
    import shutil

    shutil.rmtree(run_path, ignore_errors=True)
    print(f"removed {run_path}")
    return 0


# --- parser ------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="kuke", description="kukeon-tpu: TPU-native agent runtime")
    p.add_argument("--run-path", default=None, help="metadata root (env KUKEON_RUN_PATH)")
    p.add_argument("--socket", default=None, help="daemon socket (env KUKEOND_SOCKET)")
    p.add_argument("--no-daemon", action="store_true", help="run the controller in-process")
    p.add_argument("--json", action="store_true", help="JSON output")

    # Global flags are accepted after the verb too (SUPPRESS keeps a
    # flag-after-verb from clobbering a flag-before-verb with its default).
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--run-path", default=argparse.SUPPRESS)
    common.add_argument("--socket", default=argparse.SUPPRESS)
    common.add_argument("--no-daemon", action="store_true", default=argparse.SUPPRESS)
    common.add_argument("--json", action="store_true", default=argparse.SUPPRESS)

    sub = p.add_subparsers(dest="cmd", required=True)

    def sub_add(name, **kw):
        return sub.add_parser(name, parents=[common], **kw)

    sub_add("version")
    sp = sub_add("init")
    sp.add_argument("--no-daemon-start", action="store_true")

    sp = sub_add("daemon")
    sp.add_argument("daemon_cmd", choices=["serve", "start", "stop", "kill",
                                           "restart", "status", "logs",
                                           "metrics"])
    sp.add_argument("-f", "--follow", action="store_true")

    sp = sub_add("apply")
    sp.add_argument("-f", "--file", required=True)
    sp.add_argument("--team", default=None)
    sp.add_argument("--prune", action="store_true")

    sp = sub_add("delete")
    sp.add_argument("kind", nargs="?", default=None)
    sp.add_argument("name", nargs="?", default=None)
    sp.add_argument("-f", "--file", default=None)
    sp.add_argument("--force", action="store_true")
    _scope_args(sp)

    sp = sub_add("get")
    sp.add_argument("kind")
    sp.add_argument("name", nargs="?", default=None)
    _scope_args(sp)

    sp = sub_add("create")
    sp.add_argument("kind", nargs="?", default=None)
    sp.add_argument("name", nargs="?", default=None)
    sp.add_argument("-f", "--file", default=None)
    sp.add_argument("--image", default=None, help="cell: image for the main container")
    sp.add_argument("--command", nargs=argparse.REMAINDER, default=None,
                    help="cell: command for the main container; consumes ALL "
                         "remaining argv, so it must be the last flag")
    sp.add_argument("--no-start", action="store_true",
                    help="cell: create without starting")
    sp.add_argument("--data", action="append", help="secret: KEY=VALUE")
    sp.add_argument("--reclaim-policy", default="delete",
                    choices=["delete", "retain"], help="volume reclaim policy")
    _scope_args(sp)

    for verb in ("start", "stop", "kill"):
        sp = sub_add(verb)
        sp.add_argument("name")
        sp.set_defaults(verb=verb)
        _scope_args(sp)

    sp = sub_add("run")
    sp.add_argument("name", nargs="?", default=None)
    sp.add_argument("-f", "--file", default=None)
    sp.add_argument("-b", "--from-blueprint", default=None)
    sp.add_argument("-c", "--from-config", default=None)
    sp.add_argument("-p", "--param", action="append", help="KEY=VALUE blueprint params")
    sp.add_argument("--rm", action="store_true", help="autoDelete on exit")
    sp.add_argument("-d", "--detach", action="store_true")
    sp.add_argument("--container", default=None)
    _scope_args(sp)

    sp = sub_add("attach")
    sp.add_argument("name")
    sp.add_argument("--container", default=None)
    _scope_args(sp)

    sp = sub_add("log")
    sp.add_argument("name")
    sp.add_argument("--container", default=None)
    sp.add_argument("-f", "--follow", action="store_true")
    _scope_args(sp)

    sub_add("status")
    sp = sub_add("top")
    sp.add_argument("-w", "--watch", action="store_true",
                    help="repaint in place with sparkline history columns "
                         "(QPS, TTFT p95, queue) from the daemon's scrape "
                         "history")
    sp.add_argument("--interval", type=float, default=5.0,
                    help="--watch repaint interval in seconds")
    sub_add("doctor")
    sub_add("refresh")

    sp = sub_add("query")
    sp.add_argument("expr",
                    help="family{label=value,...} with an optional "
                         "'/ family{...}' ratio, e.g. "
                         "'kukeon_engine_ttft_seconds{cell=default/"
                         "default/default/llm}'")
    sp.add_argument("--window", default="5m",
                    help="trailing window (30s, 5m, 1h; default 5m)")
    sp.add_argument("--agg", default="avg",
                    choices=["rate", "delta", "avg", "max", "min",
                             "latest", "p50", "p95", "p99"],
                    help="aggregation over the window (p* need a "
                         "histogram family)")
    sp.add_argument("--step", default=None,
                    help="also print a per-step sparkline (e.g. 30s)")

    sp = sub_add("alerts")
    sp.add_argument("-n", "--transitions", type=int, default=50,
                    help="recent transitions to fetch")
    sp.add_argument("--check", action="store_true",
                    help="health gate: exit 1 while any rule is firing, "
                         "2 on a broken KUKEON_ALERT_RULES file")

    sp = sub_add("scale")
    sp.add_argument("name", nargs="?", default=None,
                    help="optional cell name filter")
    _scope_args(sp)

    sp = sub_add("trace")
    sp.add_argument("trace_id",
                    help="32-hex trace id (from logs, /v1/trace, or the "
                         "TTFT exemplar in `kuke top`)")

    sp = sub_add("timeline")
    sp.add_argument("cell", nargs="?", default=None,
                    help="cell key substring (realm/space/stack/name); "
                         "omit for the whole fleet")
    sp.add_argument("-n", type=int, default=50, dest="n",
                    help="newest engine steps to fetch per cell")

    sp = sub_add("profile")
    sp.add_argument("profile_cmd", choices=["layers"])
    sp.add_argument("key", nargs="?", default=None,
                    help="profile key substring (keys are "
                         "model|backend|n_chips)")

    sp = sub_add("rollout")
    sp.add_argument("name")
    sp.add_argument("--drain-timeout", type=float, default=60.0,
                    help="seconds to wait for each replica's drain")
    sp.add_argument("--ready-timeout", type=float, default=300.0,
                    help="seconds to wait for each restarted replica's readyz")
    sp.add_argument("--standby", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="pre-warm a parked replica to /readyz before the "
                         "first drain so the ready census holds at N "
                         "(skipped when the cell has no parked capacity)")
    _scope_args(sp)

    sp = sub_add("image")
    sp.add_argument("image_cmd",
                    choices=["list", "get", "delete", "prune", "load", "save",
                             "pull", "push"])
    sp.add_argument("ref", nargs="?", default=None)
    sp.add_argument("-i", "--input", default=None, help="tarball to load")
    sp.add_argument("-o", "--output", default=None, help="tarball to save to")
    sp.add_argument("--to", default=None,
                    help="push target registry/repo[:tag] (default: the "
                         "image's own ref)")
    sp.add_argument("--insecure", action="store_true",
                    help="pull/push over plain HTTP (implied for localhost)")

    sp = sub_add("build")
    sp.add_argument("context", nargs="?", default=".")
    sp.add_argument("-t", "--tag", required=True)
    sp.add_argument("-f", "--file", default=None, help="Kukefile path")
    sp.add_argument("--build-arg", action="append", help="KEY=VALUE")

    sp = sub_add("team")
    sp.add_argument("team_cmd", choices=["init"])
    sp.add_argument("-f", "--file", required=True, help="ProjectTeam manifest")
    sp.add_argument("--dry-run", action="store_true")
    sp.add_argument("--build", action="store_true",
                    help="build catalog images before rendering")
    sp.add_argument("--push", action="store_true",
                    help="push built images to the teams-config registry "
                         "(requires --build)")

    sp = sub_add("purge")
    sp.add_argument("kind")
    sp.add_argument("name")
    _scope_args(sp)

    sp = sub_add("uninstall")
    sp.add_argument("--yes", action="store_true")

    sp = sub_add("autocomplete")
    sp.add_argument("what", choices=["bash", "realms", "spaces", "stacks",
                                     "cells", "blueprints", "configs"])
    _scope_args(sp)
    return p


def _scope_args(sp):
    sp.add_argument("--realm", default=None)
    sp.add_argument("--space", default=None)
    sp.add_argument("--stack", default=None)


HANDLERS = {
    "version": cmd_version,
    "init": cmd_init,
    "daemon": cmd_daemon,
    "apply": cmd_apply,
    "delete": cmd_delete,
    "create": cmd_create,
    "get": cmd_get,
    "start": cmd_lifecycle,
    "stop": cmd_lifecycle,
    "kill": cmd_lifecycle,
    "run": cmd_run,
    "attach": cmd_attach,
    "log": cmd_log,
    "status": cmd_status,
    "top": cmd_top,
    "query": cmd_query,
    "alerts": cmd_alerts,
    "scale": cmd_scale,
    "trace": cmd_trace,
    "timeline": cmd_timeline,
    "profile": cmd_profile,
    "rollout": cmd_rollout,
    "doctor": cmd_doctor,
    "refresh": cmd_refresh,
    "purge": cmd_purge,
    "image": cmd_image,
    "build": cmd_build,
    "team": cmd_team,
    "uninstall": cmd_uninstall,
    "autocomplete": cmd_autocomplete,
}


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from kukeon_tpu.runtime import logging_setup

    logging_setup.setup(os.environ.get("KUKEOND_LOG_LEVEL", "info"))
    try:
        return HANDLERS[args.cmd](args)
    except KukeonError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except BrokenPipeError:
        # `kuke ... | head` closed the pipe: normal unix behavior, not an
        # error. Point stdout at devnull so interpreter teardown doesn't
        # raise again while flushing.
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
        return 0


if __name__ == "__main__":
    sys.exit(main())
