"""FleetScaler: SLO-driven replica autoscaling for model cells.

Closes the loop ROADMAP item 5 describes: the daemon's TSDB already holds
windowed burn-rate and queue-depth history for every replica, the alert
engine already implements the exact debounce a scaler needs
(pending -> firing per labelset, ``for:`` hold, silent cancel), the runner
can start/stop one replica container on a stable chip grant, and the
gateway can drain a replica out of rotation without losing a request.
This module wires those four primitives into a reconcile loop that rides
the telemetry thread (``FleetTelemetry.tick`` calls :meth:`tick` after
alert evaluation):

1. **Sense.** For every running model cell with ``maxReplicas`` bounds,
   aggregate the active replicas' queue depth into one pressure ratio
   (``sum(queue) / (active * max_pending)``) and take the worst 5m SLO
   burn rate across them, then ingest both as synthesized per-cell series
   (``kukeon_scaler_queue_ratio`` / ``kukeon_scaler_burn_rate``) — the
   same store, retention, and query surface every other signal uses.
2. **Debounce.** A private :class:`~kukeon_tpu.obs.alerts.AlertEngine`
   over :data:`SCALER_RULES` runs the pending->firing state machine on
   those series. Scale decisions are therefore *held breaches*, never
   single-tick spikes: scale-up needs pressure sustained for
   ``for: 10s``; scale-down needs the 2-minute *maximum* ratio below the
   idle floor for a full minute (hysteresis — growing is fast, shrinking
   is deliberate, and the two can never flap against each other because
   an up-rule firing vetoes the down path).
3. **Act, one step per tick.** Scale-up starts the next parked replica on
   its pre-partitioned chip grant (``Runner.scale_model_cell``). Scale-down
   first drains the highest-index replica through the gateway
   (``POST /drain`` -> wait drained, where *unreachable means drained* —
   a replica that died mid-drain is already gone, capacity-wise) and only
   then removes it; a drain that times out ABORTS the step (result
   ``aborted``, retried next tick) because removing a still-serving
   replica is exactly the lost-request hole this loop exists to prevent.

Chaos contract: the ``scaler.tick`` fault point fires at the top of
:meth:`tick`; the telemetry loop catches anything the scaler throws,
counts it on ``kukeon_scaler_errors_total``, and carries on — a crashed
scaler degrades to "no scaling this tick", never a wedged daemon or a
half-removed replica (the runner persists target and statuses in one
write, and reconcile heals a replica the crash left running).
"""

from __future__ import annotations

import logging
import os
import time
from collections import deque
from typing import Callable

from kukeon_tpu import faults, sanitize
from kukeon_tpu.obs import federate as fed
from kukeon_tpu.obs.alerts import AlertEngine, Rule

log = logging.getLogger("kukeon.scaler")

DRAIN_TIMEOUT_ENV = "KUKEON_SCALER_DRAIN_TIMEOUT_S"
DEFAULT_DRAIN_TIMEOUT_S = 30.0

# Pre-warm the next parked replica while a scale-up rule is still in its
# debounce hold, so the eventual promotion adopts a warm replica instead of
# cold-starting under load. On by default; set to "0" to disable.
PREWARM_ENV = "KUKEON_SCALER_PREWARM"

# The serving cell's own CLI default for --max-pending, mirrored here so a
# spec that never set maxPending still yields a meaningful pressure ratio.
DEFAULT_MAX_PENDING = 64

# The scaler's decision rules, debounced through the same state machine the
# alert engine uses (obs/alerts.py). Evaluated against the SYNTHESIZED
# per-cell aggregates this module ingests, so each labelset is one model
# cell, not one replica. Severity info: these are decisions, not pages.
SCALER_RULES: tuple[Rule, ...] = (
    Rule(name="ScaleUpQueue",
         expr="kukeon_scaler_queue_ratio",
         agg="avg", window_s=30.0, op=">", threshold=0.5, for_s=10.0,
         severity="info",
         description="aggregate admission-queue pressure above half of "
                     "the fleet's capacity, sustained — add a replica"),
    Rule(name="ScaleUpBurn",
         expr="kukeon_scaler_burn_rate",
         agg="max", window_s=60.0, op=">", threshold=1.0, for_s=10.0,
         severity="info",
         description="a replica is burning SLO error budget faster than "
                     "allowed — add a replica before the page fires"),
    Rule(name="ScaleDownIdle",
         expr="kukeon_scaler_queue_ratio",
         agg="max", window_s=120.0, op="<", threshold=0.1, for_s=60.0,
         severity="info",
         description="even the PEAK queue pressure of the last two "
                     "minutes is under 10% of capacity, held for a full "
                     "minute — drain and remove a replica"),
)

_UP_RULES = ("ScaleUpQueue", "ScaleUpBurn")
_DOWN_RULE = "ScaleDownIdle"


def _materialize_replica(ctl, rec, target: int) -> None:
    """Scale-up seam: bring the replica set to ``target`` by starting the
    next parked container on its stable chip grant. Module-level so the
    fake-backend fleet simulator can wrap it to also respawn its fake
    replica HTTP servers (the same pattern as daemon._rollout_restart)."""
    ctl.runner.scale_model_cell(rec.realm, rec.space, rec.stack, rec.name,
                                target)


def _prewarm_replica(ctl, rec) -> None:
    """Pre-warm seam: boot the next parked replica WITHOUT raising the
    active target, so a scale-up decided seconds later promotes a warm,
    already-/readyz replica instead of paying a cold start under pressure.
    Idempotent (a standby already running is adopted); module-level for the
    same fake-backend-simulator reason as :func:`_materialize_replica`."""
    ctl.runner.start_parked_replica(rec.realm, rec.space, rec.stack,
                                    rec.name)


def _remove_replica(ctl, rec, target: int) -> None:
    """Scale-down seam: the victim replica is already drained; stop its
    container and persist the lower target."""
    ctl.runner.scale_model_cell(rec.realm, rec.space, rec.stack, rec.name,
                                target)


class FleetScaler:
    """The reconcile loop over every autoscaled model cell. Owned by
    FleetTelemetry (whose tick drives :meth:`tick` right after alert
    evaluation, on the daemon's telemetry thread); `kuke scale` reads
    :meth:`states` from RPC handler threads — hence the lock around the
    decision snapshot and event ring."""

    def __init__(self, ctl, tsdb, registry=None,
                 clock: Callable[[], float] = time.time,
                 drain_timeout_s: float | None = None,
                 max_events: int = 128):
        self.ctl = ctl
        self.tsdb = tsdb
        self._clock = clock
        self.drain_timeout_s = (
            drain_timeout_s if drain_timeout_s is not None
            else float(os.environ.get(DRAIN_TIMEOUT_ENV, "")
                       or DEFAULT_DRAIN_TIMEOUT_S))
        self.prewarm = os.environ.get(PREWARM_ENV, "1") != "0"
        # The debounce: a PRIVATE alert engine over the scaler rules (no
        # registry — its firing census must not collide with the real
        # alert engine's kukeon_alerts_firing; no webhook — decisions are
        # not pages).
        self.engine = AlertEngine(tsdb, rules=SCALER_RULES, registry=None,
                                  clock=clock, webhook_url="")
        self._lock = sanitize.lock("FleetScaler._lock")
        self._events: deque[dict] = deque(maxlen=max_events)  # guarded-by: _lock
        self._last: dict[str, dict] = {}                      # guarded-by: _lock

        self._m_ticks = self._m_errors = self._m_events = None
        self._g_desired = self._g_min = self._g_max = None
        self._g_queue = self._g_burn = None
        if registry is not None:
            self._m_ticks = registry.counter(
                "kukeon_scaler_ticks_total",
                "FleetScaler reconcile passes completed.")
            self._m_errors = registry.counter(
                "kukeon_scaler_errors_total",
                "Scaler ticks that raised (incl. the armed scaler.tick "
                "fault point) — the loop survives and skips the tick.")
            self._m_events = registry.counter(
                "kukeon_scaler_events_total",
                "Scale decisions acted on, by cell, direction, and result "
                "(aborted = a scale-down drain timed out; the replica "
                "stays, retried next tick).",
                labels=("cell", "direction", "result"))
            self._g_desired = registry.gauge(
                "kukeon_scaler_replicas_desired",
                "Active replica target per autoscaled cell.",
                labels=("cell",))
            self._g_min = registry.gauge(
                "kukeon_scaler_replicas_min",
                "Lower autoscale bound per cell.", labels=("cell",))
            self._g_max = registry.gauge(
                "kukeon_scaler_replicas_max",
                "Upper autoscale bound per cell.", labels=("cell",))
            self._g_queue = registry.gauge(
                "kukeon_scaler_queue_ratio",
                "Aggregate queue depth over active-fleet capacity "
                "(sum(queue) / (active * max_pending)) per autoscaled "
                "cell — the scale-up pressure signal.", labels=("cell",))
            self._g_burn = registry.gauge(
                "kukeon_scaler_burn_rate",
                "Worst 5m SLO burn rate across the cell's active "
                "replicas — the SLO-driven scale-up signal.",
                labels=("cell",))

    def note_error(self) -> None:
        """Telemetry-loop accounting for a tick that raised."""
        if self._m_errors is not None:
            self._m_errors.inc()

    # --- the reconcile tick -------------------------------------------------

    def tick(self, at: float | None = None) -> list[dict]:
        """One reconcile pass; returns the scale events it acted on. May
        raise (the scaler.tick chaos seam does) — the caller's telemetry
        loop is the survival boundary, not this method."""
        faults.maybe_fail("scaler.tick")
        now = self._clock() if at is None else at
        cells = self._autoscaled_cells()
        if self._m_ticks is not None:
            self._m_ticks.inc()
        if not cells:
            with self._lock:
                self._last = {}
            return []

        # --- sense: synthesize per-cell aggregate signals ------------------
        signals: dict[str, dict] = {}
        queue_rows: list[tuple[str, float]] = []
        burn_rows: list[tuple[str, float]] = []
        qd = self.tsdb.query("kukeon_engine_queue_depth", 60.0, "latest",
                             at=now)
        burn = self.tsdb.query("kukeon_slo_burn_rate", 60.0, "latest",
                               at=now)
        for key, rec, m in cells:
            active = self.ctl.runner.model_target(rec)
            active_keys = {f"{key}/r{i}" for i in range(active)}
            qsum, have = 0.0, False
            for labels, v in qd:
                if labels.get("cell") in active_keys:
                    qsum += v
                    have = True
            worst_burn = 0.0
            for labels, v in burn:
                if (labels.get("cell") in active_keys
                        and labels.get("window") == "5m"):
                    worst_burn = max(worst_burn, v)
            max_pending = m.max_pending or DEFAULT_MAX_PENDING
            ratio = qsum / max(1.0, active * max_pending)
            lo = max(1, m.min_replicas or 1)
            hi = m.max_replicas or lo
            signals[key] = {
                "cell": key, "active": active, "min": lo, "max": hi,
                "queueRatio": round(ratio, 4),
                "burnRate": round(worst_burn, 4),
                "scraped": have,
            }
            if self._g_desired is not None:
                self._g_desired.set(active, cell=key)
                self._g_min.set(lo, cell=key)
                self._g_max.set(hi, cell=key)
                self._g_queue.set(ratio, cell=key)
                self._g_burn.set(worst_burn, cell=key)
            if have:
                # No queue data means the fleet has not been scraped yet
                # (fresh daemon, cell still booting): feeding a synthetic
                # 0 would read as "idle" and trigger a bogus scale-down.
                queue_rows.append((key, ratio))
                burn_rows.append((key, worst_burn))
        self.tsdb.ingest({
            "kukeon_scaler_queue_ratio": fed.Family(
                "kukeon_scaler_queue_ratio", "gauge", "",
                [("kukeon_scaler_queue_ratio", {"cell": k}, str(v))
                 for k, v in queue_rows]),
            "kukeon_scaler_burn_rate": fed.Family(
                "kukeon_scaler_burn_rate", "gauge", "",
                [("kukeon_scaler_burn_rate", {"cell": k}, str(v))
                 for k, v in burn_rows]),
        }, at=now)

        # --- debounce: the pending->firing machine over the signals --------
        self.engine.evaluate(at=now)
        firing: dict[str, set[str]] = {}
        rule_states: dict[str, dict[str, str]] = {}
        for row in self.engine.states():
            cell = (row.get("labels") or {}).get("cell")
            if cell is None:
                continue
            rule_states.setdefault(cell, {})[row["alert"]] = row["state"]
            if row["state"] == "firing":
                firing.setdefault(cell, set()).add(row["alert"])

        # --- act: at most one step per cell per tick ------------------------
        events: list[dict] = []
        for key, rec, m in cells:
            sig = signals[key]
            sig["rules"] = rule_states.get(key, {})
            lit = firing.get(key, set())
            up = bool(lit & set(_UP_RULES))
            down = _DOWN_RULE in lit
            # Pre-warm while the pressure debounce is still holding: an
            # up-rule in pending means a scale-up is likely within for_s —
            # booting the next parked replica NOW means the promotion
            # adopts a warm /readyz replica instead of cold-starting under
            # the very load spike that triggered it. Best-effort: a failed
            # pre-warm degrades to today's cold promotion, never a skipped
            # tick.
            pending_up = any(
                sig["rules"].get(r) in ("pending", "firing")
                for r in _UP_RULES)
            if (self.prewarm and pending_up and not down
                    and sig["active"] < sig["max"]):
                try:
                    _prewarm_replica(self.ctl, rec)
                    sig["prewarmed"] = True
                except Exception:  # noqa: BLE001 — degrade to cold promotion
                    log.exception("scaler: pre-warm on %s failed", key)
            try:
                if up and sig["active"] < sig["max"]:
                    events.append(self._scale_up(key, rec, sig, now))
                elif down and not up and sig["active"] > sig["min"]:
                    events.append(self._scale_down(key, rec, m, sig, now))
            except Exception as e:  # noqa: BLE001 — one cell must not stall the fleet
                log.exception("scaler: %s on %s failed",
                              "scale-up" if up else "scale-down", key)
                if self._m_events is not None:
                    self._m_events.inc(cell=key,
                                       direction="up" if up else "down",
                                       result="error")
                events.append({"at": now, "cell": key,
                               "direction": "up" if up else "down",
                               "result": "error",
                               "reason": f"{type(e).__name__}: {e}"})
        with self._lock:
            self._last = signals
            for ev in events:
                self._events.append(ev)
        return events

    def _scale_up(self, key: str, rec, sig: dict, now: float) -> dict:
        target = sig["active"] + 1
        _materialize_replica(self.ctl, rec, target)
        sig["active"] = target
        if self._m_events is not None:
            self._m_events.inc(cell=key, direction="up", result="ok")
        if self._g_desired is not None:
            self._g_desired.set(target, cell=key)
        ev = {"at": now, "cell": key, "direction": "up", "result": "ok",
              "to": target,
              "reason": f"queueRatio={sig['queueRatio']} "
                        f"burn={sig['burnRate']}"}
        log.info("scaler: %s scaled up to %d replicas (%s)", key, target,
                 ev["reason"])
        return ev

    def _scale_down(self, key: str, rec, m, sig: dict, now: float) -> dict:
        from kukeon_tpu.gateway import rollout as ro

        victim = sig["active"] - 1
        host = rec.status.ip or "127.0.0.1"
        url = f"http://{host}:{m.port + 1 + victim}"
        # Drain FIRST, remove ONLY once drained: the replica leaves the
        # gateway's rotation the moment it reports draining, finishes its
        # in-flight work, and exits — unreachable counts as drained (a
        # replica that died mid-drain holds no requests to lose).
        drained = ro.drain_replica(url, drain_timeout_s=self.drain_timeout_s)
        if not drained:
            if self._m_events is not None:
                self._m_events.inc(cell=key, direction="down",
                                   result="aborted")
            ev = {"at": now, "cell": key, "direction": "down",
                  "result": "aborted", "to": sig["active"],
                  "reason": f"model-server-{victim} still serving after "
                            f"{self.drain_timeout_s:.0f}s drain; kept"}
            log.warning("scaler: %s scale-down aborted (%s)", key,
                        ev["reason"])
            return ev
        _remove_replica(self.ctl, rec, victim)
        sig["active"] = victim
        if self._m_events is not None:
            self._m_events.inc(cell=key, direction="down", result="ok")
        if self._g_desired is not None:
            self._g_desired.set(victim, cell=key)
        ev = {"at": now, "cell": key, "direction": "down", "result": "ok",
              "to": victim,
              "reason": f"queueRatio={sig['queueRatio']} (idle)"}
        log.info("scaler: %s scaled down to %d replicas", key, victim)
        return ev

    # --- inputs / views -----------------------------------------------------

    def _autoscaled_cells(self) -> list[tuple[str, object, object]]:
        """(cell key, typed record, ModelSpec) for every running model cell
        with autoscale bounds."""
        out = []
        for realm in self.ctl.list_realms():
            for rec_json in self.ctl.list_cells(realm):
                m = (rec_json.get("spec") or {}).get("model") or {}
                if not m.get("maxReplicas"):
                    continue
                st = rec_json.get("status") or {}
                if st.get("phase") not in ("ready", "degraded"):
                    continue
                rec = self.ctl.store.read_cell(
                    rec_json["realm"], rec_json["space"],
                    rec_json["stack"], rec_json["name"])
                key = "/".join((rec.realm, rec.space, rec.stack, rec.name))
                out.append((key, rec, rec.spec.model))
        return out

    def states(self) -> list[dict]:
        """One row per autoscaled cell — bounds, active target, the latest
        signals, and each decision rule's debounce state (the `kuke scale`
        table)."""
        with self._lock:
            return [dict(sig) for sig in self._last.values()]

    def events(self, n: int = 50) -> list[dict]:
        with self._lock:
            return list(self._events)[-int(n):]
