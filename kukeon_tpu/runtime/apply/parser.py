"""Manifest parsing: multi-doc YAML -> validated Documents.

Reference: internal/apply/parser (parser.go:68 multi-doc split, :102 kind
detection, :220-823 per-kind structural validation incl. scope rules).
"""

from __future__ import annotations

import yaml

from kukeon_tpu.runtime import naming
from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.api.wire import from_wire
from kukeon_tpu.runtime.apply.validate import validate_manifest, validate_spec
from kukeon_tpu.runtime.errors import InvalidArgument

# Scope requirements per kind: which metadata fields must / may be set.
# (reference scope rules: Secret/Blueprint/Config scopable at realm/space/
#  stack; Volume at realm/space/stack only — never cell.)
_SCOPED_KINDS = {t.KIND_SECRET, t.KIND_CELL_BLUEPRINT, t.KIND_CELL_CONFIG, t.KIND_VOLUME}


def parse_documents(blob: str, source: str = "<manifest>") -> list[t.Document]:
    """Split a multi-doc YAML blob and parse/validate every document."""
    docs: list[t.Document] = []
    try:
        raw_docs = list(yaml.safe_load_all(blob))
    except yaml.YAMLError as e:
        raise InvalidArgument(f"{source}: invalid YAML: {e}") from None
    for i, raw in enumerate(raw_docs):
        if raw is None:
            continue
        docs.append(parse_document(raw, f"{source}[{i}]"))
    if not docs:
        raise InvalidArgument(f"{source}: no documents found")
    # Cross-document depth (per-doc validation already ran): model-cell
    # port ranges within one manifest must be disjoint.
    validate_manifest(docs)
    return docs


def dump_documents(docs: list[t.Document]) -> str:
    """Documents -> multi-doc YAML blob (the inverse of parse_documents)."""
    from kukeon_tpu.runtime.api.wire import to_wire

    raw_docs = []
    for d in docs:
        raw_docs.append({
            "apiVersion": d.api_version,
            "kind": d.kind,
            "metadata": to_wire(d.metadata),
            "spec": to_wire(d.spec),
        })
    return yaml.safe_dump_all(raw_docs, sort_keys=False)


def parse_document(raw: dict, context: str) -> t.Document:
    if not isinstance(raw, dict):
        raise InvalidArgument(f"{context}: document must be a mapping")
    api_version = raw.get("apiVersion")
    if api_version not in (t.API_VERSION, t.TEAMS_API_VERSION):
        raise InvalidArgument(
            f"{context}: unsupported apiVersion {api_version!r} (want {t.API_VERSION})"
        )
    kind = raw.get("kind")
    if kind not in t.SPEC_BY_KIND:
        raise InvalidArgument(
            f"{context}: unknown kind {kind!r}; known: {sorted(t.SPEC_BY_KIND)}"
        )
    extra = set(raw) - {"apiVersion", "kind", "metadata", "spec"}
    if extra:
        raise InvalidArgument(f"{context}: unknown top-level field(s) {sorted(extra)}")

    metadata = from_wire(t.Metadata, raw.get("metadata"), f"{context}.metadata")
    spec = from_wire(t.SPEC_BY_KIND[kind], raw.get("spec"), f"{context}.spec")
    doc = t.Document(api_version=api_version, kind=kind, metadata=metadata, spec=spec)
    validate_document(doc, context)
    return doc


def validate_document(doc: t.Document, context: str = "") -> None:
    ctx = context or f"{doc.kind}/{doc.metadata.name}"
    md = doc.metadata
    if doc.kind in (t.KIND_SERVER_CONFIGURATION, t.KIND_CLIENT_CONFIGURATION):
        # Config documents are client/daemon-side files, not server resources
        # (reference: consts.go — `kuke apply` rejects them). Parsed here for
        # the config loaders; apply rejects them at a higher level.
        return
    naming.validate_name(md.name, f"{doc.kind} name")
    for scope_field in ("realm", "space", "stack", "cell"):
        v = getattr(md, scope_field)
        if v is not None:
            naming.validate_name(v, f"{doc.kind} {scope_field}")

    if doc.kind == t.KIND_REALM:
        _forbid_scope(md, ctx, "realm", "space", "stack", "cell")
    elif doc.kind == t.KIND_SPACE:
        _forbid_scope(md, ctx, "space", "stack", "cell")
        validate_spec(doc.kind, doc.spec, ctx)
    elif doc.kind == t.KIND_STACK:
        _forbid_scope(md, ctx, "stack", "cell")
    elif doc.kind in (t.KIND_CELL, t.KIND_CONTAINER):
        _forbid_scope(md, ctx, "cell")
        validate_spec(doc.kind, doc.spec, ctx)
    elif doc.kind in _SCOPED_KINDS:
        if md.cell is not None:
            raise InvalidArgument(f"{ctx}: {doc.kind} cannot be cell-scoped")
        # stack scope requires space; space requires realm (when given).
        if md.stack is not None and md.space is None:
            raise InvalidArgument(f"{ctx}: stack scope requires space")
        validate_spec(doc.kind, doc.spec, ctx)


def _forbid_scope(md: t.Metadata, ctx: str, *fields: str) -> None:
    for f in fields:
        if getattr(md, f) is not None:
            raise InvalidArgument(f"{ctx}: metadata.{f} is not allowed for this kind")


def sort_documents(docs: list[t.Document], reverse: bool = False) -> list[t.Document]:
    """Dependency order for apply (reverse for delete -f)."""
    order = {k: i for i, k in enumerate(t.KIND_APPLY_ORDER)}
    key = lambda d: order.get(d.kind, len(order))
    return sorted(docs, key=key, reverse=reverse)
