"""Normalization & defaulting: wire docs -> canonical internal form.

Reference: internal/apischeme (scheme.go:43-885) — validate + default every
kind before the controller sees it. Scope fields default to the `default`
realm/space/stack; space-level container defaults merge into each cell's
containers; model cells get their serving-container shape validated.
"""

from __future__ import annotations

import dataclasses

from kukeon_tpu.runtime import consts
from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.apply import validate
from kukeon_tpu.runtime.errors import InvalidArgument


def default_scope(md: t.Metadata, *, need_space: bool = True, need_stack: bool = True) -> t.Metadata:
    md = dataclasses.replace(md, labels=dict(md.labels))
    md.realm = md.realm or consts.DEFAULT_REALM
    if need_space:
        md.space = md.space or consts.DEFAULT_SPACE
    if need_stack:
        md.stack = md.stack or consts.DEFAULT_STACK
    return md


def normalize_cell(doc: t.Document, space_defaults: t.ContainerSpec | None = None) -> t.Document:
    """Canonical cell doc: scope defaulted, container defaults merged."""
    if doc.kind != t.KIND_CELL:
        raise InvalidArgument(f"normalize_cell on kind {doc.kind}")
    md = default_scope(doc.metadata)
    spec: t.CellSpec = doc.spec
    containers = [
        _merge_container_defaults(c, space_defaults) for c in spec.containers
    ]
    spec = dataclasses.replace(spec, containers=containers)
    # Deep-validate the MERGED spec: the RPC create path reaches normalize
    # without going through the parser, and space defaults could in theory
    # merge an invalid value in (reference: apischeme validates post-merge).
    validate.validate_cell(spec, f"Cell/{md.name}")
    return dataclasses.replace(doc, metadata=md, spec=spec)


def _merge_container_defaults(
    c: t.ContainerSpec, defaults: t.ContainerSpec | None
) -> t.ContainerSpec:
    if defaults is None:
        return c
    merged = dataclasses.replace(c)
    if not merged.env and defaults.env:
        merged.env = list(defaults.env)
    elif defaults.env:
        have = {e.name for e in merged.env}
        merged.env = list(merged.env) + [e for e in defaults.env if e.name not in have]
    if merged.resources.memory is None and defaults.resources.memory is not None:
        merged.resources = dataclasses.replace(
            merged.resources, memory=defaults.resources.memory
        )
    if merged.resources.cpu is None and defaults.resources.cpu is not None:
        merged.resources = dataclasses.replace(merged.resources, cpu=defaults.resources.cpu)
    if merged.workdir is None and defaults.workdir is not None:
        merged.workdir = defaults.workdir
    return merged


def normalize_scoped(doc: t.Document) -> t.Document:
    """Secrets / blueprints / configs / volumes: realm always set; finer
    scopes only if given."""
    md = dataclasses.replace(doc.metadata, labels=dict(doc.metadata.labels))
    md.realm = md.realm or consts.DEFAULT_REALM
    return dataclasses.replace(doc, metadata=md)


def normalize(doc: t.Document) -> t.Document:
    if doc.kind == t.KIND_REALM:
        return doc
    if doc.kind == t.KIND_SPACE:
        validate.validate_space(doc.spec, f"Space/{doc.metadata.name}")
        return dataclasses.replace(doc, metadata=default_scope(doc.metadata, need_space=False, need_stack=False))
    if doc.kind == t.KIND_STACK:
        return dataclasses.replace(doc, metadata=default_scope(doc.metadata, need_stack=False))
    if doc.kind == t.KIND_CELL:
        return normalize_cell(doc)
    if doc.kind in (t.KIND_SECRET, t.KIND_CELL_BLUEPRINT, t.KIND_CELL_CONFIG, t.KIND_VOLUME):
        return normalize_scoped(doc)
    return doc
