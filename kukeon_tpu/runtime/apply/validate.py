"""Deep per-kind spec validation (the apischeme depth layer).

Reference: internal/apischeme/scheme.go:43-885 + cellblueprint.go /
cellconfig.go / volume.go and internal/apply/parser per-kind validation
(parser.go:220-823). The round-2/3 verdicts flagged that bad manifests
reached the runner before failing; this module makes normalize/parse the
place where every malformed spec dies, with a field-path error message.

Policy on unenforced fields: a field that parses but does nothing is worse
than absence (it reads as a granted capability). Anything the backends do
not enforce yet — ``tmpfs`` volume mounts, ``networks`` attachment lists —
is REJECTED here until the enforcement exists.
"""

from __future__ import annotations

import ipaddress
import re

from kukeon_tpu.runtime import naming
from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.errors import InvalidArgument

_ENV_NAME = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")
_MEMORY = re.compile(r"^\d+(\.\d+)?(Ki|Mi|Gi|Ti|K|M|G|T)?$")
_USER = re.compile(r"^(\d+|[a-z_][a-z0-9_-]*)(:(\d+|[a-z_][a-z0-9_-]*))?$")
# Linux capability names (sans CAP_ prefix tolerated, case-insensitive).
_CAPABILITY = re.compile(r"^(CAP_)?[A-Z_]+$", re.IGNORECASE)
_LOG_LEVELS = ("debug", "info", "warn", "warning", "error")
_MODEL_DTYPES = ("int8", "bfloat16", "float16", "float32")


def _has_param(v) -> bool:
    return isinstance(v, str) and "${" in v


def validate_container(c: t.ContainerSpec, ctx: str, *,
                       in_blueprint: bool = False,
                       is_defaults: bool = False) -> None:
    """Full container validation (reference: scheme.go container rules +
    spec.go mount/device constraints).

    ``in_blueprint``: the spec is a CellBlueprint template whose string
    scalars may carry ``${param}`` placeholders — format checks on such
    values are deferred to materialization (where the substituted cell is
    validated again as a plain cell). Outside blueprints a literal ``${``
    is rejected like any other malformed value.
    """
    where = f"{ctx}: container {c.name!r}"

    def deferred(v) -> bool:
        """True when validation of this scalar belongs to materialization."""
        return in_blueprint and _has_param(v)
    if not is_defaults:
        if not deferred(c.name):
            naming.validate_name(c.name, "container name")
        # Structural, not format: applies even with a parameterized name.
        if not c.command and not c.image:
            raise InvalidArgument(
                f"{where} needs a command (process backend) or image"
            )

    for e in c.env:
        if not _ENV_NAME.match(e.name) and not deferred(e.name):
            raise InvalidArgument(f"{where}: invalid env name {e.name!r}")

    if c.workdir is not None and not deferred(c.workdir):
        if not c.workdir.startswith("/"):
            raise InvalidArgument(f"{where}: workdir must be absolute, got {c.workdir!r}")

    if c.user is not None and not deferred(c.user):
        if not _USER.match(c.user):
            raise InvalidArgument(
                f"{where}: user must be uid[:gid] or name[:group], got {c.user!r}"
            )

    seen_ports: set[tuple[int, str]] = set()
    for p in c.ports:
        proto = (p.protocol or "tcp").lower()
        if deferred(p.protocol):
            continue
        if proto not in ("tcp", "udp"):
            raise InvalidArgument(f"{where}: port protocol must be tcp|udp, got {p.protocol!r}")
        if not (1 <= p.port <= 65535):
            raise InvalidArgument(f"{where}: port {p.port} out of range 1-65535")
        if (p.port, proto) in seen_ports:
            raise InvalidArgument(f"{where}: duplicate port {p.port}/{proto}")
        seen_ports.add((p.port, proto))

    for vm in c.volumes:
        refs = [x for x in (vm.name, vm.host_path) if x]
        if vm.tmpfs:
            if refs:
                raise InvalidArgument(
                    f"{where}: tmpfs mounts take no name/hostPath source"
                )
            if not vm.path or (not deferred(vm.path) and not vm.path.startswith("/")):
                raise InvalidArgument(
                    f"{where}: tmpfs mount needs an absolute path"
                )
            if vm.read_only:
                # tmpfs mounts are always rw scratch; accepting the flag and
                # ignoring it would fake a read-only guarantee.
                raise InvalidArgument(
                    f"{where}: readOnly tmpfs is not supported"
                )
            continue
        if len(refs) != 1:
            raise InvalidArgument(
                f"{where}: volume mount needs exactly one of name|hostPath"
            )
        if vm.host_path and not deferred(vm.host_path) and not vm.host_path.startswith("/"):
            raise InvalidArgument(f"{where}: hostPath must be absolute, got {vm.host_path!r}")
        if vm.path and not deferred(vm.path) and not vm.path.startswith("/"):
            raise InvalidArgument(f"{where}: volume path must be absolute, got {vm.path!r}")
        if vm.name and not deferred(vm.name):
            naming.validate_name(vm.name, "volume name")

    if c.networks:
        raise InvalidArgument(
            f"{where}: `networks` is not supported (cells attach to their "
            "space's network); remove it"
        )

    for cap in c.capabilities:
        if deferred(cap):
            continue
        if not _CAPABILITY.match(cap):
            raise InvalidArgument(f"{where}: invalid capability {cap!r}")

    for opt in c.security_opts:
        if deferred(opt):
            continue
        if opt not in ("seccomp=default", "seccomp=unconfined"):
            raise InvalidArgument(
                f"{where}: securityOpts supports seccomp=default|unconfined, "
                f"got {opt!r}"
            )

    for d in c.devices:
        if deferred(d):
            continue
        if not d.startswith("/dev/"):
            raise InvalidArgument(f"{where}: device must be a /dev path, got {d!r}")

    r = c.resources
    if r.memory is not None and not deferred(r.memory):
        if not _MEMORY.match(r.memory):
            raise InvalidArgument(
                f"{where}: memory must look like 512Mi/2Gi, got {r.memory!r}"
            )
    if r.cpu is not None and r.cpu <= 0:
        raise InvalidArgument(f"{where}: cpu must be > 0, got {r.cpu}")
    if r.pids is not None and r.pids < 1:
        raise InvalidArgument(f"{where}: pids must be >= 1, got {r.pids}")
    if r.tpu_chips is not None and r.tpu_chips < 0:
        raise InvalidArgument(f"{where}: tpuChips must be >= 0")

    for s in c.secrets:
        if not deferred(s.name):
            naming.validate_name(s.name, "secret ref name")
        if s.env is not None and not deferred(s.env) and not _ENV_NAME.match(s.env):
            raise InvalidArgument(f"{where}: secret env {s.env!r} is not a valid env name")
        if s.path is not None and not deferred(s.path) and not s.path.startswith("/"):
            raise InvalidArgument(f"{where}: secret path must be absolute, got {s.path!r}")

    for repo in c.repos:
        if not repo.url and not deferred(repo.url):
            raise InvalidArgument(f"{where}: repo url is required")
        if repo.url and not deferred(repo.url):
            # Must look like a URL/path, and never like a git OPTION — the
            # clone runs under the daemon (root), so a dash-prefixed "url"
            # must die here, not reach git's argv.
            looks_like_url = (
                "://" in repo.url
                or repo.url.startswith("/")
                or re.match(r"^[^@/\s-][^@\s]*@[^:\s]+:", repo.url)
            )
            if repo.url.startswith("-") or not looks_like_url:
                raise InvalidArgument(
                    f"{where}: repo url must be scheme://..., /abs/path, or "
                    f"user@host:path, got {repo.url!r}"
                )
        if not repo.path and not deferred(repo.path):
            raise InvalidArgument(f"{where}: repo path is required")
        if repo.ref and not deferred(repo.ref) and repo.ref.startswith("-"):
            raise InvalidArgument(f"{where}: repo ref cannot start with '-'")

    rp = c.restart_policy
    if deferred(rp.policy):
        pass
    elif rp.policy not in ("always", "on-failure", "never"):
        raise InvalidArgument(
            f"{where}: restartPolicy.policy must be always|on-failure|never, "
            f"got {rp.policy!r}"
        )
    if rp.backoff_seconds < 0:
        raise InvalidArgument(f"{where}: restartPolicy.backoffSeconds must be >= 0")
    if rp.max_retries is not None and rp.max_retries < 0:
        raise InvalidArgument(f"{where}: restartPolicy.maxRetries must be >= 0")

    if c.tty is not None:
        if not c.attachable:
            raise InvalidArgument(
                f"{where}: tty configuration requires `attachable: true` "
                "(reference: tty is the attach wrapper's config)"
            )
        if (c.tty.log_level is not None and not deferred(c.tty.log_level)
                and c.tty.log_level not in _LOG_LEVELS):
            raise InvalidArgument(
                f"{where}: tty.logLevel must be one of {_LOG_LEVELS}, "
                f"got {c.tty.log_level!r}"
            )


def validate_cell(spec: t.CellSpec, ctx: str, *, in_blueprint: bool = False) -> None:
    if not spec.containers and spec.model is None:
        raise InvalidArgument(f"{ctx}: cell needs containers or a model spec")
    seen = set()
    host_ports: set[tuple[int, str]] = set()
    for c in spec.containers:
        if c.name in seen:
            raise InvalidArgument(f"{ctx}: duplicate container name {c.name!r}")
        seen.add(c.name)
        validate_container(c, ctx, in_blueprint=in_blueprint)
        for p in c.ports:
            key = (p.port, (p.protocol or "tcp").lower())
            if key in host_ports:
                raise InvalidArgument(
                    f"{ctx}: port {key[0]}/{key[1]} declared by more than one container"
                )
            host_ports.add(key)
    if spec.model is not None:
        m = spec.model
        if not m.model:
            raise InvalidArgument(f"{ctx}: model.model is required")
        if m.chips < 1:
            raise InvalidArgument(f"{ctx}: model.chips must be >= 1")
        if not (1 <= m.port <= 65535):
            raise InvalidArgument(f"{ctx}: model.port {m.port} out of range")
        if m.replicas < 1:
            raise InvalidArgument(
                f"{ctx}: model.replicas must be >= 1, got {m.replicas}"
            )
        ports = model_ports(m)
        if ports[-1] > 65535:
            raise InvalidArgument(
                f"{ctx}: model.replicas={m.replicas} needs ports "
                f"{ports[0]}..{ports[-1]} (gateway on {m.port}, replicas "
                f"above it), past 65535"
            )
        for p in ports:
            if (p, "tcp") in host_ports:
                raise InvalidArgument(
                    f"{ctx}: model port {p} (of replica range "
                    f"{ports[0]}..{ports[-1]}) collides with a container port"
                )
        if m.min_replicas is not None and m.max_replicas is None:
            raise InvalidArgument(
                f"{ctx}: model.minReplicas without model.maxReplicas does "
                "nothing — set maxReplicas to arm autoscaling")
        if m.max_replicas is not None:
            lo = m.min_replicas if m.min_replicas is not None else 1
            if lo < 1:
                raise InvalidArgument(
                    f"{ctx}: model.minReplicas must be >= 1, got {lo}")
            if m.max_replicas < 2:
                raise InvalidArgument(
                    f"{ctx}: model.maxReplicas must be >= 2 (an autoscaled "
                    "cell serves through the gateway, which needs a "
                    "replicated port range)")
            if m.max_replicas < lo:
                raise InvalidArgument(
                    f"{ctx}: model.maxReplicas ({m.max_replicas}) must be "
                    f">= minReplicas ({lo})")
            if not (lo <= m.replicas <= m.max_replicas):
                raise InvalidArgument(
                    f"{ctx}: model.replicas ({m.replicas}) must sit inside "
                    f"the autoscale bounds [{lo}, {m.max_replicas}]")
            if (m.role or "mixed").strip() != "mixed":
                raise InvalidArgument(
                    f"{ctx}: model.role {m.role!r} cannot combine with "
                    "autoscaling bounds — the scaler assumes a homogeneous "
                    "(mixed) replica pool")
        roles = model_roles(m, ctx)
        if any(r != "mixed" for r in roles):
            # A heterogeneous fleet must still be able to COMPLETE a
            # request: at least one replica that can prefill and one that
            # can decode (mixed counts as both). A lone "prefill" cell
            # would accept work it can never finish — reject at apply.
            if not any(r in ("prefill", "mixed") for r in roles):
                raise InvalidArgument(
                    f"{ctx}: model.role {m.role!r} declares no prefill-"
                    "capable replica (prefill or mixed) — nothing could "
                    "run a prompt's prefill")
            if not any(r in ("decode", "mixed") for r in roles):
                raise InvalidArgument(
                    f"{ctx}: model.role {m.role!r} declares no decode-"
                    "capable replica (decode or mixed) — nothing could "
                    "generate tokens")
        if m.num_slots < 1:
            raise InvalidArgument(f"{ctx}: model.numSlots must be >= 1")
        if m.max_seq_len is not None and m.max_seq_len < 16:
            raise InvalidArgument(f"{ctx}: model.maxSeqLen must be >= 16")
        if m.dtype is not None and m.dtype not in _MODEL_DTYPES:
            raise InvalidArgument(
                f"{ctx}: model.dtype must be one of {_MODEL_DTYPES}, got {m.dtype!r}"
            )
        if m.slo_ttft_p95_ms is not None and m.slo_ttft_p95_ms <= 0:
            raise InvalidArgument(
                f"{ctx}: model.sloTtftP95Ms must be > 0"
            )
        if m.slo_availability is not None and not (
                0.0 < m.slo_availability < 1.0):
            raise InvalidArgument(
                f"{ctx}: model.sloAvailability must be a fraction in (0, 1)"
            )


_MODEL_ROLES = ("mixed", "prefill", "decode")


def model_roles(m: t.ModelSpec, ctx: str | None = None) -> list[str]:
    """Per-replica role list from ``ModelSpec.role`` (one entry per
    replica, declaration order — the same order the runner's base-port
    scheme assigns ports). A single atom applies to every replica; a
    comma-separated list must name each replica exactly once. Raises
    InvalidArgument on malformed input when ``ctx`` is given (the validate
    path); the runner calls it post-validation and may pass None."""
    n = max(1, m.replicas or 1)
    raw = (m.role or "mixed").strip()
    atoms = [a.strip() for a in raw.split(",")] if raw else ["mixed"]
    where = ctx or "ModelSpec"
    for a in atoms:
        if a not in _MODEL_ROLES:
            raise InvalidArgument(
                f"{where}: model.role atom {a!r} must be one of "
                f"{_MODEL_ROLES}")
    if len(atoms) == 1:
        return atoms * n
    if len(atoms) != n:
        raise InvalidArgument(
            f"{where}: model.role lists {len(atoms)} roles for "
            f"{n} replica(s) — give one role per replica (or a single "
            "role for all)")
    return atoms


def model_scale_bound(m: t.ModelSpec) -> int:
    """The largest replica count this spec can ever run: ``maxReplicas``
    when autoscaling is armed, else the static ``replicas``. The runner
    materializes containers, ports, and the chip partition up to this
    bound so a scale-up never renumbers an existing replica's grant."""
    return max(m.replicas or 1, m.max_replicas or 0)


def model_ports(m: t.ModelSpec) -> list[int]:
    """Every TCP port a ModelSpec's cell claims: just ``port`` for a single
    engine; the gateway on ``port`` plus replicas on ``port+1..port+N``
    when replicated (the runner's base-port scheme). An autoscaled cell
    claims its full ``maxReplicas`` range up front — a parked replica's
    port is reserved, never re-leased."""
    n = model_scale_bound(m)
    if n <= 1:
        return [m.port]
    return list(range(m.port, m.port + n + 1))


def validate_manifest(docs: list[t.Document]) -> None:
    """Cross-document depth pass over ONE manifest: two ModelSpecs whose
    replica port ranges overlap would race for the same listen sockets at
    runtime (EADDRINUSE inside a cell, long after apply said ok) — die here
    instead, naming both specs."""
    seen: list[tuple[str, list[int]]] = []
    for d in docs:
        if d.kind != t.KIND_CELL or getattr(d.spec, "model", None) is None:
            continue
        m = d.spec.model
        ports = model_ports(m)
        ctx = f"Cell/{d.metadata.name}"
        for other_ctx, other_ports in seen:
            overlap = sorted(set(ports) & set(other_ports))
            if overlap:
                raise InvalidArgument(
                    f"{ctx}: model port range {ports[0]}..{ports[-1]} "
                    f"collides with {other_ctx} (range "
                    f"{other_ports[0]}..{other_ports[-1]}) on port(s) "
                    f"{overlap}; replicated models claim "
                    "port..port+replicas — give each spec a disjoint range"
                )
        seen.append((ctx, ports))


def validate_space(spec: t.SpaceSpec, ctx: str) -> None:
    net = spec.network
    if net.egress_default not in ("allow", "deny"):
        raise InvalidArgument(
            f"{ctx}: network.egressDefault must be allow|deny, got {net.egress_default!r}"
        )
    for i, rule in enumerate(net.egress_allow):
        rctx = f"{ctx}: network.egressAllow[{i}]"
        if bool(rule.host) == bool(rule.cidr):
            raise InvalidArgument(f"{rctx}: exactly one of host|cidr is required")
        if rule.cidr:
            try:
                ipaddress.ip_network(rule.cidr)
            except ValueError:
                raise InvalidArgument(f"{rctx}: invalid cidr {rule.cidr!r}") from None
        for port in rule.ports:
            if not (1 <= port <= 65535):
                raise InvalidArgument(f"{rctx}: port {port} out of range")
        if (rule.protocol or "tcp").lower() not in ("tcp", "udp"):
            raise InvalidArgument(
                f"{rctx}: protocol must be tcp|udp, got {rule.protocol!r}"
            )
    if spec.subnet is not None:
        try:
            net4 = ipaddress.ip_network(spec.subnet)
        except ValueError:
            raise InvalidArgument(f"{ctx}: invalid subnet {spec.subnet!r}") from None
        if net4.num_addresses < 4:
            raise InvalidArgument(f"{ctx}: subnet {spec.subnet} too small (need >= /30)")
    if spec.container_defaults is not None:
        validate_container(spec.container_defaults, ctx, is_defaults=True)


def validate_secret(spec: t.SecretSpec, ctx: str) -> None:
    if not spec.data:
        raise InvalidArgument(f"{ctx}: secret data must not be empty")
    for k in spec.data:
        if not _ENV_NAME.match(k):
            raise InvalidArgument(f"{ctx}: secret key {k!r} is not a valid env-style name")


def validate_volume(spec: t.VolumeSpec, ctx: str) -> None:
    if spec.reclaim_policy not in ("retain", "delete"):
        raise InvalidArgument(
            f"{ctx}: reclaimPolicy must be retain|delete, got {spec.reclaim_policy!r}"
        )
    if spec.size is not None and not _MEMORY.match(spec.size):
        raise InvalidArgument(f"{ctx}: size must look like 512Mi/2Gi, got {spec.size!r}")


def validate_blueprint(spec: t.CellBlueprintSpec, ctx: str) -> None:
    seen = set()
    for p in spec.params:
        if not _ENV_NAME.match(p.name):
            raise InvalidArgument(f"{ctx}: invalid param name {p.name!r}")
        if p.name in seen:
            raise InvalidArgument(f"{ctx}: duplicate param {p.name!r}")
        seen.add(p.name)
        if p.required and p.default is not None:
            raise InvalidArgument(
                f"{ctx}: param {p.name!r} cannot be both required and defaulted"
            )
    validate_cell(spec.cell, ctx, in_blueprint=True)


def validate_cell_config(spec: t.CellConfigSpec, ctx: str) -> None:
    if not spec.blueprint:
        raise InvalidArgument(f"{ctx}: CellConfig.spec.blueprint is required")
    naming.validate_name(spec.blueprint, "blueprint reference")
    for k in spec.values:
        if not _ENV_NAME.match(k):
            raise InvalidArgument(f"{ctx}: invalid value key {k!r}")
    slots = set()
    for b in spec.secrets:
        if not b.slot or not b.secret:
            raise InvalidArgument(f"{ctx}: secret binding needs slot and secret")
        if b.slot in slots:
            raise InvalidArgument(f"{ctx}: duplicate secret slot {b.slot!r}")
        slots.add(b.slot)
        naming.validate_name(b.secret, "secret name")
    for e in spec.env:
        if not _ENV_NAME.match(e.name):
            raise InvalidArgument(f"{ctx}: invalid env name {e.name!r}")
    if spec.cell_name is not None:
        naming.validate_name(spec.cell_name, "cellName")


def validate_spec(kind: str, spec, ctx: str) -> None:
    """Dispatch: deep-validate a kind's spec (no-op for kinds without one)."""
    if kind == t.KIND_CELL:
        validate_cell(spec, ctx)
    elif kind == t.KIND_CONTAINER:
        validate_container(spec, ctx)
    elif kind == t.KIND_SPACE:
        validate_space(spec, ctx)
    elif kind == t.KIND_SECRET:
        validate_secret(spec, ctx)
    elif kind == t.KIND_VOLUME:
        validate_volume(spec, ctx)
    elif kind == t.KIND_CELL_BLUEPRINT:
        validate_blueprint(spec, ctx)
    elif kind == t.KIND_CELL_CONFIG:
        validate_cell_config(spec, ctx)
