"""Global KUKEON-FORWARD ingress-admission chain.

Reference: internal/firewall/forward.go:17-130. The host FORWARD policy may
be DROP (Docker does this); kukeon owns one admission chain that accepts
(1) established/related return traffic and (2) *external* ingress to kukeon
bridges. Egress admission deliberately lives per-space (fail-closed) — see
netpolicy.py. The ``! -i k-+`` scope on the ingress rule keeps inter-bridge
egress flowing through the per-space chains instead of being admitted here.
"""

from __future__ import annotations

from kukeon_tpu.runtime.net.bridge import BRIDGE_PREFIX
from kukeon_tpu.runtime.net.runners import CommandRunner

FORWARD_CHAIN = "KUKEON-FORWARD"
BRIDGE_MATCH = BRIDGE_PREFIX + "+"      # iptables interface wildcard
_TAG = "kukeon-forward"


def admission_rules() -> list[list[str]]:
    """Pure, ordered rule list for the admission chain (testable w/o fakes)."""
    return [
        ["-A", FORWARD_CHAIN,
         "-m", "conntrack", "--ctstate", "RELATED,ESTABLISHED",
         "-m", "comment", "--comment", f"{_TAG}:established",
         "-j", "ACCEPT"],
        ["-A", FORWARD_CHAIN,
         "!", "-i", BRIDGE_MATCH, "-o", BRIDGE_MATCH,
         "-m", "comment", "--comment", f"{_TAG}:ingress",
         "-j", "ACCEPT"],
    ]


class ForwardInstaller:
    """Idempotent installer: ensure chain, populate, ensure FORWARD jump."""

    def __init__(self, runner: CommandRunner):
        self.runner = runner

    def available(self) -> bool:
        return self.runner.available("iptables")

    def _ipt(self, *args: str) -> tuple[int, str]:
        return self.runner.run(["iptables", *args])

    def install(self) -> None:
        code, _ = self._ipt("-n", "-L", FORWARD_CHAIN)
        if code != 0:
            self._ipt("-N", FORWARD_CHAIN)
        self._ipt("-F", FORWARD_CHAIN)
        for rule in admission_rules():
            self._ipt(*rule)
        code, _ = self._ipt("-C", "FORWARD", "-j", FORWARD_CHAIN)
        if code != 0:
            self._ipt("-I", "FORWARD", "1", "-j", FORWARD_CHAIN)

    def uninstall(self) -> None:
        self._ipt("-D", "FORWARD", "-j", FORWARD_CHAIN)
        self._ipt("-F", FORWARD_CHAIN)
        self._ipt("-X", FORWARD_CHAIN)
