"""Per-space subnet allocation from a parent pool.

Reference behavior (internal/cni/subnet.go:66-146): carve /24 chunks from
10.88.0.0/16; each space's assignment persists as ``network.json`` under the
space's metadata dir, and the allocator re-scans those files on every
Allocate so it survives daemon restarts with no separate cache.
"""

from __future__ import annotations

import ipaddress
import threading

from kukeon_tpu.runtime import consts
from kukeon_tpu.runtime.errors import FailedPrecondition, InvalidArgument
from kukeon_tpu.runtime.store import ResourceStore

STATE_VERSION = "v1"
STATE_FILE = "network.json"


class SubnetAllocator:
    """Hands out per-space subnets; on-disk state is the source of truth."""

    def __init__(self, store: ResourceStore,
                 parent_cidr: str = consts.DEFAULT_SUBNET_POOL,
                 prefix_len: int = 24):
        try:
            self.parent = ipaddress.ip_network(parent_cidr)
        except ValueError as e:
            raise InvalidArgument(f"invalid subnet pool {parent_cidr!r}: {e}") from e
        if self.parent.version != 4:
            raise InvalidArgument(f"subnet pool {parent_cidr!r} must be IPv4")
        if prefix_len <= self.parent.prefixlen or prefix_len > 32:
            raise InvalidArgument(
                f"prefix /{prefix_len} must be longer than parent "
                f"/{self.parent.prefixlen} and at most /32"
            )
        self.store = store
        self.prefix_len = prefix_len
        self._mu = threading.Lock()

    # --- on-disk state ------------------------------------------------------

    def read_state(self, realm: str, space: str) -> dict | None:
        return self.store.ms.read_json_or(
            None, *self.store.space_parts(realm, space), STATE_FILE
        )

    def _write_state(self, realm: str, space: str, state: dict) -> None:
        self.store.ms.write_json(
            state, *self.store.space_parts(realm, space), STATE_FILE
        )

    def in_use(self) -> dict[str, str]:
        """subnetCIDR -> "realm/space" for every persisted assignment."""
        out: dict[str, str] = {}
        for realm in self.store.list_realms():
            for space in self.store.list_spaces(realm):
                st = self.read_state(realm, space)
                if st and st.get("subnetCIDR"):
                    out[st["subnetCIDR"]] = f"{realm}/{space}"
        return out

    # --- allocation ---------------------------------------------------------

    def allocate(self, realm: str, space: str, requested: str | None = None) -> str:
        """Return the space's subnet CIDR, allocating one if needed.

        A ``requested`` CIDR (Space.spec.subnet) is honored if it is inside
        the pool and not taken by another space; re-calling with the same
        request is idempotent.
        """
        with self._mu:
            existing = self.read_state(realm, space)
            if existing and existing.get("subnetCIDR"):
                if requested and existing["subnetCIDR"] != requested:
                    raise FailedPrecondition(
                        f"space {realm}/{space} already has subnet "
                        f"{existing['subnetCIDR']}; cannot change to {requested}"
                    )
                return existing["subnetCIDR"]

            used = self.in_use()
            me = f"{realm}/{space}"
            # Overlap detection must be by network math, not string equality:
            # a requested CIDR with a different prefix length would otherwise
            # silently overlap auto-allocated /24s.
            used_nets = {
                ipaddress.ip_network(cidr): owner
                for cidr, owner in used.items()
            }
            if requested:
                net = self._validate_requested(requested)
                for other, owner in used_nets.items():
                    if owner != me and net.overlaps(other):
                        raise FailedPrecondition(
                            f"subnet {requested} overlaps {other} "
                            f"(allocated to {owner})"
                        )
                chosen = str(net)
            else:
                chosen = None
                for cand in self.parent.subnets(new_prefix=self.prefix_len):
                    if not any(cand.overlaps(n) for n in used_nets):
                        chosen = str(cand)
                        break
                if chosen is None:
                    raise FailedPrecondition(
                        f"subnet pool {self.parent} exhausted "
                        f"({len(used)} spaces allocated)"
                    )
            self._write_state(realm, space, {
                "version": STATE_VERSION, "subnetCIDR": chosen,
            })
            return chosen

    def release(self, realm: str, space: str) -> None:
        self.store.ms.delete(*self.store.space_parts(realm, space), STATE_FILE)

    def _validate_requested(self, cidr: str):
        try:
            net = ipaddress.ip_network(cidr)
        except ValueError as e:
            raise InvalidArgument(f"invalid subnet {cidr!r}: {e}") from e
        if net.version != 4:
            raise InvalidArgument(f"subnet {cidr!r} must be IPv4")
        if not net.subnet_of(self.parent):
            raise InvalidArgument(
                f"subnet {cidr} is outside the pool {self.parent}"
            )
        if net.prefixlen < self.prefix_len:
            raise InvalidArgument(
                f"subnet {cidr} is wider than the per-space /"
                f"{self.prefix_len} carve"
            )
        return net


def gateway_ip(subnet_cidr: str) -> str:
    """First usable address of the subnet — the bridge's address."""
    net = ipaddress.ip_network(subnet_cidr)
    return str(next(net.hosts()))
