"""Command-runner seam for everything that shells out (ip, iptables).

The reference isolates iptables/bridge shelling behind CommandRunner/
BridgeRunner interfaces with fakes (netpolicy/enforcer_test.go:33,
cni/bridge_test.go:34); same pattern here so unit tests never need root.
"""

from __future__ import annotations

import shutil
import subprocess


class CommandRunner:
    """Runs argv (optionally with stdin payload), returns (exit_code, output)."""

    def run(self, argv: list[str], input: str | None = None) -> tuple[int, str]:
        raise NotImplementedError

    def available(self, binary: str) -> bool:
        raise NotImplementedError


class ShellRunner(CommandRunner):
    def run(self, argv: list[str], input: str | None = None) -> tuple[int, str]:
        try:
            p = subprocess.run(
                argv, capture_output=True, text=True, timeout=30, check=False,
                input=input,
            )
        except (OSError, subprocess.TimeoutExpired) as e:
            return 127, str(e)
        return p.returncode, (p.stdout or "") + (p.stderr or "")

    def available(self, binary: str) -> bool:
        return shutil.which(binary) is not None


class FakeRunner(CommandRunner):
    """Records every invocation; scriptable responses by argv prefix."""

    def __init__(self, fail_prefixes: list[list[str]] | None = None,
                 binaries: set[str] | None = None):
        self.calls: list[list[str]] = []
        self.inputs: list[str | None] = []
        self.fail_prefixes = fail_prefixes or []
        self.binaries = binaries  # None = everything available

    def run(self, argv: list[str], input: str | None = None) -> tuple[int, str]:
        self.calls.append(list(argv))
        self.inputs.append(input)
        for pfx in self.fail_prefixes:
            if argv[: len(pfx)] == pfx:
                return 1, f"fake failure for {pfx}"
        return 0, ""

    def available(self, binary: str) -> bool:
        return self.binaries is None or binary in self.binaries

    def calls_for(self, binary: str) -> list[list[str]]:
        return [c for c in self.calls if c and c[0] == binary]
