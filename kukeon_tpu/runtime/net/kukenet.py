"""kukenet driver: whole-table netfilter programming without iptables.

Many minimal hosts compile the iptables kernel side in (xt_conntrack,
xt_comment, xt_tcpudp, ...) but ship no userspace tool. The native
``kukenet`` binary speaks the xtables ABI directly (IPT_SO_SET_REPLACE);
this module renders the COMPLETE desired filter table — forward admission
(firewall.py) + every space's egress chain (netpolicy.py) — into kukenet's
line protocol and commits it atomically, preserving the reference's
fail-closed property (enforcer.go:34-232 via iptables-restore --noflush:
a default-deny chain never exists without its terminal DROP).

Table layout mirrors the reference:

  FORWARD:       -j KUKEON-EGRESS   (egress policy first)
                 -j KUKEON-FORWARD  (admission for return/external traffic)
  KUKEON-EGRESS: per-space dispatch by bridge interface
  KUKEON-EGRESS-<realm>-<space>: established + allows + terminal verdict
  KUKEON-FORWARD: established + external-ingress admission
"""

from __future__ import annotations

import logging
import os
import subprocess

from kukeon_tpu.runtime.net.firewall import FORWARD_CHAIN
from kukeon_tpu.runtime.net.bridge import BRIDGE_PREFIX
from kukeon_tpu.runtime.net.netpolicy import MASTER_CHAIN, Enforcer, Policy

log = logging.getLogger("kukeon.net")

_BIN_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bin"
)
KUKENET = os.path.join(_BIN_DIR, "kukenet")

BRIDGE_WILDCARD = BRIDGE_PREFIX + "+"


def kukenet_usable(path: str = KUKENET) -> bool:
    """True when the kernel xtables ABI answers and we may program it."""
    if not os.access(path, os.X_OK) or os.geteuid() != 0:
        return False
    try:
        return subprocess.run([path, "check"], capture_output=True,
                              timeout=5).returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


def render_table(policies: list[Policy]) -> str:
    """Full filter-table spec (kukenet line protocol) for these policies."""
    lines = [
        "policy INPUT ACCEPT",
        "policy FORWARD ACCEPT",
        "policy OUTPUT ACCEPT",
        f"chain {FORWARD_CHAIN}",
        f"chain {MASTER_CHAIN}",
    ]
    for p in policies:
        lines.append(f"chain {p.chain_name()}")
    # FORWARD hook: egress policy first, then admission.
    lines.append(f"rule chain=FORWARD verdict={MASTER_CHAIN}")
    lines.append(f"rule chain=FORWARD verdict={FORWARD_CHAIN}")
    # Admission chain (firewall.py semantics).
    lines.append(
        f"rule chain={FORWARD_CHAIN} state=EST_REL verdict=ACCEPT "
        "comment=kukeon-forward:established"
    )
    lines.append(
        f"rule chain={FORWARD_CHAIN} in=!{BRIDGE_WILDCARD} "
        f"out={BRIDGE_WILDCARD} verdict=ACCEPT comment=kukeon-forward:ingress"
    )
    # Per-space dispatch + chains.
    for p in policies:
        lines.append(
            f"rule chain={MASTER_CHAIN} in={p.bridge} "
            f"verdict={p.chain_name()} comment={p.comment_tag()}:dispatch"
        )
    for p in policies:
        chain = p.chain_name()
        tag = p.comment_tag()
        lines.append(
            f"rule chain={chain} state=EST_REL verdict=ACCEPT "
            f"comment={tag}:established"
        )
        for i, r in enumerate(p.allow):
            targets = [r.cidr] if r.cidr else [f"{ip}/32" for ip in r.ips]
            label = (f"allow[{i}]:host={r.original_host}" if r.original_host
                     else f"allow[{i}]:cidr={r.cidr}")
            for dst in targets:
                if r.ports:
                    proto = r.protocol or "tcp"
                    for port in r.ports:
                        lines.append(
                            f"rule chain={chain} dst={dst} proto={proto} "
                            f"dport={port} verdict=ACCEPT comment={tag}:{label}"
                        )
                else:
                    proto_part = f"proto={r.protocol} " if r.protocol else ""
                    lines.append(
                        f"rule chain={chain} dst={dst} {proto_part}"
                        f"verdict=ACCEPT comment={tag}:{label}"
                    )
        terminal = "DROP" if p.default == "deny" else "ACCEPT"
        lines.append(
            f"rule chain={chain} verdict={terminal} comment={tag}:default"
        )
    return "\n".join(lines) + "\n"


class KukenetEnforcer(Enforcer):
    """Stateful whole-table enforcer: tracks the desired policy per space
    and re-commits the complete table on every change/reconcile tick."""

    def __init__(self, kukenet: str = KUKENET):
        self.kukenet = kukenet
        self.policies: dict[str, Policy] = {}   # chain name -> policy
        self._batching = False
        # Whole-table replace + in-memory desired state means a freshly
        # restarted daemon must NOT commit before it has re-collected every
        # space's policy — doing so would wipe live deny chains (fail-open).
        # The kernel keeps the previous run's table until the first complete
        # reconcile pass primes us.
        self._primed = False

    def available(self) -> bool:
        return kukenet_usable(self.kukenet)

    def _commit(self) -> None:
        if self._batching or not self._primed:
            return
        spec = render_table(list(self.policies.values()))
        res = subprocess.run([self.kukenet, "apply"], input=spec,
                             capture_output=True, text=True)
        if res.returncode != 0:
            log.error("kukenet apply failed (%d): %s",
                      res.returncode, res.stderr.strip())

    def begin_batch(self) -> None:
        self._batching = True

    def end_batch(self, complete: bool) -> None:
        """Commit the batch. ``complete=True`` asserts every space was
        collected, which arms commits for good; an incomplete pass keeps
        the previous kernel table (stale-but-closed beats open)."""
        self._batching = False
        if complete:
            self._primed = True
            self._commit()
        elif self._primed:
            # Already primed: the in-memory set is still the full desired
            # state (the failed space keeps its last good policy entry).
            self._commit()
        else:
            log.warning("kukenet: incomplete first reconcile; keeping the "
                        "previous kernel table")

    def apply(self, p: Policy) -> None:
        self.policies[p.chain_name()] = p
        self._commit()

    def remove(self, p: Policy) -> None:
        self.policies.pop(p.chain_name(), None)
        self._commit()

    def install_admission(self) -> None:
        """Admission rules ride every commit; just assert the base table."""
        self._commit()

    def dump(self) -> str:
        res = subprocess.run([self.kukenet, "dump"], capture_output=True,
                             text=True)
        return res.stdout
