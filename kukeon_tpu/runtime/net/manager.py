"""NetworkManager: glue between the store and the network primitives.

The controller calls ``ensure_space_network`` on space ensure and
``reconcile_all`` each tick (reference: ReconcileSpaceNetworks,
reconcile.go:52-66 — re-assert conflist + bridge + egress chain so a reboot
that flushed iptables/bridges converges within one interval); the daemon
calls ``install_forward`` at boot (server.go:151-196).

Enforcement is automatic: live ``ip``/``iptables`` programming happens only
when the binaries exist and we are root; otherwise the manager still
allocates subnets, renders conflists, and computes policies (so unit tests
and non-root dev hosts exercise the full control path) but skips the shell.
``KUKEON_NET_ENFORCE=0|1`` overrides the autodetection.
"""

from __future__ import annotations

import os

from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.api.wire import from_wire
from kukeon_tpu.runtime.net.bridge import BridgeManager, write_conflist
from kukeon_tpu.runtime.net.firewall import ForwardInstaller
from kukeon_tpu.runtime.net.netpolicy import (
    IptablesEnforcer,
    NoopEnforcer,
    resolve_policy,
)
from kukeon_tpu.runtime.net.runners import CommandRunner, ShellRunner
from kukeon_tpu.runtime.net.slice import discover_slice, slice_mesh_rules
from kukeon_tpu.runtime.net.subnet import SubnetAllocator
from kukeon_tpu.runtime.store import ResourceStore


def _enforcement_enabled(runner: CommandRunner) -> bool:
    override = os.environ.get("KUKEON_NET_ENFORCE")
    if override is not None:
        return override not in ("0", "false", "")
    return (
        os.geteuid() == 0
        and runner.available("ip")
        and runner.available("iptables")
    )


class NetworkManager:
    def __init__(self, store: ResourceStore,
                 runner: CommandRunner | None = None,
                 subnet_pool: str | None = None,
                 resolver=None):
        self.store = store
        self.runner = runner or ShellRunner()
        self.subnets = SubnetAllocator(
            store, parent_cidr=subnet_pool or _pool_from_env()
        )
        self.enforcing = _enforcement_enabled(self.runner)
        self.bridges = BridgeManager(self.runner)
        self.enforcer = (IptablesEnforcer(self.runner) if self.enforcing
                         else NoopEnforcer())
        self.forward = ForwardInstaller(self.runner)
        self.resolver = resolver
        self.slice_topology = discover_slice()

    # --- bootstrap ----------------------------------------------------------

    def install_forward(self) -> None:
        if self.enforcing:
            self.forward.install()

    # --- per-space ----------------------------------------------------------

    def ensure_space_network(self, realm: str, space: str,
                             spec: t.SpaceSpec) -> dict:
        subnet = self.subnets.allocate(realm, space, spec.subnet)
        space_dir = self.store.ms.ensure_dir(*self.store.space_parts(realm, space))
        conflist_path = write_conflist(space_dir, realm, space, subnet)
        # When not enforcing, skip DNS: the resolved IPs would be discarded,
        # and a dead hostname would stall the reconcile ticker on resolver
        # timeouts for nothing.
        resolver = self.resolver if self.enforcing else _null_resolver
        policy = resolve_policy(realm, space, spec.network, resolver=resolver)
        policy.allow.extend(
            slice_mesh_rules(self.slice_topology, resolver=resolver)
        )
        bridge = policy.bridge
        if self.enforcing:
            bridge = self.bridges.ensure(realm, space, subnet)
            self.enforcer.apply(policy)
        return {
            "subnet": subnet,
            "bridge": bridge,
            "conflist": conflist_path,
            "egressDefault": policy.default,
            "egressRules": len(policy.allow),
            "enforcing": self.enforcing,
        }

    def teardown_space_network(self, realm: str, space: str,
                               spec: t.SpaceSpec | None = None) -> None:
        spec = spec or t.SpaceSpec()
        policy = resolve_policy(realm, space, spec.network,
                                resolver=self.resolver or (lambda h: []))
        if self.enforcing:
            self.enforcer.remove(policy)
            self.bridges.teardown(realm, space)
        self.subnets.release(realm, space)

    # --- reconcile ----------------------------------------------------------

    def space_spec(self, realm: str, space: str) -> t.SpaceSpec:
        rec = self.store.read_space(realm, space)
        return from_wire(t.SpaceSpec, rec.spec_json or {})

    def reconcile_all(self) -> dict[str, dict]:
        """Re-assert every space's subnet/conflist/bridge/egress chain."""
        out: dict[str, dict] = {}
        for realm in self.store.list_realms():
            for space in self.store.list_spaces(realm):
                try:
                    spec = self.space_spec(realm, space)
                    out[f"{realm}/{space}"] = self.ensure_space_network(
                        realm, space, spec
                    )
                except Exception as e:  # noqa: BLE001 — one bad space must not stall the tick
                    out[f"{realm}/{space}"] = {"error": f"{type(e).__name__}: {e}"}
        return out


def _null_resolver(host: str) -> list[str]:
    return []


def _pool_from_env() -> str:
    from kukeon_tpu.runtime import consts

    return os.environ.get("KUKEON_POD_SUBNET_CIDR", consts.DEFAULT_SUBNET_POOL)
