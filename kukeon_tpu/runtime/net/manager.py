"""NetworkManager: glue between the store and the network primitives.

The controller calls ``ensure_space_network`` on space ensure and
``reconcile_all`` each tick (reference: ReconcileSpaceNetworks,
reconcile.go:52-66 — re-assert conflist + bridge + egress chain so a reboot
that flushed iptables/bridges converges within one interval); the daemon
calls ``install_forward`` at boot (server.go:151-196).

Enforcement is automatic: live ``ip``/``iptables`` programming happens only
when the binaries exist and we are root; otherwise the manager still
allocates subnets, renders conflists, and computes policies (so unit tests
and non-root dev hosts exercise the full control path) but skips the shell.
``KUKEON_NET_ENFORCE=0|1`` overrides the autodetection.
"""

from __future__ import annotations

import os

from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.api.wire import from_wire
from kukeon_tpu.runtime.net.bridge import BridgeManager, write_conflist
from kukeon_tpu.runtime.net.firewall import ForwardInstaller
from kukeon_tpu.runtime.net.netpolicy import (
    IptablesEnforcer,
    NoopEnforcer,
    resolve_policy,
)
from kukeon_tpu.runtime.net.runners import CommandRunner, ShellRunner
from kukeon_tpu.runtime.net.slice import discover_slice, slice_mesh_rules
from kukeon_tpu.runtime.net.subnet import SubnetAllocator, gateway_ip
from kukeon_tpu.runtime.net.veth import IPAllocator, VethManager, host_ifname
from kukeon_tpu.runtime.store import ResourceStore


def _enforcement_enabled(runner: CommandRunner) -> bool:
    from kukeon_tpu.runtime.net.kukenet import kukenet_usable

    override = os.environ.get("KUKEON_NET_ENFORCE")
    if override is not None:
        return override not in ("0", "false", "")
    return (
        os.geteuid() == 0
        and runner.available("ip")
        and (runner.available("iptables") or kukenet_usable())
    )


class NetworkManager:
    def __init__(self, store: ResourceStore,
                 runner: CommandRunner | None = None,
                 subnet_pool: str | None = None,
                 resolver=None):
        self.store = store
        self.runner = runner or ShellRunner()
        self.subnets = SubnetAllocator(
            store, parent_cidr=subnet_pool or _pool_from_env()
        )
        self.enforcing = _enforcement_enabled(self.runner)
        self.bridges = BridgeManager(self.runner)
        # Enforcer preference: the iptables CLI when present (interops with
        # other tools' rules), else the native kukenet whole-table driver.
        from kukeon_tpu.runtime.net.kukenet import KukenetEnforcer, kukenet_usable

        if self.enforcing and self.runner.available("iptables"):
            self.enforcer = IptablesEnforcer(self.runner)
        elif self.enforcing and kukenet_usable():
            self.enforcer = KukenetEnforcer()
        else:
            self.enforcer = NoopEnforcer()
        self.forward = ForwardInstaller(self.runner)
        self.resolver = resolver
        self.slice_topology = discover_slice()
        self.veth = VethManager(self.runner)
        self.ipam = IPAllocator(store)

    # --- bootstrap ----------------------------------------------------------

    def install_forward(self) -> None:
        if not self.enforcing:
            return
        from kukeon_tpu.runtime.net.kukenet import KukenetEnforcer

        if isinstance(self.enforcer, KukenetEnforcer):
            self.enforcer.install_admission()   # rides the whole-table commit
        else:
            self.forward.install()
        # Routed cell traffic needs forwarding on (the CNI bridge plugin
        # does the same).
        try:
            with open("/proc/sys/net/ipv4/ip_forward", "w") as f:
                f.write("1")
        except OSError:
            pass

    # --- per-cell -----------------------------------------------------------

    def attach_cell(self, realm: str, space: str, owner: str,
                    sandbox_pid: int) -> str | None:
        """Join a cell sandbox's netns to its space bridge; returns the cell
        IP (persisted per space; stable across restarts)."""
        if not self.enforcing:
            return None
        subnet = self.subnets.allocate(realm, space)
        bridge = self.bridges.ensure(realm, space, subnet)
        ip = self.ipam.allocate(realm, space, subnet, owner)
        prefix = subnet.split("/")[1]
        self.veth.attach(
            sandbox_pid, bridge, host_ifname(owner),
            f"{ip}/{prefix}", gateway_ip(subnet),
        )
        return ip

    def detach_cell(self, realm: str, space: str, owner: str) -> None:
        if not self.enforcing:
            return
        self.veth.detach(host_ifname(owner))
        self.ipam.release(realm, space, owner)

    # --- per-space ----------------------------------------------------------

    def ensure_space_network(self, realm: str, space: str,
                             spec: t.SpaceSpec) -> dict:
        subnet = self.subnets.allocate(realm, space, spec.subnet)
        space_dir = self.store.ms.ensure_dir(*self.store.space_parts(realm, space))
        conflist_path = write_conflist(space_dir, realm, space, subnet)
        # When not enforcing, skip DNS: the resolved IPs would be discarded,
        # and a dead hostname would stall the reconcile ticker on resolver
        # timeouts for nothing.
        resolver = self.resolver if self.enforcing else _null_resolver
        policy = resolve_policy(realm, space, spec.network, resolver=resolver)
        # Intra-space traffic is always allowed: cells of one space reach
        # each other (agent cells -> their model cell) even under
        # default-deny, which governs what LEAVES the space. Hosts with
        # br_netfilter enabled push bridged (same-bridge) frames through
        # FORWARD, so without this rule a deny space would sever its own
        # cells from each other. Cross-space stays denied: the dispatch
        # matches the source bridge, and another space's subnet is not
        # covered by this rule.
        from kukeon_tpu.runtime.net.netpolicy import ResolvedRule

        policy.allow.insert(0, ResolvedRule(
            cidr=subnet, original_host="intra-space",
        ))
        policy.allow.extend(
            slice_mesh_rules(self.slice_topology, resolver=resolver)
        )
        bridge = policy.bridge
        if self.enforcing:
            bridge = self.bridges.ensure(realm, space, subnet)
            self.enforcer.apply(policy)
        return {
            "subnet": subnet,
            "bridge": bridge,
            "conflist": conflist_path,
            "egressDefault": policy.default,
            "egressRules": len(policy.allow),
            "enforcing": self.enforcing,
        }

    def teardown_space_network(self, realm: str, space: str,
                               spec: t.SpaceSpec | None = None) -> None:
        spec = spec or t.SpaceSpec()
        policy = resolve_policy(realm, space, spec.network,
                                resolver=self.resolver or (lambda h: []))
        if self.enforcing:
            self.enforcer.remove(policy)
            self.bridges.teardown(realm, space)
        self.subnets.release(realm, space)

    # --- reconcile ----------------------------------------------------------

    def space_spec(self, realm: str, space: str) -> t.SpaceSpec:
        rec = self.store.read_space(realm, space)
        return from_wire(t.SpaceSpec, rec.spec_json or {})

    def reconcile_all(self) -> dict[str, dict]:
        """Re-assert every space's subnet/conflist/bridge/egress chain.

        The whole-table kukenet driver commits once per pass, and only
        primes (arms commits after a daemon restart) when the pass covered
        every space — an incomplete first pass must keep the previous
        kernel table rather than wipe not-yet-collected deny chains."""
        out: dict[str, dict] = {}
        batched = hasattr(self.enforcer, "begin_batch")
        if batched:
            self.enforcer.begin_batch()
        complete = True
        try:
            for realm in self.store.list_realms():
                for space in self.store.list_spaces(realm):
                    try:
                        spec = self.space_spec(realm, space)
                        out[f"{realm}/{space}"] = self.ensure_space_network(
                            realm, space, spec
                        )
                    except Exception as e:  # noqa: BLE001 — one bad space must not stall the tick
                        complete = False
                        out[f"{realm}/{space}"] = {"error": f"{type(e).__name__}: {e}"}
        finally:
            if batched:
                self.enforcer.end_batch(complete)
        return out


def _null_resolver(host: str) -> list[str]:
    return []


def _pool_from_env() -> str:
    from kukeon_tpu.runtime import consts

    return os.environ.get("KUKEON_POD_SUBNET_CIDR", consts.DEFAULT_SUBNET_POOL)
