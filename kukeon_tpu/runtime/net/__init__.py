"""Space networking: subnets, bridges, egress policy, host firewall, slices.

Capability parity with the reference's internal/cni + internal/netpolicy +
internal/firewall (SURVEY.md §2.6), redesigned for a TPU-VM host:

- ``subnet``: per-space subnet allocator carving /24s from 10.88.0.0/16;
  on-disk per-space state is the source of truth (survives daemon restarts).
- ``bridge``: deterministic ``k-<8hex>`` bridge naming + conflist rendering +
  idempotent bridge ensure/teardown behind a command-runner seam.
- ``netpolicy``: pure egress-rule generator (fail-closed per-space chains)
  + iptables enforcer behind the same seam + a noop enforcer for read-only
  clients and hosts without iptables.
- ``firewall``: the global KUKEON-FORWARD ingress-admission chain.
- ``slice``: TPU pod-slice awareness — worker discovery + the realm-mesh
  rules that let a default-deny realm span the v5e slice's host NICs
  (BASELINE north star: "internal/cni + internal/netpolicy become
  pod-slice-aware").
- ``manager``: NetworkManager gluing the above to the metadata store; the
  controller calls it on space ensure/delete and each reconcile tick.
"""

from kukeon_tpu.runtime.net.runners import CommandRunner, FakeRunner, ShellRunner
from kukeon_tpu.runtime.net.subnet import SubnetAllocator
from kukeon_tpu.runtime.net.bridge import BridgeManager, bridge_name
from kukeon_tpu.runtime.net.netpolicy import (
    IptablesEnforcer,
    NoopEnforcer,
    Policy,
    ResolvedRule,
    build_rules,
    dispatch_rule,
    resolve_policy,
)
from kukeon_tpu.runtime.net.firewall import (
    FORWARD_CHAIN,
    ForwardInstaller,
    admission_rules,
)
from kukeon_tpu.runtime.net.slice import SliceTopology, discover_slice, slice_mesh_rules
from kukeon_tpu.runtime.net.manager import NetworkManager

__all__ = [
    "BridgeManager",
    "CommandRunner",
    "FORWARD_CHAIN",
    "FakeRunner",
    "ForwardInstaller",
    "IptablesEnforcer",
    "NetworkManager",
    "NoopEnforcer",
    "Policy",
    "ResolvedRule",
    "ShellRunner",
    "SliceTopology",
    "SubnetAllocator",
    "admission_rules",
    "bridge_name",
    "build_rules",
    "discover_slice",
    "dispatch_rule",
    "resolve_policy",
    "slice_mesh_rules",
]
