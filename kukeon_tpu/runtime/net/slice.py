"""TPU pod-slice awareness for space networking.

BASELINE north star: "internal/cni + internal/netpolicy become
pod-slice-aware so a Realm's default-deny mesh spans a v5e slice over the
TPU host network". On a multi-host slice (e.g. v5e-16+), each TPU-VM worker
talks to its peers over the host NICs (DCN): the libtpu runtime gRPC port
plus the megascale/premapped ports. ICI collectives inside one worker's
chips never touch the host network and need no rules.

Discovery is env-driven (the TPU runtime exports worker topology into every
TPU VM) with an injectable fallback, so tests and non-TPU hosts work
without GCE metadata:

- ``TPU_WORKER_HOSTNAMES`` — comma-separated peer hostnames/IPs
- ``TPU_WORKER_ID`` — this worker's index
- ``KUKEON_SLICE_WORKERS`` — operator override (takes precedence)
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from kukeon_tpu.runtime.net.netpolicy import ResolvedRule

# Host-network ports the TPU runtime uses between slice workers:
# 8471: libtpu runtime gRPC; 8476-8480: mesh controller / megascale DCN
# transfers; 8431-8434: worker health/telemetry. Operators can extend via
# KUKEON_SLICE_PORTS.
DEFAULT_SLICE_PORTS = [8471, 8476, 8477, 8478, 8479, 8480, 8431, 8432, 8433, 8434]


@dataclass
class SliceTopology:
    worker_id: int = 0
    workers: list[str] = field(default_factory=list)   # hostnames or IPs
    ports: list[int] = field(default_factory=lambda: list(DEFAULT_SLICE_PORTS))

    @property
    def multi_host(self) -> bool:
        return len(self.workers) > 1

    def peers(self) -> list[str]:
        return [w for i, w in enumerate(self.workers) if i != self.worker_id]


def discover_slice(env: dict[str, str] | None = None) -> SliceTopology:
    env = os.environ if env is None else env
    workers_s = env.get("KUKEON_SLICE_WORKERS") or env.get("TPU_WORKER_HOSTNAMES", "")
    workers = [w.strip() for w in workers_s.split(",") if w.strip()]
    ports_s = env.get("KUKEON_SLICE_PORTS", "")
    ports = ([int(p) for p in ports_s.split(",") if p.strip()]
             if ports_s else list(DEFAULT_SLICE_PORTS))
    try:
        worker_id = int(env.get("TPU_WORKER_ID", "0"))
    except ValueError:
        worker_id = 0
    return SliceTopology(worker_id=worker_id, workers=workers, ports=ports)


def slice_mesh_rules(topo: SliceTopology, resolver=None) -> list[ResolvedRule]:
    """Egress allowlist entries admitting peer-worker DCN traffic.

    Appended to every space policy of a slice-spanning realm so default-deny
    spaces keep the TPU runtime's worker-to-worker traffic alive. Hostname
    peers re-resolve on each reconcile tick (same drift story as user rules).
    """
    if not topo.multi_host:
        return []
    from kukeon_tpu.runtime.net.netpolicy import resolve_host

    rules = []
    for peer in topo.peers():
        ips, original = resolve_host(peer, resolver)
        rules.append(ResolvedRule(ips=ips, original_host=original,
                                  ports=list(topo.ports)))
    return rules
