"""Bridge naming, conflist rendering, and idempotent bridge lifecycle.

Reference: internal/cni (manager.go, bridge.go:32-70, network.go). Bridges
are named ``k-<8 hex>`` from a hash of realm/space (SafeBridgeName pattern;
the ``k-+`` iptables wildcard in the firewall admission rules depends on
this prefix). The conflist is rendered per space and persisted next to the
space's metadata; bridge creation shells out via the runner seam so tests
never need root.
"""

from __future__ import annotations

import hashlib
import json
import os

from kukeon_tpu.runtime.net.runners import CommandRunner
from kukeon_tpu.runtime.net.subnet import gateway_ip

BRIDGE_PREFIX = "k-"
CONFLIST_FILE = "network.conflist"


def bridge_name(realm: str, space: str) -> str:
    """Deterministic ``k-<8 hex>`` interface name (IFNAMSIZ-safe)."""
    h = hashlib.sha256(f"{realm}/{space}".encode()).hexdigest()[:8]
    return BRIDGE_PREFIX + h


def render_conflist(realm: str, space: str, subnet_cidr: str) -> dict:
    """CNI-compatible conflist document (bridge + host-local IPAM shape).

    Rendered for interoperability with standard CNI tooling even though the
    process backend programs the bridge directly; a containerd backend can
    hand this file to the CNI plugins unchanged.
    """
    return {
        "cniVersion": "1.0.0",
        "name": f"kukeon-{realm}-{space}",
        "plugins": [
            {
                "type": "bridge",
                "bridge": bridge_name(realm, space),
                "isGateway": True,
                "ipMasq": True,
                "hairpinMode": True,
                "ipam": {
                    "type": "host-local",
                    "ranges": [[{"subnet": subnet_cidr}]],
                    "routes": [{"dst": "0.0.0.0/0"}],
                },
            },
            {"type": "portmap", "capabilities": {"portMappings": True}},
        ],
    }


class BridgeManager:
    """Create/teardown Linux bridges for spaces, idempotently."""

    def __init__(self, runner: CommandRunner):
        self.runner = runner

    def available(self) -> bool:
        return self.runner.available("ip")

    def exists(self, name: str) -> bool:
        code, _ = self.runner.run(["ip", "link", "show", name])
        return code == 0

    def ensure(self, realm: str, space: str, subnet_cidr: str) -> str:
        """Idempotently create the bridge, address it with the subnet's
        gateway IP, and bring it up. Returns the bridge name."""
        name = bridge_name(realm, space)
        if not self.exists(name):
            self.runner.run(["ip", "link", "add", name, "type", "bridge"])
        gw = gateway_ip(subnet_cidr)
        prefix = subnet_cidr.split("/")[1]
        # addr add is not idempotent; tolerate EEXIST by checking first.
        code, out = self.runner.run(["ip", "-o", "addr", "show", "dev", name])
        if code != 0 or f"{gw}/{prefix}" not in out:
            self.runner.run(["ip", "addr", "add", f"{gw}/{prefix}", "dev", name])
        self.runner.run(["ip", "link", "set", name, "up"])
        return name

    def teardown(self, realm: str, space: str) -> None:
        name = bridge_name(realm, space)
        if self.exists(name):
            self.runner.run(["ip", "link", "set", name, "down"])
            self.runner.run(["ip", "link", "delete", name, "type", "bridge"])


def write_conflist(space_dir: str, realm: str, space: str, subnet_cidr: str) -> str:
    path = os.path.join(space_dir, CONFLIST_FILE)
    doc = render_conflist(realm, space, subnet_cidr)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
    os.replace(tmp, path)
    return path
