"""Per-cell network attachment: veth pair into the space bridge + IPAM.

The reference does CNI ADD/DEL per cell against the bridge/host-local
plugins (internal/cni/container.go; release-before-recreate ordering
start.go:310-348). Here the runner owns it natively: the cell sandbox's
netns (kukecell) gets one end of a veth pair renamed to eth0 with an IP
from the space's subnet; the host end joins the space bridge. IP
assignments persist per space (host-local-IPAM analog) and survive daemon
restarts; the veth dies with the sandbox's netns automatically, so crash
cleanup is structural rather than scripted.
"""

from __future__ import annotations

import hashlib
import ipaddress
import logging

from kukeon_tpu.runtime.errors import FailedPrecondition
from kukeon_tpu.runtime.net.runners import CommandRunner
from kukeon_tpu.runtime.net.subnet import gateway_ip
from kukeon_tpu.runtime.store import ResourceStore

log = logging.getLogger("kukeon.net")

IPAM_FILE = "ipam.json"
# 'kv-' prefix: deliberately NOT 'k-' so the per-space egress dispatch
# (matching in=k-<bridge>) and the admission wildcard never confuse a cell
# veth for a bridge.
VETH_PREFIX = "kv-"


def host_ifname(owner: str) -> str:
    """Deterministic IFNAMSIZ-safe host-side veth name for a cell."""
    return VETH_PREFIX + hashlib.sha256(owner.encode()).hexdigest()[:10]


class IPAllocator:
    """Per-space IP assignment, persisted under the space dir."""

    def __init__(self, store: ResourceStore):
        self.store = store

    def _state_parts(self, realm: str, space: str):
        return (*self.store.space_parts(realm, space), IPAM_FILE)

    def allocate(self, realm: str, space: str, subnet: str, owner: str) -> str:
        with self.store.ms.lock():
            state = self.store.ms.read_json_or({}, *self._state_parts(realm, space))
            for ip, o in state.items():
                if o == owner:
                    return ip
            net = ipaddress.ip_network(subnet)
            gw = gateway_ip(subnet)
            for host in net.hosts():
                ip = str(host)
                if ip == gw or ip in state:
                    continue
                state[ip] = owner
                self.store.ms.write_json(state, *self._state_parts(realm, space))
                return ip
        raise FailedPrecondition(f"subnet {subnet} exhausted in {realm}/{space}")

    def release(self, realm: str, space: str, owner: str) -> None:
        with self.store.ms.lock():
            state = self.store.ms.read_json_or({}, *self._state_parts(realm, space))
            remaining = {ip: o for ip, o in state.items() if o != owner}
            if len(remaining) != len(state):
                self.store.ms.write_json(remaining, *self._state_parts(realm, space))

    def lookup(self, realm: str, space: str, owner: str) -> str | None:
        state = self.store.ms.read_json_or({}, *self._state_parts(realm, space))
        for ip, o in state.items():
            if o == owner:
                return ip
        return None


class VethManager:
    """Create/destroy the veth pair joining a sandbox netns to a bridge."""

    def __init__(self, runner: CommandRunner):
        self.runner = runner

    def _ns(self, pid: int, *cmd: str) -> tuple[int, str]:
        return self.runner.run(["nsenter", "-t", str(pid), "-n", *cmd])

    def attached(self, host_if: str) -> bool:
        code, _ = self.runner.run(["ip", "link", "show", host_if])
        return code == 0

    def attach(self, sandbox_pid: int, bridge: str, host_if: str,
               ip_cidr: str, gateway: str) -> None:
        """Idempotent: an existing host_if means the attachment (and the
        sandbox holding its peer) survived a daemon restart."""
        if self.attached(host_if):
            return
        peer = host_if + "c"
        code, out = self.runner.run(
            ["ip", "link", "add", host_if, "type", "veth", "peer",
             "name", peer]
        )
        if code != 0:
            raise FailedPrecondition(f"veth create failed: {out.strip()}")
        steps = [
            ["ip", "link", "set", peer, "netns", str(sandbox_pid)],
            ["ip", "link", "set", host_if, "master", bridge],
            ["ip", "link", "set", host_if, "up"],
        ]
        for argv in steps:
            code, out = self.runner.run(argv)
            if code != 0:
                self.detach(host_if)
                raise FailedPrecondition(
                    f"{' '.join(argv)} failed: {out.strip()}"
                )
        ns_steps = [
            ("ip", "link", "set", "lo", "up"),
            ("ip", "link", "set", peer, "name", "eth0"),
            ("ip", "addr", "add", ip_cidr, "dev", "eth0"),
            ("ip", "link", "set", "eth0", "up"),
            ("ip", "route", "add", "default", "via", gateway),
        ]
        for argv in ns_steps:
            code, out = self._ns(sandbox_pid, *argv)
            if code != 0:
                self.detach(host_if)
                raise FailedPrecondition(
                    f"in-netns {' '.join(argv)} failed: {out.strip()}"
                )

    def detach(self, host_if: str) -> None:
        """Best-effort: the veth vanishes with the netns anyway."""
        if self.attached(host_if):
            self.runner.run(["ip", "link", "del", host_if])
