"""Space egress policy -> iptables rules.

Reference: internal/netpolicy (policy.go:17-27, rules.go:43-154,
enforcer.go:34-232, resolver.go:28-74). Design points kept:

- **Pure rule generator** — no I/O — so tests compare rule lists directly.
- **Fail-closed per-space chains**: the per-space chain terminates every
  packet itself (ACCEPT or DROP); there is no host-global egress blanket,
  so a missing chain on a default-deny space means no connectivity, never
  silent unrestricted egress.
- **Hostnames resolve at apply time** and re-resolve on every reconcile
  tick so DNS drift converges within one interval.
- Chain per space: ``KUKEON-EGRESS-<realm>-<space>`` (truncated+hashed to
  iptables' 28-char chain-name limit), dispatched from the shared
  ``KUKEON-EGRESS`` master chain by bridge interface.
"""

from __future__ import annotations

import hashlib
import ipaddress
import logging
import socket
from dataclasses import dataclass, field

log = logging.getLogger("kukeon.net")

from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.net.bridge import bridge_name
from kukeon_tpu.runtime.net.runners import CommandRunner

MASTER_CHAIN = "KUKEON-EGRESS"
_CHAIN_MAX = 28  # iptables chain-name limit


@dataclass
class ResolvedRule:
    """One allowlist entry with hostnames flattened to concrete targets."""

    cidr: str = ""
    ips: list[str] = field(default_factory=list)
    ports: list[int] = field(default_factory=list)
    protocol: str | None = None      # None = all protocols (port-less rules)
    original_host: str = ""


@dataclass
class Policy:
    realm: str = ""
    space: str = ""
    default: str = "allow"           # allow | deny
    allow: list[ResolvedRule] = field(default_factory=list)

    @property
    def bridge(self) -> str:
        return bridge_name(self.realm, self.space)

    def chain_name(self) -> str:
        base = f"{MASTER_CHAIN}-{self.realm}-{self.space}"
        if len(base) <= _CHAIN_MAX:
            return base
        h = hashlib.sha256(f"{self.realm}/{self.space}".encode()).hexdigest()[:8]
        return f"{MASTER_CHAIN}-{h}"

    def comment_tag(self) -> str:
        return f"kukeon:{self.realm}/{self.space}"


def resolve_policy(realm: str, space: str, spec: t.NetworkSpec,
                   resolver=None) -> Policy:
    """Flatten a NetworkSpec into a Policy, resolving hostnames NOW.

    ``resolver(host) -> list[str]`` is injectable for tests; default uses
    getaddrinfo. Unresolvable hosts contribute no targets (the reconcile
    tick retries), matching the reference's drift-tolerant behavior.
    """
    resolver = resolver or _dns_resolve
    rules = []
    for r in spec.egress_allow:
        # ports without protocol mean tcp; a port-less rule with no
        # protocol admits every protocol (an explicit `protocol: udp` on a
        # port-less rule still constrains it to udp).
        proto = r.protocol.lower() if r.protocol else ("tcp" if r.ports else None)
        rr = ResolvedRule(ports=list(r.ports), protocol=proto)
        if r.cidr:
            rr.cidr = r.cidr
        elif r.host:
            rr.ips, rr.original_host = resolve_host(r.host, resolver)
        rules.append(rr)
    return Policy(realm=realm, space=space, default=spec.egress_default,
                  allow=rules)


def resolve_host(host: str, resolver=None) -> tuple[list[str], str]:
    """(ips, original_host): IP literals pass through (original_host "");
    hostnames resolve via ``resolver`` — empty on failure so the next
    reconcile tick retries. Shared by egress rules and slice-mesh rules."""
    try:
        ipaddress.ip_address(host)
        return [host], ""
    except ValueError:
        pass
    resolver = resolver or _dns_resolve
    try:
        return resolver(host), host
    except OSError:
        return [], host


def _dns_resolve(host: str) -> list[str]:
    infos = socket.getaddrinfo(host, None, family=socket.AF_INET)
    return sorted({i[4][0] for i in infos})


# --- pure rule generation ----------------------------------------------------


@dataclass(frozen=True)
class Rule:
    op: str                  # "-A" | "-I"
    chain: str
    args: tuple[str, ...]

    def argv(self) -> list[str]:
        return [self.op, self.chain, *self.args]


def build_rules(p: Policy) -> list[Rule]:
    """Ordered rules for the per-space chain: established-accept, allowlist
    accepts, then the terminal ACCEPT/DROP (the chain decides every packet)."""
    chain = p.chain_name()
    tag = p.comment_tag()
    rules = [Rule("-A", chain, (
        "-m", "conntrack", "--ctstate", "RELATED,ESTABLISHED",
        "-m", "comment", "--comment", f"{tag}:established",
        "-j", "ACCEPT",
    ))]
    for i, r in enumerate(p.allow):
        rules.extend(_allow_rules(chain, tag, i, r))
    terminal = "DROP" if p.default == "deny" else "ACCEPT"
    rules.append(Rule("-A", chain, (
        "-m", "comment", "--comment", f"{tag}:default", "-j", terminal,
    )))
    return rules


def _allow_rules(chain: str, tag: str, idx: int, r: ResolvedRule) -> list[Rule]:
    targets = [r.cidr] if r.cidr else [f"{ip}/32" for ip in r.ips]
    label = (f"allow[{idx}]:host={r.original_host}" if r.original_host
             else f"allow[{idx}]:cidr={r.cidr}")
    out = []
    for dst in targets:
        if not r.ports:
            proto_args = ("-p", r.protocol) if r.protocol else ()
            out.append(Rule("-A", chain, (
                "-d", dst, *proto_args,
                "-m", "comment", "--comment", f"{tag}:{label}",
                "-j", "ACCEPT",
            )))
            continue
        for port in r.ports:
            out.append(Rule("-A", chain, (
                "-d", dst, "-p", r.protocol or "tcp", "--dport", str(port),
                "-m", "comment", "--comment", f"{tag}:{label}",
                "-j", "ACCEPT",
            )))
    return out


def dispatch_rule(p: Policy) -> Rule:
    """Master-chain entry funneling the space's bridge traffic into its chain."""
    return Rule("-A", MASTER_CHAIN, (
        "-i", p.bridge,
        "-m", "comment", "--comment", f"{p.comment_tag()}:dispatch",
        "-j", p.chain_name(),
    ))


# --- enforcement -------------------------------------------------------------


class Enforcer:
    def apply(self, p: Policy) -> None:
        raise NotImplementedError

    def remove(self, p: Policy) -> None:
        raise NotImplementedError


class NoopEnforcer(Enforcer):
    """For read-only clients and hosts without iptables (reference has the
    same class for exactly that purpose)."""

    def apply(self, p: Policy) -> None:
        pass

    def remove(self, p: Policy) -> None:
        pass


def restore_payload(p: Policy) -> str:
    """iptables-restore snippet that atomically replaces the space's chain.

    With ``iptables-restore --noflush``, only chains declared with a
    ``:NAME`` line are flushed-and-rebuilt inside one kernel commit — so a
    default-deny space never has a window where its chain exists without
    its terminal DROP (the flush-then-append approach leaks egress between
    the flush and the rebuild on every reconcile tick)."""
    lines = ["*filter", f":{p.chain_name()} - [0:0]"]
    for rule in build_rules(p):
        args = " ".join(_quote(a) for a in rule.args)
        lines.append(f"{rule.op} {rule.chain} {args}")
    lines.append("COMMIT")
    return "\n".join(lines) + "\n"


def _quote(arg: str) -> str:
    return f'"{arg}"' if (" " in arg or arg == "") else arg


class IptablesEnforcer(Enforcer):
    def __init__(self, runner: CommandRunner):
        self.runner = runner

    def available(self) -> bool:
        return (self.runner.available("iptables")
                and self.runner.available("iptables-restore"))

    def _ipt(self, *args: str, ok_codes: tuple[int, ...] = (0,)) -> tuple[int, str]:
        # -w: wait for the xtables lock instead of failing when Docker or a
        # concurrent reconcile holds it — a silently skipped -A on a deny
        # space is fail-open.
        code, out = self.runner.run(["iptables", "-w", *args])
        if code not in ok_codes:
            log.warning("iptables -w %s failed (%d): %s",
                        " ".join(args), code, out.strip())
        return code, out

    def _ensure_chain(self, chain: str) -> None:
        code, _ = self.runner.run(["iptables", "-w", "-n", "-L", chain])
        if code != 0:
            self._ipt("-N", chain)

    def apply(self, p: Policy) -> None:
        """Re-assert the space's chain (atomic replace) + ensure dispatch."""
        self._ensure_chain(MASTER_CHAIN)
        code, out = self.runner.run(["iptables-restore", "-w", "--noflush"],
                                    input=restore_payload(p))
        if code != 0:
            log.error("iptables-restore for %s failed (%d): %s",
                      p.chain_name(), code, out.strip())
        # Dispatch jump: add only if absent (-C probes; nonzero is expected).
        d = dispatch_rule(p)
        code, _ = self.runner.run(["iptables", "-w", "-C", d.chain, *d.args])
        if code != 0:
            self._ipt("-A", d.chain, *d.args)
        # Master chain must be reachable from FORWARD.
        code, _ = self.runner.run(["iptables", "-w", "-C", "FORWARD",
                                   "-j", MASTER_CHAIN])
        if code != 0:
            self._ipt("-I", "FORWARD", "1", "-j", MASTER_CHAIN)

    def remove(self, p: Policy) -> None:
        chain = p.chain_name()
        d = dispatch_rule(p)
        self._ipt("-D", d.chain, *d.args)
        self._ipt("-F", chain)
        self._ipt("-X", chain)
