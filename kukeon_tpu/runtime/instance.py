"""Instance pinning: a run path remembers how it was bootstrapped.

Reference: internal/instance/instance.go:17-60 — `.kukeon-instance.json`
pins the namespace-suffix + cgroup-root a run path was provisioned under,
and the daemon refuses to start against a run path whose configuration has
drifted (re-pointing a daemon at state bootstrapped under different
settings corrupts subnets, cgroups, and backend assumptions silently).

The TPU build's identity facts: the subnet pool the space subnets were
carved from, the cgroup base the trees were created under, and the cell
backend flavor (namespace sandboxes vs host processes — records written by
one cannot be supervised by the other).
"""

from __future__ import annotations

import json
import os

from kukeon_tpu.runtime import consts
from kukeon_tpu.runtime.errors import FailedPrecondition


def _path(run_path: str) -> str:
    return os.path.join(run_path, consts.INSTANCE_FILE)


def pin_or_verify(run_path: str, facts: dict[str, str]) -> None:
    """First boot writes the facts (O_EXCL); later boots must match.

    A mismatch names every drifted fact and how to recover (re-bootstrap a
    fresh run path, or restore the original setting).
    """
    path = _path(run_path)
    try:
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644)
    except FileExistsError:
        with open(path) as f:
            pinned = json.load(f)
        drift = {
            k: (pinned.get(k), v)
            for k, v in facts.items()
            if k in pinned and pinned[k] != v
        }
        if drift:
            detail = "; ".join(
                f"{k}: bootstrapped with {old!r}, now {new!r}"
                for k, (old, new) in sorted(drift.items())
            )
            raise FailedPrecondition(
                f"run path {run_path} was bootstrapped under different "
                f"settings ({detail}). Restore the original settings or "
                f"bootstrap a fresh --run-path."
            )
        return
    with os.fdopen(fd, "w") as f:
        json.dump(facts, f, indent=1)


def read(run_path: str) -> dict | None:
    try:
        with open(_path(run_path)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
