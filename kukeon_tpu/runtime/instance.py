"""Instance pinning: a run path remembers how it was bootstrapped.

Reference: internal/instance/instance.go:17-60 — `.kukeon-instance.json`
pins the namespace-suffix + cgroup-root a run path was provisioned under,
and the daemon refuses to start against a run path whose configuration has
drifted (re-pointing a daemon at state bootstrapped under different
settings corrupts subnets, cgroups, and backend assumptions silently).

The TPU build's identity facts: the subnet pool the space subnets were
carved from, the cgroup base the trees were created under, and the cell
backend flavor (namespace sandboxes vs host processes — records written by
one cannot be supervised by the other).
"""

from __future__ import annotations

import json
import os

from kukeon_tpu.runtime import consts
from kukeon_tpu.runtime.errors import FailedPrecondition


def _path(run_path: str) -> str:
    return os.path.join(run_path, consts.INSTANCE_FILE)


def pin_or_verify(run_path: str, facts: dict[str, str]) -> None:
    """First boot writes the facts (O_EXCL); later boots must match.

    A mismatch names every drifted fact and how to recover (re-bootstrap a
    fresh run path, or restore the original setting).
    """
    path = _path(run_path)
    try:
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644)
    except FileExistsError:
        try:
            with open(path) as f:
                pinned = json.load(f)
        except ValueError:
            # Torn first-boot write (crash mid-dump before this code wrote
            # atomically): an unreadable pin must not crash-loop the daemon
            # with a raw traceback forever — re-pin the current facts.
            _atomic_write(path, facts)
            return
        drift = {
            k: (pinned.get(k), v)
            for k, v in facts.items()
            if k in pinned and pinned[k] != v
        }
        if drift:
            detail = "; ".join(
                f"{k}: bootstrapped with {old!r}, now {new!r}"
                for k, (old, new) in sorted(drift.items())
            )
            raise FailedPrecondition(
                f"run path {run_path} was bootstrapped under different "
                f"settings ({detail}). Restore the original settings or "
                f"bootstrap a fresh --run-path."
            )
        return
    # O_EXCL reserved the slot; the content lands atomically via a sibling
    # temp file so a crash can never leave a half-written pin.
    os.close(fd)
    _atomic_write(path, facts)


def _atomic_write(path: str, facts: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(facts, f, indent=1)
    os.replace(tmp, path)


def read(run_path: str) -> dict | None:
    try:
        with open(_path(run_path)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
