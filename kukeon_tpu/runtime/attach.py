"""Attach client: raw terminal <-> kuketty unix socket.

Terminal bytes flow directly between this client and the in-cell kuketty —
never through the daemon RPC — so daemon restarts don't drop live terminals
(reference design point: cmd/kuke/attach/attach.go:17-23).

Wire format to kuketty: [1B type][4B BE len][payload]; 'D' data, 'W' resize
(u16 rows, u16 cols). Server->client is the raw PTY byte stream.
Detach: Ctrl-] pressed twice in a row.
"""

from __future__ import annotations

import os
import select
import signal
import socket
import struct
import sys
import termios
import time
import tty as tty_mod

DETACH_KEY = b"\x1d"   # Ctrl-]
PING_BUDGET_S = 10.0   # reference: run/attach.go:47-57
PING_BACKOFF_S = 0.2


def connect(socket_path: str, budget_s: float = PING_BUDGET_S) -> socket.socket:
    """Dial with a retry budget (kuketty may still be claiming the socket)."""
    deadline = time.monotonic() + budget_s
    last_err: Exception | None = None
    while time.monotonic() < deadline:
        try:
            s = socket.socket(socket.AF_UNIX)
            s.connect(socket_path)
            return s
        except OSError as e:
            last_err = e
            time.sleep(PING_BACKOFF_S)
    raise OSError(f"cannot reach terminal socket {socket_path}: {last_err}")


def _send_frame(sock: socket.socket, typ: bytes, payload: bytes) -> None:
    sock.sendall(typ + struct.pack(">I", len(payload)) + payload)


def _send_winsize(sock: socket.socket) -> None:
    try:
        import fcntl

        data = fcntl.ioctl(sys.stdout.fileno(), termios.TIOCGWINSZ, b"\x00" * 8)
        rows, cols, _, _ = struct.unpack("HHHH", data)
        _send_frame(sock, b"W", struct.pack(">HH", rows, cols))
    except (OSError, ValueError):
        pass


def run_attach(socket_path: str, stdin=None, stdout=None) -> int:
    """Interactive attach; returns 0 on detach, 1 if the session ended."""
    stdin = stdin if stdin is not None else sys.stdin
    stdout = stdout if stdout is not None else sys.stdout
    sock = connect(socket_path)

    interactive = stdin.isatty()
    old_attrs = None
    if interactive:
        old_attrs = termios.tcgetattr(stdin.fileno())
        # TCSADRAIN (not the default TCSAFLUSH): keystrokes typed while the
        # client was starting up must not be discarded.
        tty_mod.setraw(stdin.fileno(), termios.TCSADRAIN)
        _send_winsize(sock)
        signal.signal(signal.SIGWINCH, lambda *_: _send_winsize(sock))

    pending = b""   # a trailing Ctrl-] held back from the previous read
    rc = 1
    try:
        stdout.write("(attached — Ctrl-] Ctrl-] to detach)\r\n")
        stdout.flush()
        while True:
            r, _, _ = select.select([sock, stdin], [], [])
            if sock in r:
                data = sock.recv(4096)
                if not data:
                    break   # workload exited / kuketty gone
                stdout.buffer.write(data) if hasattr(stdout, "buffer") else stdout.write(
                    data.decode(errors="replace")
                )
                stdout.flush()
            if stdin in r:
                data = os.read(stdin.fileno(), 4096)
                if not data:
                    break
                combined = pending + data
                if DETACH_KEY + DETACH_KEY in combined:
                    before = combined.split(DETACH_KEY + DETACH_KEY, 1)[0]
                    if before:
                        _send_frame(sock, b"D", before)
                    rc = 0
                    break
                if combined.endswith(DETACH_KEY):
                    pending = DETACH_KEY   # hold it; maybe the pair completes
                    combined = combined[:-1]
                else:
                    pending = b""
                if combined:
                    _send_frame(sock, b"D", combined)
    finally:
        if old_attrs is not None:
            termios.tcsetattr(stdin.fileno(), termios.TCSADRAIN, old_attrs)
        sock.close()
    return rc
