"""Typed resource store over the metadata layout.

Path scheme (consts.py): realms/<r>/spaces/<s>/stacks/<st>/cells/<c>/...
Scoped resources (secrets/blueprints/configs/volumes) live under their
owning scope dir.
"""

from __future__ import annotations

from kukeon_tpu.runtime import consts, model, naming
from kukeon_tpu.runtime.errors import NotFound
from kukeon_tpu.runtime.metadata import MetadataStore


class ResourceStore:
    def __init__(self, ms: MetadataStore):
        self.ms = ms

    # --- scope paths -------------------------------------------------------
    # Every name becomes a path component, so every path helper validates —
    # verbs like DeleteRealm must not accept "../../other" (the metadata
    # store's escape guard is the backstop; this gives the clean error).

    def realm_parts(self, realm: str) -> tuple[str, ...]:
        naming.validate_name(realm, "realm")
        return (consts.REALMS_DIR, realm)

    def space_parts(self, realm: str, space: str) -> tuple[str, ...]:
        naming.validate_name(space, "space")
        return (*self.realm_parts(realm), consts.SPACES_DIR, space)

    def stack_parts(self, realm: str, space: str, stack: str) -> tuple[str, ...]:
        naming.validate_name(stack, "stack")
        return (*self.space_parts(realm, space), consts.STACKS_DIR, stack)

    def cell_parts(self, realm: str, space: str, stack: str, cell: str) -> tuple[str, ...]:
        naming.validate_name(cell, "cell")
        return (*self.stack_parts(realm, space, stack), consts.CELLS_DIR, cell)

    def container_dir(self, realm: str, space: str, stack: str, cell: str, container: str) -> str:
        return self.ms.ensure_dir(
            *self.cell_parts(realm, space, stack, cell), consts.CONTAINERS_DIR, container
        )

    def scope_parts(self, realm: str, space: str | None, stack: str | None) -> tuple[str, ...]:
        if stack is not None and space is not None:
            return self.stack_parts(realm, space, stack)
        if space is not None:
            return self.space_parts(realm, space)
        return self.realm_parts(realm)

    # --- scope records -----------------------------------------------------

    def write_scope(self, rec: model.ScopeRecord) -> None:
        if rec.kind == "Realm":
            parts = (*self.realm_parts(rec.name), "realm.json")
        elif rec.kind == "Space":
            parts = (*self.space_parts(rec.realm, rec.name), "space.json")
        else:
            parts = (*self.stack_parts(rec.realm, rec.space, rec.name), "stack.json")
        self.ms.write_json(rec.to_json(), *parts)

    def read_realm(self, realm: str) -> model.ScopeRecord:
        d = self.ms.read_json_or(None, *self.realm_parts(realm), "realm.json")
        if d is None:
            raise NotFound(f"realm {realm!r} not found")
        return model.ScopeRecord.from_json(d)

    def read_space(self, realm: str, space: str) -> model.ScopeRecord:
        d = self.ms.read_json_or(None, *self.space_parts(realm, space), "space.json")
        if d is None:
            raise NotFound(f"space {realm}/{space} not found")
        return model.ScopeRecord.from_json(d)

    def read_stack(self, realm: str, space: str, stack: str) -> model.ScopeRecord:
        d = self.ms.read_json_or(None, *self.stack_parts(realm, space, stack), "stack.json")
        if d is None:
            raise NotFound(f"stack {realm}/{space}/{stack} not found")
        return model.ScopeRecord.from_json(d)

    def list_realms(self) -> list[str]:
        return self.ms.list_dirs(consts.REALMS_DIR)

    def list_spaces(self, realm: str) -> list[str]:
        return self.ms.list_dirs(*self.realm_parts(realm), consts.SPACES_DIR)

    def list_stacks(self, realm: str, space: str) -> list[str]:
        return self.ms.list_dirs(*self.space_parts(realm, space), consts.STACKS_DIR)

    def list_cells(self, realm: str, space: str, stack: str) -> list[str]:
        return self.ms.list_dirs(*self.stack_parts(realm, space, stack), consts.CELLS_DIR)

    # --- cell records ------------------------------------------------------

    def write_cell(self, rec: model.CellRecord) -> None:
        self.ms.write_json(
            rec.to_json(), *self.cell_parts(rec.realm, rec.space, rec.stack, rec.name), "cell.json"
        )

    def read_cell(self, realm: str, space: str, stack: str, cell: str) -> model.CellRecord:
        d = self.ms.read_json_or(None, *self.cell_parts(realm, space, stack, cell), "cell.json")
        if d is None:
            raise NotFound(f"cell {realm}/{space}/{stack}/{cell} not found")
        return model.CellRecord.from_json(d)

    def cell_exists(self, realm: str, space: str, stack: str, cell: str) -> bool:
        return self.ms.exists(*self.cell_parts(realm, space, stack, cell), "cell.json")

    def delete_cell_tree(self, realm: str, space: str, stack: str, cell: str) -> bool:
        return self.ms.delete_tree(*self.cell_parts(realm, space, stack, cell))

    # --- scoped resources --------------------------------------------------

    def write_scoped(self, kind_dir: str, realm: str, space: str | None,
                     stack: str | None, name: str, doc: dict) -> None:
        self.ms.write_json(doc, *self.scope_parts(realm, space, stack), kind_dir, f"{name}.json")

    def read_scoped(self, kind_dir: str, realm: str, space: str | None,
                    stack: str | None, name: str) -> dict | None:
        return self.ms.read_json_or(
            None, *self.scope_parts(realm, space, stack), kind_dir, f"{name}.json"
        )

    def resolve_scoped(self, kind_dir: str, realm: str, space: str | None,
                       stack: str | None, name: str) -> dict | None:
        """Look up a scoped resource from the innermost scope outward
        (stack -> space -> realm), the reference's resolution order."""
        scopes = []
        if space is not None and stack is not None:
            scopes.append((realm, space, stack))
        if space is not None:
            scopes.append((realm, space, None))
        scopes.append((realm, None, None))
        for r, s, st in scopes:
            d = self.read_scoped(kind_dir, r, s, st, name)
            if d is not None:
                return d
        return None

    def list_scoped(self, kind_dir: str, realm: str, space: str | None = None,
                    stack: str | None = None) -> list[str]:
        return [
            f[: -len(".json")]
            for f in self.ms.list_files(*self.scope_parts(realm, space, stack), kind_dir)
        ]

    def delete_scoped(self, kind_dir: str, realm: str, space: str | None,
                      stack: str | None, name: str) -> bool:
        return self.ms.delete(*self.scope_parts(realm, space, stack), kind_dir, f"{name}.json")
