"""Namespace-isolated cells: the real isolation layer.

TPU-native redesign of the reference's containerd path (internal/ctr/
spec.go:309-511 builds OCI specs with namespaces/mounts/devices/security;
internal/ctr/container.go drives the runtime): instead of an external
container runtime, the native ``kukecell`` helper owns the namespace
surgery and the supervisors stay host-side:

- per cell, a **sandbox**: UTS+IPC+NET+PID namespaces with ``kukepause``
  as in-namespace PID 1 (its reference role, cmd/kukepause/main.go:17-62);
- per container, the supervisor (kukeshim/kuketty) runs on the host —
  exit files, logs and the attach socket keep their daemon-restart-safe
  host paths — and execs the workload through ``kukecell enter``, which
  joins the sandbox, pivot_roots onto the image rootfs, builds a minimal
  /dev containing ONLY granted device nodes (airtight chip partitioning,
  reference devices.go:23-171), applies volume/secret binds, drops
  capabilities, and honors privileged/hostNetwork/hostPID/readOnlyRoot.

``available()`` reports whether this host can run namespaced cells
(root + kukecell binary); the daemon auto-selects the backend on that.
"""

from __future__ import annotations

import os
import signal
import subprocess
import time

from kukeon_tpu.runtime.cells.backend import ContainerContext
from kukeon_tpu.runtime.cells.process import (
    BIN_DIR,
    ProcessBackend,
    _pid_alive,
)
from kukeon_tpu.runtime.errors import FailedPrecondition

KUKECELL = os.path.join(BIN_DIR, "kukecell")
KUKEPAUSE = os.path.join(BIN_DIR, "kukepause")

SANDBOX_PID_FILE = "sandbox.pid"


def available() -> bool:
    """Can this host run namespaced cells?"""
    override = os.environ.get("KUKEON_ISOLATION")
    if override is not None:
        return override not in ("0", "false", "process", "")
    return os.geteuid() == 0 and os.access(KUKECELL, os.X_OK)


class NamespaceBackend(ProcessBackend):
    isolated = True

    def __init__(self, shim: str | None = None, tty: str | None = None,
                 kukecell: str = KUKECELL, pause: str = KUKEPAUSE):
        super().__init__()
        if shim:
            self.shim = shim
        if tty:
            self.tty = tty
        self.kukecell = kukecell
        self.pause = pause

    # --- sandbox lifecycle --------------------------------------------------

    def ensure_sandbox(self, cell_dir: str, hostname: str) -> int:
        pid = self.sandbox_pid(cell_dir)
        if pid is not None:
            return pid
        os.makedirs(cell_dir, exist_ok=True)
        pid_file = os.path.join(cell_dir, SANDBOX_PID_FILE)
        res = subprocess.run(
            [self.kukecell, "sandbox", "--pid-file", pid_file,
             "--hostname", hostname, "--pause", self.pause],
            capture_output=True, text=True,
        )
        if res.returncode != 0:
            raise FailedPrecondition(
                f"sandbox creation failed (rc={res.returncode}): "
                f"{res.stderr.strip()}"
            )
        pid = self._read_pid(pid_file)
        if pid is None or not _pid_alive(pid):
            raise FailedPrecondition("sandbox pause process did not come up")
        return pid

    @staticmethod
    def _is_pause(pid: int) -> bool:
        """Guard against recycled pids: only ever join/kill a process that
        really is our pause binary (host reboot can hand sandbox.pid's pid
        to an arbitrary process)."""
        try:
            with open(f"/proc/{pid}/comm") as f:
                return f.read().strip() == "kukepause"
        except OSError:
            return False

    def sandbox_pid(self, cell_dir: str) -> int | None:
        """Live sandbox pid, re-derived from disk + /proc (restart-safe)."""
        pid = self._read_pid(os.path.join(cell_dir, SANDBOX_PID_FILE))
        if pid and _pid_alive(pid) and self._is_pause(pid):
            return pid
        return None

    def teardown_sandbox(self, cell_dir: str) -> None:
        pid_file = os.path.join(cell_dir, SANDBOX_PID_FILE)
        pid = self._read_pid(pid_file)
        if pid and not self._is_pause(pid):
            pid = None
        if pid and _pid_alive(pid):
            try:
                os.kill(pid, signal.SIGTERM)  # kukepause exits immediately
            except ProcessLookupError:
                pass
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline and _pid_alive(pid):
                time.sleep(0.02)
            if _pid_alive(pid):
                try:
                    os.kill(pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
        try:
            os.unlink(pid_file)
        except FileNotFoundError:
            pass

    # --- workload wrapping --------------------------------------------------

    def _workload(self, ctx: ContainerContext) -> tuple[list[str], str | None]:
        spec = ctx.spec
        if ctx.sandbox_pid is None:
            raise FailedPrecondition(
                "namespace backend needs a cell sandbox before containers"
            )
        argv = [self.kukecell, "enter", "--sandbox", str(ctx.sandbox_pid)]
        rootfs = ctx.env.get("KUKEON_IMAGE_ROOTFS")
        if rootfs:
            # Per-container copy-on-write layer over the shared image rootfs.
            argv += ["--rootfs", rootfs,
                     "--overlay-dir", os.path.join(ctx.container_dir, "overlay")]
        if spec.host_network:
            argv += ["--host-net"]
        if spec.host_pid:
            argv += ["--host-pid"]
        if spec.privileged:
            argv += ["--privileged"]
        if spec.read_only_root_filesystem:
            argv += ["--readonly-root"]
        for cap in spec.capabilities:
            argv += ["--cap", cap]
        for dev in list(spec.devices) + list(ctx.devices):
            argv += ["--device", dev]
        for src, dst, ro in ctx.binds:
            argv += ["--bind", f"{src}:{dst}" + (":ro" if ro else "")]
        for dst in ctx.tmpfs:
            argv += ["--tmpfs", dst]
        if "seccomp=unconfined" in spec.security_opts:
            argv += ["--seccomp", "unconfined"]
        if spec.user:
            argv += ["--user", spec.user]
        # In-image (post-pivot) path: kukecell chdirs after the namespace
        # setup; the supervisor's host-side --cwd must stay unset.
        if ctx.workdir:
            argv += ["--workdir", ctx.workdir]
        argv += ["--"] + list(ctx.command)
        return argv, None
