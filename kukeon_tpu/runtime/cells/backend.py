"""Cell backend interface — the containerd seam.

Reference: internal/ctr/client.go:50-183 defines a Client interface wrapping
containerd; everything above it (runner/controller) is backend-agnostic and
unit-tested against fakes (SURVEY.md section 4). Same seam here:

- :class:`ProcessBackend` runs workloads as supervised host processes
  (kukeshim / kuketty native supervisors) — the in-sandbox / TPU-VM default;
  a containerd-gRPC backend can slot in behind the same interface.
- :class:`FakeBackend` is the in-memory test double.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field

from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.model import C_CREATED, C_EXITED, C_RUNNING


@dataclass
class ContainerContext:
    """Everything a backend needs to run one container."""

    container_dir: str                    # metadata dir (logs, pidfiles, tty)
    spec: t.ContainerSpec = field(default_factory=t.ContainerSpec)
    env: dict[str, str] = field(default_factory=dict)
    command: list[str] = field(default_factory=list)
    cgroup_dir: str | None = None
    workdir: str | None = None
    # Isolation inputs (namespace backend; ignored by the process backend):
    sandbox_pid: int | None = None        # cell sandbox to join
    devices: list[str] = field(default_factory=list)   # granted /dev nodes
    binds: list[tuple[str, str, bool]] = field(default_factory=list)  # (src, dst, ro)
    tmpfs: list[str] = field(default_factory=list)     # private scratch mounts


@dataclass
class ContainerState:
    state: str = C_CREATED                # created | running | exited
    pid: int | None = None
    exit_code: int | None = None

    @property
    def running(self) -> bool:
        return self.state == C_RUNNING

    @property
    def exited(self) -> bool:
        return self.state == C_EXITED


class CellBackend(abc.ABC):
    #: True when containers run inside per-cell namespaces (the namespace
    #: backend); the runner then provisions sandboxes and real binds.
    isolated = False

    @abc.abstractmethod
    def start_container(self, ctx: ContainerContext) -> int:
        """Start (or restart) the workload; returns supervisor/workload pid."""

    @abc.abstractmethod
    def signal_container(self, ctx: ContainerContext, sig: int) -> None:
        """Deliver a signal to the workload (via its supervisor)."""

    @abc.abstractmethod
    def container_state(self, ctx: ContainerContext) -> ContainerState:
        """Observe live state (survives daemon restarts)."""

    @abc.abstractmethod
    def cleanup_container(self, ctx: ContainerContext) -> None:
        """Remove runtime droppings after the workload is gone."""

    # --- cell sandbox (namespace set shared by the cell's containers) ------
    # Reference analog: the root (pause) container every cell gets
    # (runner/provision.go:1346, kukepause as PID 1). Backends without
    # real isolation keep these as no-ops.

    def ensure_sandbox(self, cell_dir: str, hostname: str) -> int | None:
        return None

    def sandbox_pid(self, cell_dir: str) -> int | None:
        return None

    def teardown_sandbox(self, cell_dir: str) -> None:
        return None
