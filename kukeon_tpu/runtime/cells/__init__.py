from kukeon_tpu.runtime.cells.backend import (  # noqa: F401
    CellBackend,
    ContainerContext,
    ContainerState,
)
from kukeon_tpu.runtime.cells.fake import FakeBackend  # noqa: F401
from kukeon_tpu.runtime.cells.process import ProcessBackend  # noqa: F401
