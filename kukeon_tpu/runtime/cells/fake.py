"""In-memory fake backend for controller/runner unit tests.

The reference tests its runner against scenario-scoped fake ctr.Clients
(stopKillFakeClient etc., SURVEY.md section 4); this single configurable
fake covers the same ground: scripted exits, start failures, signal log.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kukeon_tpu.runtime.cells.backend import (
    CellBackend,
    ContainerContext,
    ContainerState,
)
from kukeon_tpu.runtime.errors import Unavailable
from kukeon_tpu.runtime.model import C_CREATED, C_EXITED, C_RUNNING


@dataclass
class _Entry:
    state: str = C_CREATED
    pid: int = 0
    exit_code: int | None = None
    starts: int = 0
    signals: list[int] = field(default_factory=list)


class FakeBackend(CellBackend):
    def __init__(self):
        self.entries: dict[str, _Entry] = {}
        self.fail_start: set[str] = set()        # container dirs that fail to start
        self.auto_exit: dict[str, int] = {}      # dir -> exit code right after start
        self.started: list[ContainerContext] = []   # every start, in order
        self._next_pid = 1000

    def entry(self, ctx: ContainerContext) -> _Entry:
        return self.entries.setdefault(ctx.container_dir, _Entry())

    # --- CellBackend -------------------------------------------------------

    def start_container(self, ctx: ContainerContext) -> int:
        if ctx.container_dir in self.fail_start:
            raise Unavailable(f"fake: start failure for {ctx.container_dir}")
        e = self.entry(ctx)
        e.starts += 1
        self.started.append(ctx)
        self._next_pid += 1
        e.pid = self._next_pid
        if ctx.container_dir in self.auto_exit:
            e.state = C_EXITED
            e.exit_code = self.auto_exit[ctx.container_dir]
        else:
            e.state = C_RUNNING
            e.exit_code = None
        return e.pid

    def signal_container(self, ctx: ContainerContext, sig: int) -> None:
        e = self.entry(ctx)
        e.signals.append(sig)
        if e.state == C_RUNNING:
            e.state = C_EXITED
            e.exit_code = 128 + sig

    def container_state(self, ctx: ContainerContext) -> ContainerState:
        e = self.entry(ctx)
        return ContainerState(e.state, pid=e.pid or None, exit_code=e.exit_code)

    def cleanup_container(self, ctx: ContainerContext) -> None:
        self.entries.pop(ctx.container_dir, None)

    # --- test helpers ------------------------------------------------------

    def exit(self, ctx_dir: str, code: int) -> None:
        e = self.entries.setdefault(ctx_dir, _Entry())
        e.state = C_EXITED
        e.exit_code = code
