"""Process-backed cells: workloads supervised by the native shim binaries.

Each container is a host process owned by a native supervisor that survives
daemon restarts (the containerd-shim analog):

- non-attachable -> ``kukeshim``: logs to container.log, exit code to the
  exit file (the reference's cio.LogFile path, ctr/attachable.go:60-75);
- attachable -> ``kuketty``: PTY + attach socket + capture transcript (the
  reference's kuketty path).

State is derived purely from on-disk artifacts (pidfile + exit file +
/proc), so a restarted daemon re-derives truth the way the reference
re-derives from containerd (SURVEY.md section 5.3).
"""

from __future__ import annotations

import os
import signal
import subprocess
import time

from kukeon_tpu.runtime import consts
from kukeon_tpu.runtime.cells.backend import (
    CellBackend,
    ContainerContext,
    ContainerState,
)
from kukeon_tpu.runtime import naming
from kukeon_tpu.runtime.errors import FailedPrecondition
from kukeon_tpu.runtime.model import C_CREATED, C_EXITED, C_RUNNING

BIN_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bin")
KUKESHIM = os.path.join(BIN_DIR, "kukeshim")
KUKETTY = os.path.join(BIN_DIR, "kuketty")

EXIT_FILE = "exit"
SHIM_PID_FILE = "shim.pid"


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


class ProcessBackend(CellBackend):
    def __init__(self, shim: str = KUKESHIM, tty: str = KUKETTY):
        self.shim = shim
        self.tty = tty

    # --- paths -------------------------------------------------------------

    @staticmethod
    def paths(ctx: ContainerContext) -> dict[str, str]:
        d = ctx.container_dir
        return {
            "log": os.path.join(d, consts.SHIM_LOG),
            "capture": os.path.join(d, consts.CAPTURE_FILE),
            "socket": os.path.join(d, consts.TTY_SOCKET),
            "pid": os.path.join(d, consts.PID_FILE),
            "shim_pid": os.path.join(d, SHIM_PID_FILE),
            "exit": os.path.join(d, EXIT_FILE),
        }

    # --- lifecycle ---------------------------------------------------------

    def start_container(self, ctx: ContainerContext) -> int:
        if not ctx.command:
            raise FailedPrecondition(
                "container has no command and its image (if any) has no "
                "entrypoint"
            )
        workload, cwd = self._workload(ctx)
        p = self.paths(ctx)
        os.makedirs(ctx.container_dir, exist_ok=True)
        # A fresh start invalidates previous run artifacts.
        for stale in (p["exit"], p["pid"]):
            try:
                os.unlink(stale)
            except FileNotFoundError:
                pass

        if ctx.spec.attachable:
            argv = [self.tty, "--socket", p["socket"], "--capture", p["capture"],
                    "--exit-file", p["exit"], "--pid-file", p["pid"]]
            if ctx.spec.tty:
                for stage in ctx.spec.tty.on_init:
                    argv += ["--stage", stage]
        else:
            argv = [self.shim, "--log", p["log"],
                    "--exit-file", p["exit"], "--pid-file", p["pid"]]
        if cwd:
            argv += ["--cwd", cwd]
        if ctx.cgroup_dir:
            argv += ["--cgroup", ctx.cgroup_dir]
        argv += ["--"] + workload

        env = dict(os.environ)
        env.update(ctx.env)
        proc = subprocess.Popen(
            argv,
            env=env,
            start_new_session=True,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            stdin=subprocess.DEVNULL,
        )
        with open(p["shim_pid"], "w") as f:
            f.write(str(proc.pid))
        # Don't hold the Popen: the supervisor outlives us by design. Hand it
        # to a reaper-friendly close (init reaps if we die; if we live, the
        # reconcile loop's poll() below collects it).
        self._spawned = getattr(self, "_spawned", {})
        self._spawned[proc.pid] = proc

        # Wait briefly for the workload pidfile so immediate status reads see
        # 'running' rather than a startup race.
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if os.path.exists(p["pid"]) or os.path.exists(p["exit"]):
                break
            if proc.poll() is not None and not os.path.exists(p["exit"]):
                break
            time.sleep(0.01)
        return proc.pid

    def signal_container(self, ctx: ContainerContext, sig: int) -> None:
        p = self.paths(ctx)
        shim_pid = self._read_pid(p["shim_pid"])
        workload_pid = self._read_pid(p["pid"])
        # Signal the supervisor (it forwards TERM/INT); for KILL, hit the
        # workload's process group directly — the supervisor then reports.
        if sig in (signal.SIGTERM, signal.SIGINT) and shim_pid and _pid_alive(shim_pid):
            os.kill(shim_pid, sig)
            return
        if workload_pid and _pid_alive(workload_pid):
            try:
                os.killpg(workload_pid, sig)
            except (ProcessLookupError, PermissionError):
                try:
                    os.kill(workload_pid, sig)
                except ProcessLookupError:
                    pass
        elif shim_pid and _pid_alive(shim_pid):
            os.kill(shim_pid, sig)

    def container_state(self, ctx: ContainerContext) -> ContainerState:
        p = self.paths(ctx)
        self._reap()
        if os.path.exists(p["exit"]):
            try:
                with open(p["exit"]) as f:
                    code = int(f.read().strip())
            except (OSError, ValueError):
                code = None
            return ContainerState(C_EXITED, exit_code=code)
        pid = self._read_pid(p["pid"])
        if pid and _pid_alive(pid):
            return ContainerState(C_RUNNING, pid=pid)
        shim_pid = self._read_pid(p["shim_pid"])
        if shim_pid and _pid_alive(shim_pid):
            # Supervisor up, workload pid not yet written: starting.
            return ContainerState(C_RUNNING, pid=shim_pid)
        if pid or shim_pid:
            # Ran before but no exit file (crash/SIGKILL of the supervisor).
            return ContainerState(C_EXITED, exit_code=None)
        return ContainerState(C_CREATED)

    def cleanup_container(self, ctx: ContainerContext) -> None:
        p = self.paths(ctx)
        for f in (p["socket"], p["pid"], p["shim_pid"], p["exit"]):
            try:
                os.unlink(f)
            except FileNotFoundError:
                pass

    # --- helpers -----------------------------------------------------------

    def _workload(self, ctx: ContainerContext) -> tuple[list[str], str | None]:
        """(workload argv, supervisor --cwd). Seam the namespace backend
        overrides to wrap the workload in `kukecell enter`."""
        return self._overlay_command(ctx), self._overlay_workdir(ctx)

    @staticmethod
    def _overlay_command(ctx: ContainerContext) -> list[str]:
        """Image-path overlay: absolute argv components that exist inside the
        image's rootfs resolve there; everything else resolves on the host.
        This is the process backend's analog of a mount namespace — a scratch
        image's /bin/app.sh runs via the host's /bin/sh, and workloads read
        their bundle files at their in-image paths."""
        rootfs = ctx.env.get("KUKEON_IMAGE_ROOTFS")
        if not rootfs:
            return ctx.command
        out = []
        for arg in ctx.command:
            if arg.startswith("/"):
                candidate = os.path.join(rootfs, arg.lstrip("/"))
                if os.path.exists(candidate):
                    out.append(candidate)
                    continue
            out.append(arg)
        return out

    @staticmethod
    def _overlay_workdir(ctx: ContainerContext) -> str | None:
        """For an image-backed container, an absolute workdir ALWAYS names an
        in-image path (OCI semantics): resolve it inside the rootfs, creating
        it on demand (builders commonly WORKDIR a dir no instruction made).
        Host-dir fallbacks are deliberately not attempted — /srv or /opt
        existing on the host must not shadow the image's own tree."""
        wd = ctx.workdir
        rootfs = ctx.env.get("KUKEON_IMAGE_ROOTFS")
        if not wd or not rootfs or not wd.startswith("/"):
            return wd
        # A tar-imported manifest can carry '..' components; clamp the
        # resolved path under the rootfs (same escape class as COPY dst).
        candidate = naming.resolve_under(rootfs, wd, "workdir")
        os.makedirs(candidate, exist_ok=True)
        return candidate

    def _reap(self) -> None:
        """Collect any finished supervisors we spawned (avoid zombies)."""
        for pid, proc in list(getattr(self, "_spawned", {}).items()):
            if proc.poll() is not None:
                del self._spawned[pid]

    @staticmethod
    def _read_pid(path: str) -> int | None:
        try:
            with open(path) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None
