"""On-disk metadata store: JSON files with locking under the run path.

Reference: internal/metadata (metadata.go:30-45, lock.go). Every resource's
desired spec + status persists as one JSON file; the daemon can die at any
point and the eager reconcile pass re-derives live state (metadata-first
design, SURVEY.md section 5.4).

Writes are atomic (tempfile + rename) and serialized by an fcntl lock file
per directory, so the daemon and in-process CLI clients can share the store.
"""

from __future__ import annotations

import contextlib
import fcntl
import json
import os
import tempfile
from typing import Any, Iterator


class MetadataStore:
    def __init__(self, root: str):
        self.root = os.path.abspath(root)

    # --- paths -------------------------------------------------------------

    def path(self, *parts: str) -> str:
        p = os.path.join(self.root, *parts)
        # Normalize and require the result to be root itself or inside it
        # (plain startswith would let "../kukeon-backup" match "/kukeon").
        ap = os.path.abspath(p)
        if ap != self.root and not ap.startswith(self.root + os.sep):
            raise ValueError(f"path escapes store root: {parts}")
        return p

    def ensure_dir(self, *parts: str, mode: int = 0o750) -> str:
        p = self.path(*parts)
        os.makedirs(p, mode=mode, exist_ok=True)
        return p

    # --- locking -----------------------------------------------------------

    @contextlib.contextmanager
    def lock(self, *parts: str) -> Iterator[None]:
        """Exclusive advisory lock scoped to a directory."""
        d = self.ensure_dir(*parts)
        lock_path = os.path.join(d, ".lock")
        fd = os.open(lock_path, os.O_CREAT | os.O_RDWR, 0o600)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    # --- JSON documents ----------------------------------------------------

    def write_json(self, doc: Any, *parts: str, mode: int = 0o640) -> str:
        p = self.path(*parts)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(p), prefix=".tmp-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, indent=2, sort_keys=True)
                f.write("\n")
            os.chmod(tmp, mode)
            os.replace(tmp, p)
        except BaseException:
            with contextlib.suppress(OSError):
                os.unlink(tmp)
            raise
        return p

    def read_json(self, *parts: str) -> Any:
        with open(self.path(*parts)) as f:
            return json.load(f)

    def read_json_or(self, default: Any, *parts: str) -> Any:
        try:
            return self.read_json(*parts)
        except FileNotFoundError:
            return default

    def exists(self, *parts: str) -> bool:
        return os.path.exists(self.path(*parts))

    def delete(self, *parts: str) -> bool:
        try:
            os.unlink(self.path(*parts))
            return True
        except FileNotFoundError:
            return False

    def delete_tree(self, *parts: str) -> bool:
        import shutil

        p = self.path(*parts)
        if not os.path.exists(p):
            return False
        shutil.rmtree(p)
        return True

    def list_dirs(self, *parts: str) -> list[str]:
        p = self.path(*parts)
        try:
            return sorted(
                d for d in os.listdir(p)
                if os.path.isdir(os.path.join(p, d)) and not d.startswith(".")
            )
        except FileNotFoundError:
            return []

    def list_files(self, *parts: str, suffix: str = ".json") -> list[str]:
        p = self.path(*parts)
        try:
            return sorted(
                f for f in os.listdir(p)
                if f.endswith(suffix) and not f.startswith(".")
            )
        except FileNotFoundError:
            return []
