"""Sentinel error vocabulary for the runtime (reference: internal/errdefs).

Typed exceptions that map 1:1 onto RPC error codes, so the daemon can send a
code over the wire and the client can re-raise the same type
(reference: internal/errdefs/errdefs.go + pkg/api/kukeonv1/errmap.go).
"""

from __future__ import annotations


class KukeonError(Exception):
    """Base class; ``code`` crosses the RPC boundary."""

    code = "internal"


class NotFound(KukeonError):
    code = "not_found"


class AlreadyExists(KukeonError):
    code = "already_exists"


class InvalidArgument(KukeonError):
    code = "invalid_argument"


class FailedPrecondition(KukeonError):
    code = "failed_precondition"


class Conflict(KukeonError):
    code = "conflict"


class Unavailable(KukeonError):
    code = "unavailable"


class PermissionDenied(KukeonError):
    code = "permission_denied"


class DiskPressure(FailedPrecondition):
    code = "disk_pressure"


class NotSupported(KukeonError):
    code = "not_supported"


_BY_CODE = {
    cls.code: cls
    for cls in (
        KukeonError, NotFound, AlreadyExists, InvalidArgument,
        FailedPrecondition, Conflict, Unavailable, PermissionDenied,
        DiskPressure, NotSupported,
    )
}


def from_code(code: str, message: str) -> KukeonError:
    """Rehydrate a typed error from its wire code (client side)."""
    return _BY_CODE.get(code, KukeonError)(message)
