"""Llama-family decoder model, functional JAX.

This is the flagship model of the in-tree serving path (BASELINE.json north
star: Llama-3-8B agent serving on a v5e slice). Design points, TPU-first:

- **Pure functional**: params are a plain pytree dict; the forward is a pure
  function — trivially jittable, shardable, and checkpointable.
- **Stacked layers + ``lax.scan``**: all transformer blocks share one set of
  stacked weights ([L, ...] leading axis) and run under ``lax.scan``, so
  compile time and HLO size are O(1) in depth instead of O(L).
- **bf16 weights/activations, f32 softmax & norms**: keeps matmuls on the MXU
  while reductions stay numerically stable.
- **GQA + RoPE + SwiGLU**: Llama-3 architecture (also covers Llama-2 shapes).
- **Cache-aware**: the same ``forward`` covers prefill (no cache), cached
  prefill, and single-token decode; cache layout is [L, B, S, KV, D] so the
  scan carries per-layer cache slices.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from kukeon_tpu.ops.attention import gqa_attention
from kukeon_tpu.ops.norms import rms_norm
from kukeon_tpu.ops.rope import apply_rope

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    rope_theta: float = 500_000.0
    rms_norm_eps: float = 1e-5
    max_seq_len: int = 8192
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    # Route quantized decode matmuls through the Pallas int8 kernel
    # (ops/int8_matmul.py) instead of XLA's dequant-fused dot. Measured at
    # parity with XLA 0.9's fusion on v5e (both stream int8 at the HBM roof);
    # kept as an explicit switch so the kernel path stays exercised and the
    # win is guaranteed on XLA versions whose fusion regresses. Enable via
    # ServingEngine(int8_pallas=...) or directly; ignored for bf16 params.
    int8_pallas: bool = False

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    def param_count(self) -> int:
        embed = self.vocab_size * self.hidden_size
        attn = self.hidden_size * (self.q_dim + 2 * self.kv_dim) + self.q_dim * self.hidden_size
        mlp = 3 * self.hidden_size * self.intermediate_size
        norms = 2 * self.hidden_size
        head = 0 if self.tie_embeddings else embed
        return embed + self.num_layers * (attn + mlp + norms) + self.hidden_size + head


# --- Presets -----------------------------------------------------------------

def llama3_8b() -> LlamaConfig:
    return LlamaConfig()


def llama3_1b() -> LlamaConfig:
    """Llama-3.2-1B shapes — fits one v5e chip in bf16 with headroom."""
    return LlamaConfig(
        vocab_size=128256, hidden_size=2048, intermediate_size=8192,
        num_layers=16, num_heads=32, num_kv_heads=8, head_dim=64,
        tie_embeddings=True,
    )


def llama_tiny() -> LlamaConfig:
    """Test-size config: runs fast on a CPU mesh."""
    return LlamaConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=32,
        rope_theta=10_000.0, max_seq_len=256, dtype=jnp.float32,
        tie_embeddings=True,
    )


# --- Init --------------------------------------------------------------------

def init_params(key: jax.Array, cfg: LlamaConfig) -> Params:
    """Random-init a parameter pytree.

    Layout (stacked layers on axis 0):
      embed:   [V, H]
      layers:  attn_norm [L, H], wq [L, H, NH*D], wk/wv [L, H, KV*D],
               wo [L, NH*D, H], mlp_norm [L, H],
               w_gate/w_up [L, H, I], w_down [L, I, H]
      final_norm: [H]
      lm_head: [H, V] (absent when tie_embeddings)
    """
    c = cfg
    keys = iter(jax.random.split(key, 16))

    def dense(k, shape, fan_in):
        scale = fan_in ** -0.5
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(c.dtype)

    L, H, I, V = c.num_layers, c.hidden_size, c.intermediate_size, c.vocab_size
    params: Params = {
        "embed": dense(next(keys), (V, H), H),
        "layers": {
            "attn_norm": jnp.ones((L, H), c.dtype),
            "wq": dense(next(keys), (L, H, c.q_dim), H),
            "wk": dense(next(keys), (L, H, c.kv_dim), H),
            "wv": dense(next(keys), (L, H, c.kv_dim), H),
            "wo": dense(next(keys), (L, c.q_dim, H), c.q_dim),
            "mlp_norm": jnp.ones((L, H), c.dtype),
            "w_gate": dense(next(keys), (L, H, I), H),
            "w_up": dense(next(keys), (L, H, I), H),
            "w_down": dense(next(keys), (L, I, H), I),
        },
        "final_norm": jnp.ones((H,), c.dtype),
    }
    if not c.tie_embeddings:
        params["lm_head"] = dense(next(keys), (H, V), H)
    return params


# --- KV cache ----------------------------------------------------------------

@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    """Decode cache. k/v: [L, B, S_max, KV, D]; lengths: [B] used slots.

    Quantized form (``create(..., quantized=True)``): k/v are int8 with
    per-token per-kv-head symmetric scales k_scale/v_scale [L, B, S_max, KV]
    f32 — halves the cache's HBM bytes, the dominant decode stream once
    contexts grow (weights are already int8 in the flagship config). Dequant
    is fused into the decode attention dots (ops/attention.py
    decode_gqa_attention), so int8 is what actually crosses HBM.
    """

    k: jnp.ndarray
    v: jnp.ndarray
    lengths: jnp.ndarray
    k_scale: jnp.ndarray | None = None
    v_scale: jnp.ndarray | None = None

    @staticmethod
    def create(cfg: LlamaConfig, batch: int, max_len: int, dtype=None,
               quantized: bool = False) -> "KVCache":
        dtype = dtype or cfg.dtype
        shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
        if quantized:
            return KVCache(
                k=jnp.zeros(shape, jnp.int8),
                v=jnp.zeros(shape, jnp.int8),
                lengths=jnp.zeros((batch,), jnp.int32),
                k_scale=jnp.zeros(shape[:-1], jnp.float32),
                v_scale=jnp.zeros(shape[:-1], jnp.float32),
            )
        return KVCache(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            lengths=jnp.zeros((batch,), jnp.int32),
        )

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-token per-head symmetric int8 over the last (head_dim) axis:
    x ≈ q * s[..., None]. x: [..., D] -> (int8 [..., D], f32 [...])."""
    q, s = _int8_sym(x, -1)
    return q, jnp.squeeze(s, axis=-1)


def _cache_insert(cache_kv: jnp.ndarray, new_kv: jnp.ndarray, offsets: jnp.ndarray) -> jnp.ndarray:
    """Insert [B, S, ...] at per-batch ``offsets`` into [B, S_max, ...].

    Unrolled over the (small, static) batch: per-row dynamic_update_slice
    stays a real in-place slice write. A vmap'd DUS with per-row offsets
    lowers to a whole-tensor select — measured at several ms/step against a
    large cache — so the loop is the fast path, not a naive one.
    """
    B = cache_kv.shape[0]
    zeros = (0,) * (cache_kv.ndim - 2)
    for b in range(B):
        cache_kv = jax.lax.dynamic_update_slice(
            cache_kv, new_kv[b : b + 1], (b, offsets[b]) + zeros
        )
    return cache_kv


# --- int8 weight quantization ------------------------------------------------
#
# Per-output-channel symmetric int8: w ≈ q * s with q int8, s f32[out].
# Decode on TPU is HBM-bound (every weight byte streams once per step), so
# halving weight bytes ≈ doubles decode throughput; XLA fuses the
# convert(s8→bf16) into the dot, so int8 is what actually crosses HBM.
# 8B-class weights (~8 GB int8) fit a single 16 GB v5e chip.


def _int8_sym(w: jnp.ndarray, axis: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """THE device symmetric-int8 recipe: w ≈ q * s, s keepdims along ``axis``.

    Single source of truth for every on-device quantization (weights via
    :func:`quantize_params`, KV cache via :func:`quantize_kv`); the host copy
    is :func:`quantize_np` and must match exactly."""
    a = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis, keepdims=True)
    s = jnp.maximum(a / 127.0, 1e-12)
    q = jnp.round(w.astype(jnp.float32) / s).astype(jnp.int8)
    return q, s


def quantize_params(params: Params) -> Params:
    """bf16 param pytree -> int8 pytree ({"q": int8, "s": f32} leaves for
    every dense matrix; norms stay as-is). Works with forward/_decode_forward
    transparently via :func:`_mm` / :func:`_embed` / :func:`_logits`."""

    def q(w, axis):
        qw, s = _int8_sym(w, axis)
        return {"q": qw, "s": jnp.squeeze(s, axis=axis)}

    L = params["layers"]
    out: Params = {
        "embed": q(params["embed"], 1),                     # scale per vocab row
        "layers": {
            "attn_norm": L["attn_norm"],
            "wq": q(L["wq"], 1), "wk": q(L["wk"], 1), "wv": q(L["wv"], 1),
            "wo": q(L["wo"], 1),
            "mlp_norm": L["mlp_norm"],
            "w_gate": q(L["w_gate"], 1), "w_up": q(L["w_up"], 1),
            "w_down": q(L["w_down"], 1),
        },
        "final_norm": params["final_norm"],
    }
    if "lm_head" in params:
        out["lm_head"] = q(params["lm_head"], 0)            # scale per vocab col
    return out


def quantize_np(w, axis: int):
    """Per-output-channel symmetric int8 on the host (numpy): w ~= q * s.

    The single source of truth for the numpy quantization recipe — host
    loaders (hf_convert.load_params_quantized, init_quantized_params_host)
    must match :func:`quantize_params`'s device recipe exactly, or
    streamed-vs-quantized trees silently diverge.
    """
    import numpy as np

    w = np.asarray(w, np.float32)
    a = np.max(np.abs(w), axis=axis, keepdims=True)
    s = np.maximum(a / 127.0, 1e-12).astype(np.float32)
    q = np.round(w / s).astype(np.int8)
    return {"q": q, "s": np.squeeze(s, axis=axis)}


def init_quantized_params_host(cfg: LlamaConfig, seed: int = 0) -> Params:
    """Random-init DIRECTLY in int8 on the host, leaf by leaf.

    An 8B-class bf16 tree (~16 GB) cannot be materialized on one v5e chip
    just to be quantized; building {"q", "s"} leaves in numpy keeps peak
    memory at one leaf and ships only int8 + scales to the device."""
    import numpy as np

    c = cfg
    rng = np.random.default_rng(seed)
    L, H, I, V = c.num_layers, c.hidden_size, c.intermediate_size, c.vocab_size
    ndtype = np.dtype(c.dtype)   # norms must match the activation dtype

    def q(shape, fan_in, axis):
        w = rng.standard_normal(shape, np.float32) * (fan_in ** -0.5)
        return quantize_np(w, axis)

    params: Params = {
        "embed": q((V, H), H, 1),
        "layers": {
            "attn_norm": np.ones((L, H), ndtype),
            "wq": q((L, H, c.q_dim), H, 1),
            "wk": q((L, H, c.kv_dim), H, 1),
            "wv": q((L, H, c.kv_dim), H, 1),
            "wo": q((L, c.q_dim, H), c.q_dim, 1),
            "mlp_norm": np.ones((L, H), ndtype),
            "w_gate": q((L, H, I), H, 1),
            "w_up": q((L, H, I), H, 1),
            "w_down": q((L, I, H), I, 1),
        },
        "final_norm": np.ones((H,), ndtype),
    }
    if not c.tie_embeddings:
        params["lm_head"] = q((H, V), H, 0)
    return params


def _is_q(w) -> bool:
    return isinstance(w, dict) and "q" in w


def _mm(h: jnp.ndarray, w, pallas: bool = False) -> jnp.ndarray:
    """h @ w for plain or quantized weights (dequant fused into the dot).

    ``pallas=True`` routes int8 weights through the Pallas kernel (decode
    path); the kernel itself falls back to the XLA fused dot for odd shapes
    or large batches (prefill), so callers can pass the flag unconditionally.
    """
    if _is_q(w):
        if pallas:
            from kukeon_tpu.ops.int8_matmul import int8_matmul

            lead = h.shape[:-1]
            out = int8_matmul(h.reshape(-1, h.shape[-1]), w["q"], w["s"])
            return out.reshape(*lead, out.shape[-1])
        return (h @ w["q"].astype(h.dtype)) * w["s"].astype(h.dtype)
    return h @ w


def _embed(params: Params, tokens: jnp.ndarray, dtype) -> jnp.ndarray:
    e = params["embed"]
    if _is_q(e):
        rows = jnp.take(e["q"], tokens, axis=0).astype(dtype)
        return rows * jnp.take(e["s"], tokens, axis=0)[..., None].astype(dtype)
    return jnp.take(e, tokens, axis=0).astype(dtype)


def _logits(params: Params, c: LlamaConfig, x: jnp.ndarray,
            pallas: bool = False) -> jnp.ndarray:
    if c.tie_embeddings:
        e = params["embed"]
        if _is_q(e):
            if pallas:
                from kukeon_tpu.ops.int8_matmul import int8_matmul

                lead = x.shape[:-1]
                out = int8_matmul(
                    x.reshape(-1, x.shape[-1]), e["q"], e["s"], transpose=True
                )
                return out.reshape(*lead, out.shape[-1]).astype(jnp.float32)
            raw = jnp.einsum("bsh,vh->bsv", x, e["q"].astype(x.dtype))
            return (raw * e["s"].astype(x.dtype)).astype(jnp.float32)
        return jnp.einsum("bsh,vh->bsv", x, e).astype(jnp.float32)
    return _mm(x, params["lm_head"], pallas).astype(jnp.float32)


# --- Forward -----------------------------------------------------------------

def transformer_block(
    x: jnp.ndarray,
    w: dict,
    cfg: LlamaConfig,
    positions: jnp.ndarray,
    attn_impl: str = "auto",
) -> jnp.ndarray:
    """One no-cache decoder block (attention + SwiGLU residual) over
    [B, S, H]. Identical math to ``forward``'s cacheless layer step; exposed
    standalone for the pipeline-parallel path (parallel/pipeline.py), whose
    per-stage scan runs blocks outside forward's whole-model scan."""
    c = cfg
    B, S = x.shape[:2]
    h = rms_norm(x, w["attn_norm"], c.rms_norm_eps)
    q = _mm(h, w["wq"]).reshape(B, S, c.num_heads, c.head_dim)
    k = _mm(h, w["wk"]).reshape(B, S, c.num_kv_heads, c.head_dim)
    v = _mm(h, w["wv"]).reshape(B, S, c.num_kv_heads, c.head_dim)
    q = apply_rope(q, positions, c.rope_theta)
    k = apply_rope(k, positions, c.rope_theta)
    attn = gqa_attention(
        q, k, v, q_positions=positions, kv_positions=positions, impl=attn_impl
    )
    x = x + _mm(attn.reshape(B, S, c.q_dim), w["wo"])
    h = rms_norm(x, w["mlp_norm"], c.rms_norm_eps)
    gate = jax.nn.silu(_mm(h, w["w_gate"]).astype(jnp.float32)).astype(c.dtype)
    up = _mm(h, w["w_up"])
    return x + _mm(gate * up, w["w_down"])


def forward(
    params: Params,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,
    positions: jnp.ndarray,
    cache: KVCache | None = None,
    attn_impl: str = "auto",
    logit_positions: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, KVCache | None]:
    """Run the decoder.

    Args:
      params: pytree from :func:`init_params`.
      tokens: [B, S] int32 token ids.
      positions: [B, S] absolute positions of those tokens.
      cache: optional KVCache; when given, new K/V are written at each
        sequence's current length and attention runs against the cache.
        ``positions`` must equal ``cache.lengths[:, None] + arange(S)``.
      logit_positions: optional [B] int32 sequence indices; when given, the
        LM head runs at ONLY those positions and logits come back [B, 1, V].
        Prefill needs one next-token distribution, not S_bucket of them —
        at 8B shapes the full head is an S×H×128k matmul plus a [S, 128k]
        f32 tensor, bigger than the rest of the prefill combined.

    Returns:
      (logits [B, S, V] float32 — [B, 1, V] with ``logit_positions`` —
      and the updated cache or None).
    """
    c = cfg
    B, S = tokens.shape
    x = _embed(params, tokens, c.dtype)  # [B, S, H]

    # The fused decode path implements its own (reference-equivalent) masked
    # attention; honor an explicit request for a specific impl by falling
    # through to the generic path instead of silently ignoring it.
    # (logit_positions is moot at S == 1: there is only one position.)
    if cache is not None and S == 1 and attn_impl in ("auto", "reference"):
        return _decode_forward(params, c, x, positions, cache, B)

    offsets = cache.lengths if cache is not None else None

    def layer_step(x, layer):
        w, layer_cache = layer
        # Attention block.
        h = rms_norm(x, w["attn_norm"], c.rms_norm_eps)
        q = _mm(h, w["wq"]).reshape(B, S, c.num_heads, c.head_dim)
        k = _mm(h, w["wk"]).reshape(B, S, c.num_kv_heads, c.head_dim)
        v = _mm(h, w["wv"]).reshape(B, S, c.num_kv_heads, c.head_dim)
        q = apply_rope(q, positions, c.rope_theta)
        k = apply_rope(k, positions, c.rope_theta)

        if layer_cache is not None:
            ck, cv, cks, cvs = layer_cache
            if cks is not None:
                # Quantized cache, generic (multi-token) path: quantize the
                # new K/V in, then dequantize the whole layer cache for the
                # attention. Prefill is compute-bound, so the materialized
                # dequant is fine here; the HBM-bound decode path fuses it
                # (_decode_forward / decode_gqa_attention).
                qk, sk = quantize_kv(k)
                qv, sv = quantize_kv(v)
                ck = _cache_insert(ck, qk, offsets)
                cv = _cache_insert(cv, qv, offsets)
                cks = _cache_insert(cks, sk, offsets)
                cvs = _cache_insert(cvs, sv, offsets)
                # Dequantize in f32 and cast the PRODUCT down: scaling the
                # f32 scales to bf16 first would double-round, and the fused
                # decode path applies scales in f32 — the two paths must
                # agree numerically (ADVICE r4).
                ak = (ck.astype(jnp.float32)
                      * cks[..., None].astype(jnp.float32)).astype(c.dtype)
                av = (cv.astype(jnp.float32)
                      * cvs[..., None].astype(jnp.float32)).astype(c.dtype)
            else:
                ck = _cache_insert(ck, k, offsets)
                cv = _cache_insert(cv, v, offsets)
                ak, av = ck, cv
            kv_positions = jnp.broadcast_to(
                jnp.arange(ck.shape[1], dtype=jnp.int32)[None, :], (B, ck.shape[1])
            )
            kv_length = offsets + S
            attn = gqa_attention(
                q, ak, av,
                q_positions=positions, kv_positions=kv_positions,
                kv_length=kv_length, impl=attn_impl,
            )
            new_layer_cache = (ck, cv, cks, cvs)
        else:
            attn = gqa_attention(
                q, k, v,
                q_positions=positions, kv_positions=positions, impl=attn_impl,
            )
            new_layer_cache = None

        attn = _mm(attn.reshape(B, S, c.q_dim), w["wo"])
        x = x + attn

        # MLP block (SwiGLU).
        h = rms_norm(x, w["mlp_norm"], c.rms_norm_eps)
        gate = jax.nn.silu(_mm(h, w["w_gate"]).astype(jnp.float32)).astype(c.dtype)
        up = _mm(h, w["w_up"])
        x = x + _mm(gate * up, w["w_down"])
        return x, new_layer_cache

    layer_ws = params["layers"]
    if cache is not None:
        x, (new_k, new_v, new_ks, new_vs) = jax.lax.scan(
            lambda carry, layer: layer_step(carry, (layer[0], layer[1:])),
            x,
            (layer_ws, cache.k, cache.v, cache.k_scale, cache.v_scale),
        )
        new_cache = KVCache(k=new_k, v=new_v, lengths=cache.lengths + S,
                            k_scale=new_ks, v_scale=new_vs)
    else:
        x, _ = jax.lax.scan(
            lambda carry, w: layer_step(carry, (w, None)), x, layer_ws
        )
        new_cache = None

    x = rms_norm(x, params["final_norm"], c.rms_norm_eps)
    if logit_positions is not None:
        x = jnp.take_along_axis(x, logit_positions[:, None, None], axis=1)
    return _logits(params, c, x), new_cache


def _decode_forward(
    params: Params,
    c: LlamaConfig,
    x: jnp.ndarray,
    positions: jnp.ndarray,
    cache: KVCache,
    B: int,
) -> tuple[jnp.ndarray, KVCache]:
    """Single-token decode, HBM-optimal.

    The generic path writes each layer's K/V into the cache BEFORE attending
    and re-stacks the full cache as scan outputs — two whole-cache copies per
    step. Here the layer scan reads the cache as a read-only input
    (append-free attention scores the new token separately), emits only the
    tiny per-layer new K/V, and the cache is updated once per step with
    per-slot in-place slice writes. Cache bytes stream through HBM exactly
    once per step — and for a quantized cache those bytes are int8, with
    dequant fused into the attention dots.
    """
    from kukeon_tpu.ops.attention import decode_gqa_attention

    offsets = cache.lengths
    pl8 = c.int8_pallas

    def layer_step(x, layer):
        w, ck, cv, cks, cvs = layer
        h = rms_norm(x, w["attn_norm"], c.rms_norm_eps)
        q = _mm(h, w["wq"], pl8).reshape(B, 1, c.num_heads, c.head_dim)
        k = _mm(h, w["wk"], pl8).reshape(B, 1, c.num_kv_heads, c.head_dim)
        v = _mm(h, w["wv"], pl8).reshape(B, 1, c.num_kv_heads, c.head_dim)
        q = apply_rope(q, positions, c.rope_theta)
        k = apply_rope(k, positions, c.rope_theta)

        attn = decode_gqa_attention(q, k, v, ck, cv, offsets,
                                    k_scale=cks, v_scale=cvs)
        x = x + _mm(attn.reshape(B, 1, c.q_dim), w["wo"], pl8)

        h = rms_norm(x, w["mlp_norm"], c.rms_norm_eps)
        gate = jax.nn.silu(_mm(h, w["w_gate"], pl8).astype(jnp.float32)).astype(c.dtype)
        up = _mm(h, w["w_up"], pl8)
        x = x + _mm(gate * up, w["w_down"], pl8)
        return x, (k, v)

    x, (new_k, new_v) = jax.lax.scan(
        lambda carry, layer: layer_step(carry, layer),
        x,
        (params["layers"], cache.k, cache.v, cache.k_scale, cache.v_scale),
    )
    # new_k/new_v: [L, B, 1, KV, D] — one in-place slice write per slot
    # covering every layer at once (layers share the slot's offset).
    k_upd, v_upd = cache.k, cache.v
    ks_upd, vs_upd = cache.k_scale, cache.v_scale
    if cache.quantized:
        new_k, new_ks = quantize_kv(new_k)       # [L, B, 1, KV, D] / [L, B, 1, KV]
        new_v, new_vs = quantize_kv(new_v)
    for b in range(B):
        start = (0, b, offsets[b], 0, 0)
        k_upd = jax.lax.dynamic_update_slice(k_upd, new_k[:, b : b + 1], start)
        v_upd = jax.lax.dynamic_update_slice(v_upd, new_v[:, b : b + 1], start)
        if cache.quantized:
            ks_upd = jax.lax.dynamic_update_slice(
                ks_upd, new_ks[:, b : b + 1], start[:-1])
            vs_upd = jax.lax.dynamic_update_slice(
                vs_upd, new_vs[:, b : b + 1], start[:-1])
    new_cache = KVCache(k=k_upd, v=v_upd, lengths=cache.lengths + 1,
                        k_scale=ks_upd, v_scale=vs_upd)

    x = rms_norm(x, params["final_norm"], c.rms_norm_eps)
    return _logits(params, c, x, pl8), new_cache
