"""HuggingFace Llama checkpoint -> kukeon param pytree.

Real-weights serving (VERDICT r1 item 3): load `*.safetensors` shards (the
HF hub layout — single file or `model.safetensors.index.json` sharded) and
re-layout into :mod:`kukeon_tpu.models.llama`'s stacked-layers pytree.

Layout mapping (HF -> ours); HF Linear stores [out, in], our matmuls take
[in, out], so every dense transposes:

  model.embed_tokens.weight            [V, H]   -> embed [V, H]
  model.layers.N.input_layernorm       [H]      -> layers.attn_norm [L, H]
  model.layers.N.self_attn.{q,k,v,o}_proj       -> layers.w{q,k,v,o} (T)
  model.layers.N.post_attention_layernorm       -> layers.mlp_norm
  model.layers.N.mlp.{gate,up,down}_proj        -> layers.w_{gate,up,down} (T)
  model.norm.weight                    [H]      -> final_norm
  lm_head.weight                       [V, H]   -> lm_head [H, V] (T);
                                                   absent when tied

config.json (HF) carries the architecture hyperparams; :func:`config_from_hf`
maps them onto LlamaConfig so the caller never hand-syncs shapes.
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax.numpy as jnp
import numpy as np

from kukeon_tpu.models.llama import LlamaConfig, Params


def config_from_hf(checkpoint_dir: str) -> LlamaConfig:
    with open(os.path.join(checkpoint_dir, "config.json")) as f:
        hf = json.load(f)
    head_dim = hf.get("head_dim") or (
        hf["hidden_size"] // hf["num_attention_heads"]
    )
    return LlamaConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        num_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=head_dim,
        rope_theta=hf.get("rope_theta", 500_000.0),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
        max_seq_len=hf.get("max_position_embeddings", 8192),
        tie_embeddings=hf.get("tie_word_embeddings", False),
    )


def _open_shards(checkpoint_dir: str) -> dict[str, Any]:
    """tensor name -> (shard path). Single-file and index layouts."""
    index_path = os.path.join(checkpoint_dir, "model.safetensors.index.json")
    if os.path.exists(index_path):
        with open(index_path) as f:
            index = json.load(f)
        return {
            name: os.path.join(checkpoint_dir, shard)
            for name, shard in index["weight_map"].items()
        }
    single = os.path.join(checkpoint_dir, "model.safetensors")
    if not os.path.exists(single):
        cands = [f for f in os.listdir(checkpoint_dir)
                 if f.endswith(".safetensors")]
        if len(cands) != 1:
            raise FileNotFoundError(
                f"no model.safetensors[.index.json] in {checkpoint_dir}"
            )
        single = os.path.join(checkpoint_dir, cands[0])
    from safetensors import safe_open

    with safe_open(single, framework="numpy") as f:
        return {name: single for name in f.keys()}


def load_params(checkpoint_dir: str, cfg: LlamaConfig | None = None,
                dtype=jnp.bfloat16) -> tuple[Params, LlamaConfig]:
    """Load an HF Llama checkpoint directory into (params, cfg).

    Tensors stream shard-by-shard (never more than one shard resident
    beyond the assembled output), stacked along the layer axis.
    """
    import dataclasses

    from safetensors import safe_open

    cfg = cfg or config_from_hf(checkpoint_dir)
    cfg = dataclasses.replace(cfg, dtype=dtype)   # params and cfg must agree
    where = _open_shards(checkpoint_dir)

    # Group by shard so each file opens once.
    by_shard: dict[str, list[str]] = {}
    for name, shard in where.items():
        by_shard.setdefault(shard, []).append(name)

    raw: dict[str, np.ndarray] = {}
    for shard, names in by_shard.items():
        with safe_open(shard, framework="numpy") as f:
            for name in names:
                raw[name] = f.get_tensor(name)

    L = cfg.num_layers

    def stack(fmt: str, transpose: bool) -> jnp.ndarray:
        tensors = []
        for i in range(L):
            t = raw.pop(fmt.format(i))
            tensors.append(t.T if transpose else t)
        return jnp.asarray(np.stack(tensors), dtype)

    p = "model.layers.{}."
    params: Params = {
        "embed": jnp.asarray(raw.pop("model.embed_tokens.weight"), dtype),
        "layers": {
            "attn_norm": stack(p + "input_layernorm.weight", False),
            "wq": stack(p + "self_attn.q_proj.weight", True),
            "wk": stack(p + "self_attn.k_proj.weight", True),
            "wv": stack(p + "self_attn.v_proj.weight", True),
            "wo": stack(p + "self_attn.o_proj.weight", True),
            "mlp_norm": stack(p + "post_attention_layernorm.weight", False),
            "w_gate": stack(p + "mlp.gate_proj.weight", True),
            "w_up": stack(p + "mlp.up_proj.weight", True),
            "w_down": stack(p + "mlp.down_proj.weight", True),
        },
        "final_norm": jnp.asarray(raw.pop("model.norm.weight"), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = jnp.asarray(raw.pop("lm_head.weight").T, dtype)
    raw.pop("lm_head.weight", None)   # tied checkpoints may still ship it
    if raw:
        unexpected = sorted(raw)[:5]
        raise ValueError(f"unmapped tensors in checkpoint: {unexpected}")
    return params, cfg


# --- Mixtral (sparse MoE) -----------------------------------------------------

def moe_config_from_hf(checkpoint_dir: str):
    """config.json (MixtralForCausalLM layout) -> MoEConfig."""
    from kukeon_tpu.models.moe import MoEConfig

    with open(os.path.join(checkpoint_dir, "config.json")) as f:
        hf = json.load(f)
    head_dim = hf.get("head_dim") or (
        hf["hidden_size"] // hf["num_attention_heads"]
    )
    return MoEConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        num_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=head_dim,
        num_experts=hf.get("num_local_experts", 8),
        experts_per_token=hf.get("num_experts_per_tok", 2),
        rope_theta=hf.get("rope_theta", 1_000_000.0),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
        max_seq_len=hf.get("max_position_embeddings", 8192),
        tie_embeddings=hf.get("tie_word_embeddings", False),
    )


def load_moe_params(checkpoint_dir: str, cfg=None,
                    dtype=jnp.bfloat16):
    """HF Mixtral checkpoint -> (moe params, MoEConfig).

    Name mapping (HF Linear is [out, in]; our matmuls take [in, out]):

      model.layers.N.block_sparse_moe.gate.weight   [E, H] -> router [L, H, E]
      ...experts.E.w1.weight [I, H] -> w_gate [L, E, H, I]  (T per expert)
      ...experts.E.w3.weight [I, H] -> w_up   [L, E, H, I]
      ...experts.E.w2.weight [H, I] -> w_down [L, E, I, H]

    Attention / norms / embed map exactly as Llama (same trunk).
    """
    import dataclasses

    from safetensors import safe_open

    cfg = cfg or moe_config_from_hf(checkpoint_dir)
    cfg = dataclasses.replace(cfg, dtype=dtype)
    where = _open_shards(checkpoint_dir)

    by_shard: dict[str, list[str]] = {}
    for name, shard in where.items():
        by_shard.setdefault(shard, []).append(name)
    raw: dict[str, np.ndarray] = {}
    for shard, names in by_shard.items():
        with safe_open(shard, framework="numpy") as f:
            for name in names:
                raw[name] = f.get_tensor(name)

    L, E = cfg.num_layers, cfg.num_experts

    def stack(fmt: str, transpose: bool) -> jnp.ndarray:
        tensors = []
        for i in range(L):
            t = raw.pop(fmt.format(i))
            tensors.append(t.T if transpose else t)
        return jnp.asarray(np.stack(tensors), dtype)

    def stack_experts(w_name: str) -> jnp.ndarray:
        layers = []
        for i in range(L):
            experts = []
            for e in range(E):
                t = raw.pop(
                    f"model.layers.{i}.block_sparse_moe.experts.{e}.{w_name}.weight"
                )
                experts.append(t.T)
            layers.append(np.stack(experts))
        return jnp.asarray(np.stack(layers), dtype)

    p = "model.layers.{}."
    params = {
        "embed": jnp.asarray(raw.pop("model.embed_tokens.weight"), dtype),
        "layers": {
            "attn_norm": stack(p + "input_layernorm.weight", False),
            "wq": stack(p + "self_attn.q_proj.weight", True),
            "wk": stack(p + "self_attn.k_proj.weight", True),
            "wv": stack(p + "self_attn.v_proj.weight", True),
            "wo": stack(p + "self_attn.o_proj.weight", True),
            "mlp_norm": stack(p + "post_attention_layernorm.weight", False),
            # Router stays f32: routing decisions must not wobble with the
            # activation dtype (models/moe.py keeps it f32 at init too).
            "router": jnp.asarray(
                np.stack([
                    raw.pop(f"model.layers.{i}.block_sparse_moe.gate.weight").T
                    for i in range(L)
                ]), jnp.float32),
            "w_gate": stack_experts("w1"),
            "w_up": stack_experts("w3"),
            "w_down": stack_experts("w2"),
        },
        "final_norm": jnp.asarray(raw.pop("model.norm.weight"), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = jnp.asarray(raw.pop("lm_head.weight").T, dtype)
    raw.pop("lm_head.weight", None)
    if raw:
        raise ValueError(f"unmapped tensors in checkpoint: {sorted(raw)[:5]}")
    return params, cfg


# --- streaming int8 load ------------------------------------------------------

def load_params_quantized(checkpoint_dir: str,
                          cfg: LlamaConfig | None = None,
                          dtype=None) -> tuple[Params, LlamaConfig]:
    """Load an HF Llama checkpoint directly into the int8 pytree
    ({"q", "s"} leaves), streaming tensor-by-tensor on the host.

    An 8B-class bf16 tree (~16 GB) cannot be materialized on one 16 GB v5e
    chip just to be quantized — and materializing it in device memory before
    quantization would defeat the point. This path quantizes on the host,
    one tensor at a time (peak transient = one f32 tensor: ~230 MB for an
    8B layer matrix, ~2.1 GB for its embed/lm_head), and produces numpy
    leaves the caller ships to the device already-int8 (half the HBM
    footprint).

    ``dtype`` sets the activation/norm dtype (default: cfg's dtype, or
    bfloat16 when cfg comes from config.json). Returns numpy (host) leaves;
    pass through parallel.sharding.shard_params or ServingEngine to place
    on device.
    """
    import contextlib
    import dataclasses

    from safetensors import safe_open

    from kukeon_tpu.models.llama import quantize_np

    if cfg is None:
        cfg = dataclasses.replace(config_from_hf(checkpoint_dir),
                                  dtype=dtype or jnp.bfloat16)
    elif dtype is not None:
        cfg = dataclasses.replace(cfg, dtype=dtype)
    where = _open_shards(checkpoint_dir)

    with contextlib.ExitStack() as stack:
        handles: dict[str, Any] = {}
        consumed: set[str] = set()

        def get(name: str) -> np.ndarray:
            shard = where[name]
            if shard not in handles:
                handles[shard] = stack.enter_context(
                    safe_open(shard, framework="numpy")
                )
            consumed.add(name)
            # f16/bf16 checkpoints load as their stored dtype; quantization
            # promotes to f32 per tensor.
            return handles[shard].get_tensor(name)

        L = cfg.num_layers
        ndtype = np.dtype(cfg.dtype)  # ml_dtypes registers bfloat16 with numpy

        def stack_q(fmt: str) -> dict[str, np.ndarray]:
            """Per-layer quantize (HF [out, in] -> ours [in, out]), stack."""
            qs, ss = [], []
            for i in range(L):
                leaf = quantize_np(get(fmt.format(i)).T, axis=0)
                qs.append(leaf["q"])
                ss.append(leaf["s"])
            return {"q": np.stack(qs), "s": np.stack(ss)}

        def stack_plain(fmt: str) -> np.ndarray:
            return np.stack([get(fmt.format(i)) for i in range(L)]).astype(ndtype)

        p = "model.layers.{}."
        params: Params = {
            "embed": quantize_np(get("model.embed_tokens.weight"), axis=1),
            "layers": {
                "attn_norm": stack_plain(p + "input_layernorm.weight"),
                "wq": stack_q(p + "self_attn.q_proj.weight"),
                "wk": stack_q(p + "self_attn.k_proj.weight"),
                "wv": stack_q(p + "self_attn.v_proj.weight"),
                "wo": stack_q(p + "self_attn.o_proj.weight"),
                "mlp_norm": stack_plain(p + "post_attention_layernorm.weight"),
                "w_gate": stack_q(p + "mlp.gate_proj.weight"),
                "w_up": stack_q(p + "mlp.up_proj.weight"),
                "w_down": stack_q(p + "mlp.down_proj.weight"),
            },
            "final_norm": get("model.norm.weight").astype(ndtype),
        }
        if not cfg.tie_embeddings:
            params["lm_head"] = quantize_np(get("lm_head.weight").T, axis=0)
        consumed.add("lm_head.weight")   # tied checkpoints may still ship it
        unmapped = sorted(set(where) - consumed)
        if unmapped:
            raise ValueError(f"unmapped tensors in checkpoint: {unmapped[:5]}")
    return params, cfg


# --- streamed (leaf-granular) HF loads ----------------------------------------

def _llama_hf_names(cfg: LlamaConfig) -> set[str]:
    """Every HF tensor name the Llama mapping consumes (the unmapped-tensor
    guard for the streaming loaders, checked from headers alone)."""
    names = {"model.embed_tokens.weight", "model.norm.weight"}
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        names |= {
            p + "input_layernorm.weight",
            p + "self_attn.q_proj.weight", p + "self_attn.k_proj.weight",
            p + "self_attn.v_proj.weight", p + "self_attn.o_proj.weight",
            p + "post_attention_layernorm.weight",
            p + "mlp.gate_proj.weight", p + "mlp.up_proj.weight",
            p + "mlp.down_proj.weight",
        }
    if not cfg.tie_embeddings:
        names.add("lm_head.weight")
    return names


def _check_mapped(where: dict[str, str], cfg: LlamaConfig) -> None:
    expected = _llama_hf_names(cfg)
    unmapped = sorted(set(where) - expected - {"lm_head.weight"})
    if unmapped:
        raise ValueError(f"unmapped tensors in checkpoint: {unmapped[:5]}")
    missing = sorted(expected - set(where))
    if missing:
        raise ValueError(f"missing tensors in checkpoint: {missing[:5]}")


def _shard_getter(where: dict[str, str]):
    """name -> tensor via per-thread shard handles (safetensors handles are
    not shared across the stream's reader threads)."""
    import threading

    from safetensors import safe_open

    tls = threading.local()

    def get(name: str) -> np.ndarray:
        handles = getattr(tls, "handles", None)
        if handles is None:
            handles = tls.handles = {}
        shard = where[name]
        f = handles.get(shard)
        if f is None:
            f = handles[shard] = safe_open(shard, framework="numpy")
        return f.get_tensor(name)

    return get


def stream_params(checkpoint_dir: str, cfg: LlamaConfig | None = None,
                  dtype=jnp.bfloat16, *, threads: int = 2,
                  buffer: int = 4):
    """Streaming twin of :func:`load_params`: a CheckpointStream whose
    abstract tree comes from cfg shapes alone, with one reader job per
    final pytree leaf (a stacked leaf's job reads its L per-layer tensors,
    transposes, stacks, and casts — leaf values identical to the
    materialized loader's)."""
    import dataclasses
    import time

    from kukeon_tpu.models import checkpoints as ck

    cfg = cfg or config_from_hf(checkpoint_dir)
    cfg = dataclasses.replace(cfg, dtype=dtype)
    where = _open_shards(checkpoint_dir)
    _check_mapped(where, cfg)
    get = _shard_getter(where)
    c = cfg
    L, H, V, I = c.num_layers, c.hidden_size, c.vocab_size, c.intermediate_size
    ndtype = np.dtype(cfg.dtype)

    def spec(*shape):
        return ck.TensorSpec(shape, ndtype)

    abstract = {
        "embed": spec(V, H),
        "layers": {
            "attn_norm": spec(L, H),
            "wq": spec(L, H, c.q_dim), "wk": spec(L, H, c.kv_dim),
            "wv": spec(L, H, c.kv_dim), "wo": spec(L, c.q_dim, H),
            "mlp_norm": spec(L, H),
            "w_gate": spec(L, H, I), "w_up": spec(L, H, I),
            "w_down": spec(L, I, H),
        },
        "final_norm": spec(H),
    }
    if not cfg.tie_embeddings:
        abstract["lm_head"] = spec(H, V)

    def single_job(path, name, transpose=False):
        def job():
            t, disk_s = ck._timed_get(lambda: get(name))
            t0 = time.monotonic()
            out = np.asarray(t.T if transpose else t).astype(ndtype)
            return [(path, out)], disk_s, time.monotonic() - t0
        return job

    def stack_job(leaf, fmt, transpose):
        def job():
            disk_s, tensors = 0.0, []
            for i in range(L):
                t, dt = ck._timed_get(lambda i=i: get(fmt.format(i)))
                disk_s += dt
                tensors.append(t.T if transpose else t)
            t0 = time.monotonic()
            out = np.stack(tensors).astype(ndtype)
            return ([(("layers", leaf), out)], disk_s,
                    time.monotonic() - t0)
        return job

    p = "model.layers.{}."
    jobs = [
        single_job(("embed",), "model.embed_tokens.weight"),
        stack_job("attn_norm", p + "input_layernorm.weight", False),
        stack_job("wq", p + "self_attn.q_proj.weight", True),
        stack_job("wk", p + "self_attn.k_proj.weight", True),
        stack_job("wv", p + "self_attn.v_proj.weight", True),
        stack_job("wo", p + "self_attn.o_proj.weight", True),
        stack_job("mlp_norm", p + "post_attention_layernorm.weight", False),
        stack_job("w_gate", p + "mlp.gate_proj.weight", True),
        stack_job("w_up", p + "mlp.up_proj.weight", True),
        stack_job("w_down", p + "mlp.down_proj.weight", True),
        single_job(("final_norm",), "model.norm.weight"),
    ]
    if not cfg.tie_embeddings:
        jobs.append(single_job(("lm_head",), "lm_head.weight",
                               transpose=True))
    return ck.CheckpointStream(abstract, cfg, jobs,
                               threads=threads, buffer=buffer)


def stream_params_quantized(checkpoint_dir: str,
                            cfg: LlamaConfig | None = None,
                            dtype=None, *, threads: int = 2,
                            buffer: int = 4):
    """Streaming twin of :func:`load_params_quantized`: quantize-on-load,
    one reader job per final {"q","s"} (or norm) leaf. Peak transient host
    memory stays at ~one f32 leaf per reader thread."""
    import dataclasses
    import time

    from kukeon_tpu.models import checkpoints as ck
    from kukeon_tpu.models.llama import quantize_np

    if cfg is None:
        cfg = dataclasses.replace(config_from_hf(checkpoint_dir),
                                  dtype=dtype or jnp.bfloat16)
    elif dtype is not None:
        cfg = dataclasses.replace(cfg, dtype=dtype)
    where = _open_shards(checkpoint_dir)
    _check_mapped(where, cfg)
    get = _shard_getter(where)
    c = cfg
    L, H, V, I = c.num_layers, c.hidden_size, c.vocab_size, c.intermediate_size
    ndtype = np.dtype(cfg.dtype)

    def qspec(*shape):
        """{"q","s"} abstract pair: int8 matrix + f32 per-output-channel
        scale (the contracted axis squeezed out — sharding._quant_scale_spec
        reads these shapes)."""
        return {"q": ck.TensorSpec(shape, np.int8),
                "s": ck.TensorSpec(shape[:-2] + shape[-1:], np.float32)}

    abstract = {
        # embed quantizes along axis=1: s spans the vocab rows.
        "embed": {"q": ck.TensorSpec((V, H), np.int8),
                  "s": ck.TensorSpec((V,), np.float32)},
        "layers": {
            "attn_norm": ck.TensorSpec((L, H), ndtype),
            "wq": qspec(L, H, c.q_dim), "wk": qspec(L, H, c.kv_dim),
            "wv": qspec(L, H, c.kv_dim), "wo": qspec(L, c.q_dim, H),
            "mlp_norm": ck.TensorSpec((L, H), ndtype),
            "w_gate": qspec(L, H, I), "w_up": qspec(L, H, I),
            "w_down": qspec(L, I, H),
        },
        "final_norm": ck.TensorSpec((H,), ndtype),
    }
    if not cfg.tie_embeddings:
        abstract["lm_head"] = qspec(H, V)

    def quant_single_job(path, name, axis, transpose):
        def job():
            t, disk_s = ck._timed_get(lambda: get(name))
            t0 = time.monotonic()
            leaf = quantize_np(t.T if transpose else t, axis=axis)
            return ([(path + ("q",), leaf["q"]),
                     (path + ("s",), leaf["s"])],
                    disk_s, time.monotonic() - t0)
        return job

    def quant_stack_job(leaf_name, fmt):
        def job():
            disk_s = cast_s = 0.0
            qs, ss = [], []
            for i in range(L):
                t, dt = ck._timed_get(lambda i=i: get(fmt.format(i)))
                disk_s += dt
                t0 = time.monotonic()
                leaf = quantize_np(t.T, axis=0)
                cast_s += time.monotonic() - t0
                qs.append(leaf["q"])
                ss.append(leaf["s"])
            t0 = time.monotonic()
            q, s = np.stack(qs), np.stack(ss)
            cast_s += time.monotonic() - t0
            return ([(("layers", leaf_name, "q"), q),
                     (("layers", leaf_name, "s"), s)], disk_s, cast_s)
        return job

    def plain_stack_job(leaf_name, fmt):
        def job():
            disk_s, tensors = 0.0, []
            for i in range(L):
                t, dt = ck._timed_get(lambda i=i: get(fmt.format(i)))
                disk_s += dt
                tensors.append(t)
            t0 = time.monotonic()
            out = np.stack(tensors).astype(ndtype)
            return ([(("layers", leaf_name), out)], disk_s,
                    time.monotonic() - t0)
        return job

    def plain_single_job(path, name):
        def job():
            t, disk_s = ck._timed_get(lambda: get(name))
            t0 = time.monotonic()
            out = t.astype(ndtype)
            return [(path, out)], disk_s, time.monotonic() - t0
        return job

    p = "model.layers.{}."
    jobs = [
        quant_single_job(("embed",), "model.embed_tokens.weight",
                         axis=1, transpose=False),
        plain_stack_job("attn_norm", p + "input_layernorm.weight"),
        quant_stack_job("wq", p + "self_attn.q_proj.weight"),
        quant_stack_job("wk", p + "self_attn.k_proj.weight"),
        quant_stack_job("wv", p + "self_attn.v_proj.weight"),
        quant_stack_job("wo", p + "self_attn.o_proj.weight"),
        plain_stack_job("mlp_norm", p + "post_attention_layernorm.weight"),
        quant_stack_job("w_gate", p + "mlp.gate_proj.weight"),
        quant_stack_job("w_up", p + "mlp.up_proj.weight"),
        quant_stack_job("w_down", p + "mlp.down_proj.weight"),
        plain_single_job(("final_norm",), "model.norm.weight"),
    ]
    if not cfg.tie_embeddings:
        jobs.append(quant_single_job(("lm_head",), "lm_head.weight",
                                     axis=0, transpose=True))
    return ck.CheckpointStream(abstract, cfg, jobs,
                               threads=threads, buffer=buffer)
