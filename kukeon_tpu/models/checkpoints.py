"""Checkpoint tooling: synthesize HF-layout checkpoints and save/load the
kukeon int8 quantized format.

Two jobs, both in service of the flagship bench (BASELINE north star:
Llama-3-8B serving on v5e):

1. **Synthesis** — this environment has no network egress, so "load a real
   8B checkpoint" is exercised against a synthesized one: the exact HF hub
   layout (config.json + sharded ``model-*.safetensors`` +
   ``model.safetensors.index.json`` + tokenizer.json) with random weights at
   the real shapes/dtypes. Every byte of the serving path — shard streaming,
   name mapping, transposes, tokenizer.json loading — is the code a real
   download would hit (reference test strategy: fakes with real protocol,
   SURVEY.md §4).

2. **Quantized format** — cold-start (<90s target) cannot afford
   re-quantizing 16 GB of bf16 on every model-cell boot. ``save_quantized``
   persists the int8 {"q","s"} pytree as safetensors (~½ the bytes, zero
   quantization work at load); ``load_quantized`` streams it back as numpy
   leaves ready for device_put. ``kukeon_quant.json`` carries the
   LlamaConfig so the server never hand-syncs shapes.
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import struct
import threading
import time
from typing import Any, Callable, Iterator

import numpy as np

from kukeon_tpu import faults
from kukeon_tpu.models.llama import LlamaConfig

QUANT_MANIFEST = "kukeon_quant.json"

_CFG_FIELDS = (
    "vocab_size", "hidden_size", "intermediate_size", "num_layers",
    "num_heads", "num_kv_heads", "head_dim", "rope_theta", "rms_norm_eps",
    "max_seq_len", "tie_embeddings",
)


def _cfg_to_json(cfg: LlamaConfig) -> dict:
    return {f: getattr(cfg, f) for f in _CFG_FIELDS}


def _cfg_from_json(d: dict) -> LlamaConfig:
    return LlamaConfig(**{f: d[f] for f in _CFG_FIELDS if f in d})


# --- HF-layout synthesis ------------------------------------------------------

def write_hf_config(path: str, cfg: LlamaConfig) -> None:
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump({
            "architectures": ["LlamaForCausalLM"],
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "intermediate_size": cfg.intermediate_size,
            "num_hidden_layers": cfg.num_layers,
            "num_attention_heads": cfg.num_heads,
            "num_key_value_heads": cfg.num_kv_heads,
            "head_dim": cfg.head_dim,
            "rope_theta": cfg.rope_theta,
            "rms_norm_eps": cfg.rms_norm_eps,
            "max_position_embeddings": cfg.max_seq_len,
            "tie_word_embeddings": cfg.tie_embeddings,
            "torch_dtype": "float16",
        }, f, indent=1)


def write_tokenizer_json(path: str) -> None:
    """A real (HF ``tokenizers``-format) byte-level BPE with Llama-3 special
    tokens — small trained vocab, but byte-complete so any text round-trips.
    Exercises the exact HFTokenizer path a downloaded tokenizer.json would."""
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

    tk = Tokenizer(models.BPE(unk_token=None))
    tk.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tk.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=2048,
        special_tokens=["<|begin_of_text|>", "<|end_of_text|>", "<|eot_id|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    corpus = [
        "def main(argv):\n    return run(argv)\n",
        "the quick brown fox jumps over the lazy dog",
        "kukeon serves agent sessions on tpu slices with scoped secrets",
        "import jax\nimport numpy as np\n",
    ] * 64
    tk.train_from_iterator(corpus, trainer)
    tk.save(os.path.join(path, "tokenizer.json"))


def synthesize_hf_checkpoint(
    path: str,
    cfg: LlamaConfig,
    *,
    seed: int = 0,
    dtype: Any = np.float16,
    max_shard_bytes: int = 4 << 30,
    tokenizer: bool = True,
) -> str:
    """Write a random-weights checkpoint at ``cfg``'s shapes in the HF hub
    layout (sharded safetensors + index + config.json [+ tokenizer.json]).

    Weights are streamed to shards one tensor at a time — an 8B checkpoint
    (~16 GB f16) never holds more than one tensor in memory. Idempotent:
    returns immediately if the directory already has an index/config.
    """
    from safetensors.numpy import save_file

    os.makedirs(path, exist_ok=True)
    if os.path.exists(os.path.join(path, "config.json")) and (
        os.path.exists(os.path.join(path, "model.safetensors.index.json"))
        or os.path.exists(os.path.join(path, "model.safetensors"))
    ):
        return path

    rng = np.random.default_rng(seed)
    c = cfg
    H, I, V = c.hidden_size, c.intermediate_size, c.vocab_size

    def tensor_specs():
        yield "model.embed_tokens.weight", (V, H), H
        for i in range(c.num_layers):
            p = f"model.layers.{i}."
            yield p + "input_layernorm.weight", (H,), None
            yield p + "self_attn.q_proj.weight", (c.q_dim, H), H
            yield p + "self_attn.k_proj.weight", (c.kv_dim, H), H
            yield p + "self_attn.v_proj.weight", (c.kv_dim, H), H
            yield p + "self_attn.o_proj.weight", (H, c.q_dim), c.q_dim
            yield p + "post_attention_layernorm.weight", (H,), None
            yield p + "mlp.gate_proj.weight", (I, H), H
            yield p + "mlp.up_proj.weight", (I, H), H
            yield p + "mlp.down_proj.weight", (H, I), I
        yield "model.norm.weight", (H,), None
        if not c.tie_embeddings:
            yield "lm_head.weight", (V, H), H

    def make(shape, fan_in):
        if fan_in is None:
            return np.ones(shape, dtype)          # norm scales
        w = rng.standard_normal(shape, np.float32)
        w *= fan_in ** -0.5
        return w.astype(dtype)

    weight_map: dict[str, str] = {}
    shard: dict[str, np.ndarray] = {}
    shard_bytes = 0
    shard_names: list[str] = []

    def flush():
        nonlocal shard, shard_bytes
        if not shard:
            return
        name = f"model-part-{len(shard_names):05d}.safetensors"
        save_file(shard, os.path.join(path, name))
        shard_names.append(name)
        for n in shard:
            weight_map[n] = name
        shard = {}
        shard_bytes = 0

    for name, shape, fan_in in tensor_specs():
        t = make(shape, fan_in)
        if shard_bytes + t.nbytes > max_shard_bytes:
            flush()
        shard[name] = t
        shard_bytes += t.nbytes
    flush()

    # Rename to the canonical HF n-of-m scheme now that m is known.
    total = len(shard_names)
    final_map: dict[str, str] = {}
    renames: dict[str, str] = {}
    for idx, name in enumerate(shard_names):
        final = f"model-{idx + 1:05d}-of-{total:05d}.safetensors"
        renames[name] = final
        os.rename(os.path.join(path, name), os.path.join(path, final))
    for n, shard_name in weight_map.items():
        final_map[n] = renames[shard_name]
    with open(os.path.join(path, "model.safetensors.index.json"), "w") as f:
        json.dump({"weight_map": final_map}, f)
    write_hf_config(path, cfg)
    if tokenizer:
        write_tokenizer_json(path)
    return path


# --- kukeon int8 quantized checkpoint ----------------------------------------

def _flatten_quant(params: dict) -> dict[str, np.ndarray]:
    flat: dict[str, np.ndarray] = {}

    def walk(prefix: str, node):
        if isinstance(node, dict):
            if "q" in node and "s" in node and len(node) == 2:
                flat[prefix + ".q"] = np.asarray(node["q"])
                flat[prefix + ".s"] = np.asarray(node["s"])
            else:
                for k, v in node.items():
                    walk(f"{prefix}.{k}" if prefix else k, v)
        else:
            flat[prefix] = np.asarray(node)

    walk("", params)
    return flat


def _unflatten_quant(flat: dict[str, np.ndarray]) -> dict:
    tree: dict = {}
    for name, t in flat.items():
        parts = name.split(".")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = t
    return tree


def save_quantized(path: str, params: dict, cfg: LlamaConfig) -> str:
    """Persist an int8 {"q","s"} pytree as safetensors + manifest."""
    from safetensors.numpy import save_file

    os.makedirs(path, exist_ok=True)
    flat = _flatten_quant(params)
    # ml_dtypes bfloat16 isn't a safetensors-numpy dtype; norms store as f32.
    flat = {
        k: (v.astype(np.float32) if v.dtype not in (np.dtype(np.int8),
                                                    np.dtype(np.float32),
                                                    np.dtype(np.float16)) else v)
        for k, v in flat.items()
    }
    save_file(flat, os.path.join(path, "model.quant.safetensors"))
    with open(os.path.join(path, QUANT_MANIFEST), "w") as f:
        json.dump({"format": "kukeon-int8-v1", "config": _cfg_to_json(cfg)}, f)
    return path


def is_quantized_checkpoint(path: str) -> bool:
    return os.path.exists(os.path.join(path, QUANT_MANIFEST))


def load_quantized(path: str, dtype=None) -> tuple[dict, LlamaConfig]:
    """Load the int8 pytree back (numpy leaves; norms cast to ``dtype`` or
    the config's activation dtype)."""
    import jax.numpy as jnp
    from safetensors import safe_open

    with open(os.path.join(path, QUANT_MANIFEST)) as f:
        manifest = json.load(f)
    if manifest.get("format") != "kukeon-int8-v1":
        raise ValueError(f"unknown quantized checkpoint format in {path}")
    cfg = _cfg_from_json(manifest["config"])
    if dtype is not None:
        cfg = dataclasses.replace(cfg, dtype=dtype)
    ndtype = np.dtype(cfg.dtype)
    flat: dict[str, np.ndarray] = {}
    with safe_open(os.path.join(path, "model.quant.safetensors"),
                   framework="numpy") as f:
        for name in f.keys():
            t = f.get_tensor(name)
            if t.dtype == np.float32 and not name.endswith(".s"):
                t = t.astype(ndtype)   # norm scales follow activation dtype
            flat[name] = t
    params = _unflatten_quant(flat)
    # jnp import kept above so callers on fresh processes pay it here, not
    # at first forward.
    del jnp
    return params, cfg


# --- streamed (tensor-granular) checkpoint pipeline ---------------------------

class CheckpointStreamError(RuntimeError):
    """A reader thread died mid-stream (I/O error, decode error, or the
    armed ``checkpoint.stream`` fault point). The consumer re-raises this
    so a boot can fail CLEAN — a half-loaded engine must never flip
    /readyz."""


class TensorSpec:
    """Shape+dtype stand-in for one param leaf, parsed from the checkpoint
    manifest before any tensor byte is read. Duck-types the subset of the
    array interface the sharding planner (``parallel.sharding``) and
    ``jax.ShapeDtypeStruct`` construction need — deliberately NOT a jax
    type, so building the abstract tree costs no device work."""

    __slots__ = ("shape", "dtype")

    def __init__(self, shape: tuple[int, ...], dtype) -> None:
        self.shape = tuple(int(d) for d in shape)
        self.dtype = np.dtype(dtype)

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def nbytes(self) -> int:
        n = self.dtype.itemsize
        for d in self.shape:
            n *= d
        return n

    def __repr__(self) -> str:
        return f"TensorSpec(shape={self.shape}, dtype={self.dtype})"


# safetensors header dtype strings -> numpy dtypes. BF16 resolves lazily
# (ml_dtypes registers it with numpy via the jax import chain).
_ST_DTYPES = {
    "F64": "float64", "F32": "float32", "F16": "float16", "BF16": "bfloat16",
    "I64": "int64", "I32": "int32", "I16": "int16", "I8": "int8",
    "U8": "uint8", "BOOL": "bool",
}


def read_safetensors_header(path: str) -> dict[str, TensorSpec]:
    """tensor name -> TensorSpec from a safetensors file's JSON header —
    the whole-checkpoint manifest for the cost of one small read (the
    8-byte length prefix plus the header itself; zero tensor bytes)."""
    with open(path, "rb") as f:
        (n,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(n))
    out: dict[str, TensorSpec] = {}
    for name, meta in header.items():
        if name == "__metadata__":
            continue
        out[name] = TensorSpec(tuple(meta["shape"]),
                               np.dtype(_ST_DTYPES[meta["dtype"]]))
    return out


def _walk_tree(node, prefix: tuple[str, ...] = ()) -> Iterator[tuple[tuple[str, ...], Any]]:
    """(path tuple, leaf) pairs of a nested-dict param tree (quantized
    {"q","s"} dicts are interior nodes here: their arrays are the leaves)."""
    if isinstance(node, dict):
        for k in node:
            yield from _walk_tree(node[k], prefix + (k,))
    else:
        yield prefix, node


class CheckpointStream:
    """Bounded-buffer tensor-granular checkpoint reader.

    ``jobs`` is a list of zero-arg callables, each returning
    ``(leaves, disk_s, cast_s)`` where ``leaves`` is a list of
    ``(path tuple, np.ndarray)`` pairs ready for device_put. ``threads``
    reader threads drain the job list concurrently (tensor i+1's disk read
    overlaps tensor i's upload on the consumer side) and push results
    through a bounded queue, so host memory holds at most
    ``buffer + threads`` tensors no matter how far the disk runs ahead of
    the device link.

    The consumer iterates ``(path, array)`` pairs until every leaf of
    :attr:`abstract_params` arrived; a reader error (or the armed
    ``checkpoint.stream`` fault point) surfaces as
    :class:`CheckpointStreamError` on the consuming thread — fail-clean is
    the contract, never a silent half-tree.

    :attr:`stats` accumulates ``disk_s`` / ``cast_s`` / ``bytes`` /
    ``tensors`` under a lock; scrape it via :meth:`stat_snapshot`.
    """

    def __init__(self, abstract_params: dict, cfg, jobs: list[Callable],
                 *, threads: int = 4, buffer: int = 16):
        from kukeon_tpu import sanitize

        self.abstract_params = abstract_params
        self.cfg = cfg
        self.total_leaves = sum(1 for _ in _walk_tree(abstract_params))
        self._jobs = list(jobs)
        self._jobs_lock = sanitize.lock("CheckpointStream._jobs_lock")
        self._stats_lock = sanitize.lock("CheckpointStream._stats_lock")
        self.stats = {"disk_s": 0.0, "cast_s": 0.0,
                      "bytes": 0, "tensors": 0}        # guarded-by: _stats_lock
        self._q: queue.Queue = queue.Queue(maxsize=max(1, buffer))
        self._closed = sanitize.event("CheckpointStream._closed")
        self._threads = [
            threading.Thread(target=self._reader, daemon=True,
                             name=f"ckpt-stream-{i}")
            for i in range(max(1, min(threads, len(self._jobs) or 1)))
        ]
        for t in self._threads:
            t.start()

    # --- reader side --------------------------------------------------------

    def _reader(self) -> None:
        while not self._closed.is_set():
            with self._jobs_lock:
                if not self._jobs:
                    return
                job = self._jobs.pop(0)
            try:
                faults.maybe_fail("checkpoint.stream")
                leaves, disk_s, cast_s = job()
            except BaseException as e:  # noqa: BLE001 — surfaced to the consumer
                self._put(("err", CheckpointStreamError(
                    f"checkpoint stream reader failed: "
                    f"{type(e).__name__}: {e}"), e))
                return
            nbytes = sum(arr.nbytes for _, arr in leaves)
            with self._stats_lock:
                self.stats["disk_s"] += disk_s
                self.stats["cast_s"] += cast_s
                self.stats["bytes"] += nbytes
                self.stats["tensors"] += len(leaves)
            for path, arr in leaves:
                if not self._put(("leaf", path, arr)):
                    return

    def _put(self, item) -> bool:
        """Bounded put that gives up once the stream is closed (a consumer
        that errored out must not leave readers blocked forever)."""
        while not self._closed.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    # --- consumer side ------------------------------------------------------

    def __iter__(self) -> Iterator[tuple[tuple[str, ...], np.ndarray]]:
        remaining = self.total_leaves
        try:
            while remaining:
                item = self._q.get()
                if item[0] == "err":
                    raise item[1] from item[2]
                yield item[1], item[2]
                remaining -= 1
        finally:
            self.close()

    def close(self) -> None:
        """Stop the readers (idempotent). Iteration closes on completion
        and on error; an engine tearing down early must call this too."""
        self._closed.set()

    def stat_snapshot(self) -> dict:
        with self._stats_lock:
            return dict(self.stats)


def _timed_get(get: Callable[[], np.ndarray]) -> tuple[np.ndarray, float]:
    t0 = time.monotonic()
    out = get()
    return out, time.monotonic() - t0


def stream_quantized(path: str, dtype=None, *, threads: int = 4,
                     buffer: int = 16) -> CheckpointStream:
    """Streaming twin of :func:`load_quantized`: the abstract param tree
    and config come from the manifest + safetensors header alone (so
    ``precompile()`` can start before any tensor byte is read), then
    reader threads walk the file tensor-by-tensor, casting norms to the
    activation dtype on the host. Leaf values and tree structure are
    byte-identical to the materialized loader's."""
    with open(os.path.join(path, QUANT_MANIFEST)) as f:
        manifest = json.load(f)
    if manifest.get("format") != "kukeon-int8-v1":
        raise ValueError(f"unknown quantized checkpoint format in {path}")
    cfg = _cfg_from_json(manifest["config"])
    if dtype is not None:
        cfg = dataclasses.replace(cfg, dtype=dtype)
    ndtype = np.dtype(cfg.dtype)
    st_path = os.path.join(path, "model.quant.safetensors")
    header = read_safetensors_header(st_path)

    abstract_flat = {
        name: (TensorSpec(spec.shape, ndtype)
               if spec.dtype == np.dtype(np.float32)
               and not name.endswith(".s") else spec)
        for name, spec in header.items()
    }
    abstract = _unflatten_quant(abstract_flat)  # type: ignore[arg-type]

    from safetensors import safe_open

    tls = threading.local()

    def _handle():
        f = getattr(tls, "f", None)
        if f is None:
            f = tls.f = safe_open(st_path, framework="numpy")
        return f

    def make_job(name: str):
        want = abstract_flat[name].dtype

        def job():
            t, disk_s = _timed_get(lambda: _handle().get_tensor(name))
            t0 = time.monotonic()
            if t.dtype != want:
                t = t.astype(want)
            cast_s = time.monotonic() - t0
            return [(tuple(name.split(".")), t)], disk_s, cast_s

        return job

    jobs = [make_job(name) for name in header]
    return CheckpointStream(abstract, cfg, jobs,
                            threads=threads, buffer=buffer)
